"""Serving telemetry: latency reservoir + counters + profiler hooks.

Reference capability (SURVEY.md §5): observability in the reference is a
wall-clock ``print`` per job (reference worker.py:544,657-658) and stdout
breadcrumbs. Here a process-wide, thread-safe metrics object records
per-request latency and per-task counters, exposed via ``GET /metrics``
(serve/http_api.py), plus thin ``jax.profiler`` trace toggles for on-demand
TPU traces.
"""

from __future__ import annotations

import threading
import time
from collections import Counter, deque
from typing import Any, Dict, Optional


class Metrics:
    def __init__(self, reservoir: int = 2048):
        self._lock = threading.Lock()
        self._lat_ms: deque = deque(maxlen=reservoir)
        self._by_task: Counter = Counter()
        self._failures: Counter = Counter()
        self._started = time.time()

    def record(self, task_id: int, latency_ms: float) -> None:
        with self._lock:
            self._lat_ms.append(latency_ms)
            self._by_task[task_id] += 1

    def record_failure(self, task_id: Optional[int] = None) -> None:
        with self._lock:
            self._failures[task_id if task_id is not None else -1] += 1

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            lat = sorted(self._lat_ms)
            by_task = dict(self._by_task)
            failures = dict(self._failures)

        def pct(p: float) -> Optional[float]:
            if not lat:
                return None
            return round(lat[min(len(lat) - 1, int(p * len(lat)))], 3)

        return {
            "uptime_s": round(time.time() - self._started, 1),
            "requests": sum(by_task.values()),
            "by_task": {str(k): v for k, v in sorted(by_task.items())},
            "failures": {str(k): v for k, v in sorted(failures.items())},
            "latency_ms": {"p50": pct(0.50), "p90": pct(0.90),
                           "p99": pct(0.99), "n": len(lat)},
        }


def start_device_trace(log_dir: str) -> None:
    """Begin a jax.profiler trace (view in TensorBoard/XProf)."""
    import jax

    jax.profiler.start_trace(log_dir)


def stop_device_trace() -> None:
    import jax

    jax.profiler.stop_trace()
