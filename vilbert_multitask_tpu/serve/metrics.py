"""Serving telemetry: latency histogram + counters + profiler hooks.

Reference capability (SURVEY.md §5): observability in the reference is a
wall-clock ``print`` per job (reference worker.py:544,657-658) and stdout
breadcrumbs. Here a process-wide, thread-safe metrics object records
per-request latency and per-task counters, exposed via ``GET /metrics``
(serve/http_api.py), plus thin ``jax.profiler`` trace toggles for
on-demand TPU traces.

Latency storage and percentile math live in ``obs.instruments`` — the one
shared :class:`~vilbert_multitask_tpu.obs.instruments.Histogram` /
:func:`~vilbert_multitask_tpu.obs.instruments.percentile` implementation
(linear interpolation; the old nearest-rank ``int(p * len(lat))`` here was
upward-biased — p50 of two samples returned the max).
"""

from __future__ import annotations

import threading
import time
from collections import Counter
from typing import Any, Dict, Optional

from vilbert_multitask_tpu.obs.instruments import Histogram, percentile


class Metrics:
    def __init__(self, reservoir: int = 2048):
        self._lock = threading.Lock()
        # Standalone histogram (not in obs.REGISTRY): each Metrics instance
        # owns its samples, so tests composing several stacks don't share.
        self._lat = Histogram("request_latency_ms",
                              "End-to-end request latency (ms).",
                              labelnames=("task",), reservoir=reservoir)
        self._failures: Counter = Counter()
        # Failures as a (standalone) histogram too: the availability SLO
        # needs failures COUNTED OVER A SLIDING WINDOW, which the lifetime
        # Counter above cannot answer. Values are the task id; only
        # window_count matters.
        self._fail_hist = Histogram("request_failures",
                                    "Terminal request failures.",
                                    reservoir=reservoir)
        # Uptime is wall-clock by definition (reported across restarts,
        # compared against deploy timestamps) — not a duration measurement.
        self._started = time.time()

    def record(self, task_id: int, latency_ms: float, *,
               exemplar_trace_id: Optional[str] = None) -> None:
        # The exemplar links this sample's histogram bucket to its stored
        # trace (OpenMetrics exposition + SLO page payloads follow it).
        self._lat.observe(latency_ms, exemplar_trace_id=exemplar_trace_id,
                          task=str(task_id))

    def record_failure(self, task_id: Optional[int] = None) -> None:
        with self._lock:
            self._failures[task_id if task_id is not None else -1] += 1
        self._fail_hist.observe(float(task_id if task_id is not None else -1))

    @property
    def latency(self) -> Histogram:
        """The underlying histogram (Prometheus exposition reads buckets)."""
        return self._lat

    @property
    def failure_events(self) -> Histogram:
        """Windowed failure events (availability-SLO bad counter)."""
        return self._fail_hist

    def uptime_s(self) -> float:
        return time.time() - self._started  # vmtlint: disable=VMT109 — uptime is wall-clock, not a latency

    def snapshot(self) -> Dict[str, Any]:
        lat = sorted(self._lat.all_samples())
        by_task = {task: n for (task,), n in sorted(
            self._lat.series_counts().items(),
            key=lambda kv: int(kv[0][0]))}
        with self._lock:
            failures = dict(self._failures)

        def pct(p: float) -> Optional[float]:
            v = percentile(lat, p)
            return round(v, 3) if v is not None else None

        return {
            "uptime_s": round(self.uptime_s(), 1),
            "requests": sum(by_task.values()),
            "by_task": by_task,
            "failures": {str(k): v for k, v in sorted(failures.items())},
            "latency_ms": {"p50": pct(0.50), "p90": pct(0.90),
                           "p99": pct(0.99), "n": len(lat)},
        }


def start_device_trace(log_dir: str) -> None:
    """Begin a jax.profiler trace (view in TensorBoard/XProf)."""
    import jax

    jax.profiler.start_trace(log_dir)


def stop_device_trace() -> None:
    import jax

    jax.profiler.stop_trace()
