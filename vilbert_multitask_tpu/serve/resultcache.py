"""Durable result cache + in-flight coalescing (singleflight) registry.

At millions-of-users scale the 12-in-1 traffic is heavily duplicated —
the same viral image with the same question — yet every submit used to
pay a full TPU forward. This module makes duplicates ~free behind one
cache key (:func:`cache_key`): task id, content-stable image identities
(path + mtime_ns + size, the feature store's identity idiom), the
whitespace-canonicalized question text, and the serving config
fingerprint / model generation (so a rolling swap invalidates, never
serves stale).

Two tables live in the SAME WAL-sqlite file as the durable queue
(``serve/queue.py``), under the queue's ``BEGIN IMMEDIATE`` discipline,
so the txn tier declares them in ``TXN_SURFACE.json`` with their own
recovered state machine:

- ``result_cache`` — one row per key, ``state`` walking
  ``'leading' -> 'done'``. A ``'leading'`` row is the singleflight
  admit: exactly one submit per key wins leadership (publishes the one
  real job); concurrent identical submits attach as followers. A
  ``'done'`` row carries the written-through payload; hits skip the
  queue and TPU entirely.
- ``cache_followers`` — the keyed follower registry. Terminal frames
  fan out to every follower via the push hub;
  :meth:`ResultCache.pop_followers` is a destructive pop inside one
  write transaction so each follower is fanned exactly once
  (exactly-one-terminal per *submit*, not just per job).

Crash story: a leader that dies without reaching any worker terminal
leaves its ``'leading'`` row behind. The row carries ``created_at``; a
later identical submit past ``lease_s`` takes the lease over (same
``state='leading'`` write, recovered as the self-transition) and
republishes, inheriting the stranded followers — so no follower waits
on a corpse forever.
"""

from __future__ import annotations

import hashlib
import json
import os
import sqlite3
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple


def _image_identity(path: str) -> str:
    """Content-stable identity (features.store.file_identity idiom),
    best-effort: a path that cannot be stat'd (remote URI, dryrun
    placeholder) keys on the raw string — still correct, just blind to
    file replacement."""
    try:
        st = os.stat(path)
        return f"{path}:{st.st_mtime_ns}:{st.st_size}"
    except OSError:
        return path


def canonical_question(question: str) -> str:
    """Whitespace-canonical text: strip + collapse runs. Lowercasing is
    an upstream serving policy (ServingConfig.lowercase_questions) and
    happens before the key is derived, so both spellings of the policy
    cache consistently."""
    return " ".join(question.split())


def cache_key(task_id: "int | str", image_paths: Sequence[str],
              question: str, fingerprint: str) -> str:
    """The one cache key: (task, feature-content hash, canonicalized
    text, config_fingerprint/model_gen) — deterministic sha256 over the
    canonical JSON encoding."""
    canon = {
        "task": str(task_id),
        "images": [_image_identity(p) for p in image_paths],
        "question": canonical_question(question),
        "fingerprint": fingerprint,
    }
    raw = json.dumps(canon, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(raw.encode("utf-8")).hexdigest()


@dataclass
class Follower:
    """One coalesced submit waiting on the leader's terminal frame."""
    socket_id: str
    trace_id: Optional[str]
    tenant: Optional[str]
    attached_at: float


class ResultCache:
    """Durable result cache + singleflight follower registry.

    Lives next to the jobs table (same sqlite path as
    :class:`~vilbert_multitask_tpu.serve.queue.DurableQueue`) so cache
    state shares the queue's durability and its one-writer-at-a-time
    ``BEGIN IMMEDIATE`` discipline: every read-modify-write below takes
    the write lock before reading, which is what makes the
    exactly-one-leader claim and the exactly-once follower pop hold
    across worker threads and processes.
    """

    def __init__(self, path: str, *, fingerprint: str,
                 max_rows: int = 4096, ttl_s: float = 3600.0,
                 lease_s: float = 120.0):
        self.path = path
        self.fingerprint = fingerprint
        self.max_rows = max_rows
        self.ttl_s = ttl_s
        self.lease_s = lease_s
        if os.path.dirname(path):
            os.makedirs(os.path.dirname(path), exist_ok=True)
        with self._conn() as c:
            # One write transaction for the DDL, same rationale as the
            # queue's boot: two processes booting at once must not race
            # the CREATEs.
            c.execute("BEGIN IMMEDIATE")
            c.execute(
                """CREATE TABLE IF NOT EXISTS result_cache (
                    cache_key TEXT PRIMARY KEY,
                    state TEXT NOT NULL DEFAULT 'leading',
                    payload TEXT,
                    fingerprint TEXT NOT NULL,
                    leader_job_id INTEGER,
                    created_at REAL NOT NULL,
                    completed_at REAL,
                    hits INTEGER NOT NULL DEFAULT 0
                )"""
            )
            c.execute(
                """CREATE TABLE IF NOT EXISTS cache_followers (
                    id INTEGER PRIMARY KEY AUTOINCREMENT,
                    cache_key TEXT NOT NULL,
                    socket_id TEXT NOT NULL,
                    trace_id TEXT,
                    tenant TEXT,
                    attached_at REAL NOT NULL
                )"""
            )
            c.execute("CREATE INDEX IF NOT EXISTS cache_followers_key "
                      "ON cache_followers (cache_key, id)")

    def _conn(self) -> sqlite3.Connection:
        conn = sqlite3.connect(self.path, timeout=30.0)
        conn.execute("PRAGMA journal_mode=WAL")
        conn.execute("PRAGMA synchronous=NORMAL")
        return conn

    # ------------------------------------------------------------- submit path
    def admit(self, key: str, *, socket_id: str,
              trace_id: Optional[str] = None,
              tenant: Optional[str] = None,
              coalesce: bool = True) -> Tuple[str, Any]:
        """Resolve one submit against the cache, atomically.

        Returns one of:

        - ``("hit", payload)`` — a live ``'done'`` row; the caller
          pushes the cached result and never touches the queue;
        - ``("attach", leader_job_id)`` — an in-flight ``'leading'``
          row; this submit was registered as a follower and the caller
          must NOT publish (the leader's terminal fans out to it);
        - ``("lead", None)`` — this submit won the singleflight claim
          (fresh key, expired TTL, stale fingerprint, or lease takeover
          from a dead leader) and must publish the one real job, then
          :meth:`set_leader`.

        ``coalesce=False`` (ServingConfig.coalesce_enabled off) turns
        the attach branch into a plain lead: the duplicate publishes its
        own job, the shared ``'done'`` write-through stays last-wins.

        The whole decision is one ``BEGIN IMMEDIATE`` transaction: two
        identical concurrent submits serialize on the write lock, so
        exactly one leads and the other attaches.
        """
        now = time.time()
        with self._conn() as c:
            c.execute("BEGIN IMMEDIATE")
            row = c.execute(
                "SELECT state, payload, fingerprint, leader_job_id, "
                "created_at, completed_at FROM result_cache "
                "WHERE cache_key=?",
                (key,),
            ).fetchone()
            if row is not None:
                state, payload, fprint, leader_id, created_at, done_at = row
                # Persisted wall stamps, possibly another process's
                # clock (same rationale as queue.claim's sweep).
                stale = (
                    fprint != self.fingerprint
                    or (state == "done" and done_at is not None
                        and now - done_at > self.ttl_s)  # vmtlint: disable=VMT109
                )
                if stale:
                    c.execute("DELETE FROM result_cache WHERE cache_key=?",
                              (key,))
                    row = None
                elif state == "done":
                    c.execute(
                        "UPDATE result_cache SET hits=hits+1 "
                        "WHERE cache_key=?",
                        (key,),
                    )
                    return "hit", json.loads(payload)
                elif now - created_at > self.lease_s:  # vmtlint: disable=VMT109
                    # Dead-leader takeover: re-arm the lease and lead
                    # again; stranded followers stay attached and ride
                    # the new leader's terminal fan-out.
                    c.execute(
                        "UPDATE result_cache SET state='leading', "
                        "leader_job_id=NULL, created_at=? "
                        "WHERE cache_key=? AND state='leading'",
                        (now, key),
                    )
                    return "lead", None
                elif not coalesce:
                    return "lead", None
                else:
                    c.execute(
                        "INSERT INTO cache_followers "
                        "(cache_key, socket_id, trace_id, tenant, "
                        "attached_at) VALUES (?, ?, ?, ?, ?)",
                        (key, socket_id, trace_id, tenant, now),
                    )
                    return "attach", leader_id
            if row is None:
                c.execute(
                    "INSERT INTO result_cache "
                    "(cache_key, state, fingerprint, created_at) "
                    "VALUES (?, 'leading', ?, ?)",
                    (key, self.fingerprint, now),
                )
            return "lead", None

    def set_leader(self, key: str, job_id: int) -> None:
        """Stamp the published job id on the leading row — introspection
        ("which job is this key waiting on") and the attach branch's
        returned leader id."""
        with self._conn() as c:
            c.execute(
                "UPDATE result_cache SET leader_job_id=? "
                "WHERE cache_key=? AND state='leading'",
                (job_id, key),
            )

    # ----------------------------------------------------------- worker side
    def complete(self, key: str, payload: Dict[str, Any]) -> None:
        """Write-through at job completion: ``'leading' -> 'done'``.

        Guarded on the current state so a row invalidated mid-flight
        (rolling swap) is NOT resurrected with a stale-generation
        payload — the UPDATE simply matches nothing.
        """
        now = time.time()
        with self._conn() as c:
            c.execute("BEGIN IMMEDIATE")
            c.execute(
                "UPDATE result_cache SET state='done', payload=?, "
                "completed_at=? WHERE cache_key=? AND state='leading'",
                (json.dumps(payload), now, key),
            )
            # Capacity trim: evict oldest-completed rows beyond
            # max_rows, inside the same write transaction.
            c.execute(
                "DELETE FROM result_cache WHERE state='done' "
                "AND cache_key IN (SELECT cache_key FROM result_cache "
                "WHERE state='done' ORDER BY completed_at DESC "
                "LIMIT -1 OFFSET ?)",
                (self.max_rows,),
            )

    def abandon(self, key: str) -> None:
        """Leader reached a non-result terminal (dead-letter, deadline,
        drain without requeue): drop the singleflight claim so the next
        identical submit retries instead of attaching to a corpse."""
        with self._conn() as c:
            c.execute(
                "DELETE FROM result_cache "
                "WHERE cache_key=? AND state='leading'",
                (key,),
            )

    def pop_followers(self, key: str) -> List[Follower]:
        """Destructively take every follower for ``key`` — one write
        transaction, so with multiple workers racing a terminal each
        follower is returned to exactly one caller (the fan-out side of
        exactly-one-terminal per submit)."""
        with self._conn() as c:
            c.execute("BEGIN IMMEDIATE")
            rows = c.execute(
                "SELECT socket_id, trace_id, tenant, attached_at "
                "FROM cache_followers WHERE cache_key=? ORDER BY id",
                (key,),
            ).fetchall()
            if rows:
                c.execute("DELETE FROM cache_followers WHERE cache_key=?",
                          (key,))
        return [Follower(s, t, ten, at) for s, t, ten, at in rows]

    def peek_followers(self, key: str) -> List[Follower]:
        """Non-destructive read, for NON-terminal frames (requeued /
        failover notices): followers stay attached and still get the
        eventual terminal."""
        with self._conn() as c:
            rows = c.execute(
                "SELECT socket_id, trace_id, tenant, attached_at "
                "FROM cache_followers WHERE cache_key=? ORDER BY id",
                (key,),
            ).fetchall()
        return [Follower(s, t, ten, at) for s, t, ten, at in rows]

    # ---------------------------------------------------------- invalidation
    def invalidate(self, new_fingerprint: str) -> int:
        """Rolling swap landed: adopt the new fingerprint/model_gen and
        drop every row keyed to any other generation. Followers of
        in-flight leaders stay attached — they submitted against the old
        generation and still get its result; the row's deletion just
        stops the stale payload from being *cached*."""
        with self._conn() as c:
            c.execute("BEGIN IMMEDIATE")
            dropped = c.execute(
                "DELETE FROM result_cache WHERE fingerprint != ?",
                (new_fingerprint,),
            ).rowcount
        self.fingerprint = new_fingerprint
        return int(dropped)

    # ---------------------------------------------------------- introspection
    def stats(self) -> Dict[str, float]:
        """Sampler-shaped flat floats (rides /metrics via app._sample)."""
        with self._conn() as c:
            done, hits = c.execute(
                "SELECT COUNT(*), COALESCE(SUM(hits), 0) "
                "FROM result_cache WHERE state='done'",
            ).fetchone()
            leading = c.execute(
                "SELECT COUNT(*) FROM result_cache WHERE state='leading'",
            ).fetchone()[0]
            followers = c.execute(
                "SELECT COUNT(*) FROM cache_followers",
            ).fetchone()[0]
        return {
            "cache_done_rows": float(done),
            "cache_leading_rows": float(leading),
            "cache_followers": float(followers),
            "cache_stored_hits": float(hits),
        }
