"""Durable job queue (sqlite-backed), wire-compatible with the reference.

Reference capability: the RabbitMQ layer — producer ``vilbert_task``
(reference demo/sender.py:10-35: durable queue ``vilbert_multitask_queue``,
persistent JSON messages ``{image_path, question, socket_id, task_id}``) and
the worker's blocking consume + ack (worker.py:661-673,650).

Redesign, not translation: a broker daemon is replaced by an embedded
WAL-mode sqlite file, which keeps the reference's durability guarantees
(jobs survive process death; unacked jobs are redelivered) while fixing the
poison-message loop the reference has (worker.py:650-655 — a job that always
throws is redelivered forever): delivery attempts are counted and jobs move
to a dead-letter state after ``max_delivery_attempts``.
"""

from __future__ import annotations

import json
import os
import sqlite3
import time
from dataclasses import dataclass
from typing import Any, Dict, Optional, Sequence

from vilbert_multitask_tpu import obs
from vilbert_multitask_tpu.resilience.faults import fault_point


@dataclass
class Job:
    id: int
    body: Dict[str, Any]
    attempts: int
    deliveries: int = 0


class DurableQueue:
    """Embedded durable queue with at-least-once delivery + dead-lettering.

    Two independent poison bounds govern redelivery:

    - ``max_delivery_attempts`` counts *charged* attempts (claims minus
      releases) — the classic nack-toward-dead-letter path;
    - ``max_deliveries`` counts TOTAL claims, release or not. It exists
      because ``release()`` un-charges the attempt (graceful drain and
      replica failover are not the job's fault), which would otherwise
      reopen the reference's redeliver-forever loop for a job that crashes
      every replica it lands on: such jobs release, redeliver, and crash
      the next replica. After ``max_deliveries`` claims the job is
      quarantined as dead regardless of its attempt balance.
    """

    def __init__(self, path: str, *, queue_name: str = "vilbert_multitask_queue",
                 max_delivery_attempts: int = 3,
                 max_deliveries: int = 3,
                 visibility_timeout_s: float = 300.0):
        self.path = path
        self.queue_name = queue_name
        self.max_delivery_attempts = max_delivery_attempts
        self.max_deliveries = max_deliveries
        self.visibility_timeout_s = visibility_timeout_s
        if os.path.dirname(path):
            os.makedirs(os.path.dirname(path), exist_ok=True)
        with self._conn() as c:
            # One write transaction for create + index + migrations: DDL
            # autocommits per-statement under the implicit mode, so two
            # processes booting at once would race the PRAGMA-guarded
            # ALTERs below (the loser dies on "duplicate column").
            c.execute("BEGIN IMMEDIATE")
            c.execute(
                """CREATE TABLE IF NOT EXISTS jobs (
                    id INTEGER PRIMARY KEY AUTOINCREMENT,
                    queue TEXT NOT NULL,
                    body TEXT NOT NULL,
                    status TEXT NOT NULL DEFAULT 'pending',
                    attempts INTEGER NOT NULL DEFAULT 0,
                    claimed_at REAL,
                    created_at REAL NOT NULL
                )"""
            )
            c.execute("CREATE INDEX IF NOT EXISTS jobs_ready "
                      "ON jobs (queue, status, id)")
            # Schema migration for pre-existing queue files: CREATE TABLE IF
            # NOT EXISTS never adds columns, and serving state survives
            # restarts by design.
            cols = {r[1] for r in c.execute("PRAGMA table_info(jobs)")}
            if "delivery_count" not in cols:
                c.execute("ALTER TABLE jobs ADD COLUMN "
                          "delivery_count INTEGER NOT NULL DEFAULT 0")
            if "dead_notified" not in cols:
                # 0 until some consumer has pushed the terminal dead_letter
                # frame for this row; pop_dead_letters() flips it atomically
                # so exactly one consumer notifies the client.
                c.execute("ALTER TABLE jobs ADD COLUMN "
                          "dead_notified INTEGER NOT NULL DEFAULT 0")
            if "claimed_by" not in cols:
                # Which process incarnation (WorkerIdentity.ident,
                # host:pid:nonce) holds the in-flight claim — the queue-side
                # half of fleet observability: a stuck job names its holder.
                c.execute("ALTER TABLE jobs ADD COLUMN claimed_by TEXT")

    def _conn(self) -> sqlite3.Connection:
        conn = sqlite3.connect(self.path, timeout=30.0)
        conn.execute("PRAGMA journal_mode=WAL")
        conn.execute("PRAGMA synchronous=NORMAL")
        return conn

    # ---------------------------------------------------------------- producer
    def publish(self, body: Dict[str, Any]) -> int:
        """Persist one job (the reference's delivery_mode=2, sender.py:30-31)."""
        body = fault_point("queue.publish", body)
        with self._conn() as c:
            cur = c.execute(
                "INSERT INTO jobs (queue, body, created_at) VALUES (?, ?, ?)",
                (self.queue_name, json.dumps(body), time.time()),
            )
            return int(cur.lastrowid)

    # ---------------------------------------------------------------- consumer
    def claim(self, exclude: Sequence[int] = (),
              claimed_by: Optional[str] = None) -> Optional[Job]:
        """Atomically claim the oldest deliverable job (None if drained).

        ``exclude`` skips specific job ids for this call — the batch worker
        uses it so a failing job doesn't block or spin while its batchmates
        drain. ``claimed_by`` stamps the claimer's process identity on the
        row so introspection can name the holder of every in-flight job.

        Also sweeps expired in-flight claims back to pending — the embedded
        equivalent of a broker's visibility timeout, covering worker crashes
        between claim and ack (reference relies on connection-drop redelivery,
        worker.py:653-655).
        """
        fault_point("queue.claim")
        now = time.time()
        with self._conn() as c:
            c.execute("BEGIN IMMEDIATE")
            c.execute(
                "UPDATE jobs SET status='pending', claimed_at=NULL, "
                "claimed_by=NULL "
                "WHERE queue=? AND status='inflight' AND claimed_at < ?",
                # Deadline math on persisted wall-clock stamps: claimed_at is
                # written by (possibly) another process, so a monotonic clock
                # cannot be compared against it.
                (self.queue_name, now - self.visibility_timeout_s),  # vmtlint: disable=VMT109
            )
            # Jobs that crash the whole worker never reach nack(); without
            # this, a timed-out claim would redeliver them forever.
            c.execute(
                "UPDATE jobs SET status='dead', claimed_at=NULL "
                "WHERE queue=? AND status='pending' AND attempts >= ?",
                (self.queue_name, self.max_delivery_attempts),
            )
            # Poison quarantine on TOTAL deliveries: release() un-charges
            # the attempt, so a job that kills every replica it lands on
            # (failover → release → redeliver) never trips the attempts
            # bound above. delivery_count only ever increments.
            poisoned = c.execute(
                "UPDATE jobs SET status='dead', claimed_at=NULL "
                "WHERE queue=? AND status='pending' AND delivery_count >= ?",
                (self.queue_name, self.max_deliveries),
            ).rowcount
            exclude = list(exclude)
            not_in = (
                f" AND id NOT IN ({','.join('?' * len(exclude))})"
                if exclude else ""
            )
            row = c.execute(
                "SELECT id, body, attempts, delivery_count FROM jobs "
                f"WHERE queue=? AND status='pending'{not_in} "
                "ORDER BY id LIMIT 1",
                (self.queue_name, *exclude),
            ).fetchone()
            if row is None:
                if poisoned:
                    obs.POISON_COUNTER.inc(poisoned)
                return None
            job_id, body, attempts, deliveries = row
            c.execute(
                "UPDATE jobs SET status='inflight', attempts=attempts+1, "
                "delivery_count=delivery_count+1, claimed_at=?, "
                "claimed_by=? WHERE id=?",
                (now, claimed_by, job_id),
            )
        if poisoned:
            obs.POISON_COUNTER.inc(poisoned)
        return Job(id=job_id, body=json.loads(body), attempts=attempts + 1,
                   deliveries=deliveries + 1)

    def ack(self, job_id: int) -> None:
        """Success: remove the job (reference basic_ack, worker.py:650)."""
        with self._conn() as c:
            c.execute("DELETE FROM jobs WHERE id=?", (job_id,))

    def nack(self, job_id: int) -> str:
        """Failure: requeue, or dead-letter once attempts are exhausted.

        Returns the resulting status ('pending' or 'dead').
        """
        with self._conn() as c:
            # Take the write lock before reading `attempts`: under the
            # deferred default the SELECT is lock-free, so a concurrent
            # process could claim-and-charge this job between our read and
            # the dependent status write (lost update / SQLITE_BUSY
            # upgrade). Same discipline as claim()/pop_dead_letters().
            c.execute("BEGIN IMMEDIATE")
            row = c.execute(
                "SELECT attempts FROM jobs WHERE id=?", (job_id,)
            ).fetchone()
            if row is None:
                return "gone"
            status = ("dead" if row[0] >= self.max_delivery_attempts
                      else "pending")
            # An explicit nack's caller pushes the terminal frame itself
            # (worker._fail_job) — mark notified so pop_dead_letters()
            # never double-pushes for this row.
            c.execute(
                "UPDATE jobs SET status=?, claimed_at=NULL, claimed_by=NULL, "
                "dead_notified=? WHERE id=?",
                (status, 1 if status == "dead" else 0, job_id),
            )
        if status == "dead":
            # A poison job is poison however it dead-letters: the explicit
            # nack path must feed vmt_poison_jobs_total the same as the
            # claim-side sweep — the autoscaler's storm gate reads the
            # counter's windowed rate and must see BOTH paths.
            obs.POISON_COUNTER.inc()
        return status

    def release(self, job_id: int) -> None:
        """Un-claim without charging a delivery attempt, for consumers that
        claim a job and then decline to process it (load shedding, graceful
        shutdown with claims in hand). The batch worker's failure path uses
        ``claim(exclude=...)`` instead — release is for *unprocessed* jobs."""
        with self._conn() as c:
            c.execute(
                "UPDATE jobs SET status='pending', claimed_at=NULL, "
                "claimed_by=NULL, attempts=MAX(attempts-1, 0) "
                "WHERE id=? AND status='inflight'",
                (job_id,),
            )

    # ------------------------------------------------------------------ introspection
    def counts(self) -> Dict[str, int]:
        with self._conn() as c:
            rows = c.execute(
                "SELECT status, COUNT(*) FROM jobs WHERE queue=? "
                "GROUP BY status",
                (self.queue_name,),
            ).fetchall()
        return {status: n for status, n in rows}

    def inflight_claims(self) -> list[Dict[str, Any]]:
        """Who holds what: each in-flight job's id, holder identity, and
        claim age — the fleet-health answer to "is this job stuck, and on
        which process"."""
        with self._conn() as c:
            rows = c.execute(
                "SELECT id, claimed_by, claimed_at FROM jobs "
                "WHERE queue=? AND status='inflight' ORDER BY id",
                (self.queue_name,),
            ).fetchall()
        # Persisted wall stamps, possibly from another process (same
        # rationale as oldest_pending_age_s).
        now = time.time()
        return [{"id": i, "claimed_by": by,
                 "age_s": (round(max(0.0, now - at), 3)  # vmtlint: disable=VMT109
                           if at is not None else None)}
                for i, by, at in rows]

    def oldest_pending_age_s(self) -> Optional[float]:
        """Age of the oldest pending job (None when the queue is empty) —
        the admission controller's queue-age overload signal."""
        with self._conn() as c:
            row = c.execute(
                "SELECT MIN(created_at) FROM jobs "
                "WHERE queue=? AND status='pending'",
                (self.queue_name,),
            ).fetchone()
        if row is None or row[0] is None:
            return None
        # Age of a persisted wall-clock stamp (possibly written by another
        # process) — monotonic clocks cannot be compared cross-process.
        return max(0.0, time.time() - row[0])  # vmtlint: disable=VMT109

    def dead_jobs(self) -> list[Job]:
        with self._conn() as c:
            rows = c.execute(
                "SELECT id, body, attempts, delivery_count FROM jobs "
                "WHERE queue=? AND status='dead' ORDER BY id",
                (self.queue_name,),
            ).fetchall()
        return [Job(i, json.loads(b), a, d) for i, b, a, d in rows]

    def pop_dead_letters(self) -> list[Job]:
        """Atomically take the dead jobs nobody has told the client about.

        Claim-sweep dead-letters (worker crashed mid-job, or poison
        quarantine after ``max_deliveries``) happen inside ``claim()``
        where no caller holds the job body — so the terminal
        ``dead_letter`` push can't be sent at the kill site. Consumers
        call this after each claim; the notified flag flips inside one
        BEGIN IMMEDIATE transaction so exactly one consumer pushes each
        job's terminal frame (exactly-one-terminal survives multi-worker
        and multi-replica claim races).
        """
        with self._conn() as c:
            c.execute("BEGIN IMMEDIATE")
            rows = c.execute(
                "SELECT id, body, attempts, delivery_count FROM jobs "
                "WHERE queue=? AND status='dead' AND dead_notified=0 "
                "ORDER BY id",
                (self.queue_name,),
            ).fetchall()
            if rows:
                c.executemany(
                    "UPDATE jobs SET dead_notified=1 WHERE id=?",
                    [(r[0],) for r in rows],
                )
        return [Job(i, json.loads(b), a, d) for i, b, a, d in rows]


def make_job_message(image_paths, question: str, task_id: int,
                     socket_id: str, *,
                     collect_attention: "bool | str" = False,
                     trace_id: "str | None" = None,
                     deadline: "Dict[str, float] | None" = None,
                     published_unix: "float | None" = None,
                     tenant: "str | None" = None,
                     cache_key: "str | None" = None
                     ) -> Dict[str, Any]:
    """The reference wire schema (demo/sender.py:26-31): ``image_path`` is a
    list of absolute paths, ``question`` the (pre-lowercased) query.

    ``collect_attention`` extends the schema: the reference requests
    per-layer attention maps on every forward (worker.py:288,
    ``output_all_attention_masks=True``) but never surfaces them; here the
    maps are opt-in per job — truthy returns the [CLS]→regions summary in
    the result payload; the string ``"full"`` additionally persists every
    per-bridge per-head map, retrievable via ``/attention/<qa_id>`` and as
    a downloadable ``.npz``.
    """
    msg = {
        "image_path": list(image_paths),
        "question": question,
        "task_id": str(task_id),  # reference sends str; worker eval()s it
        "socket_id": socket_id,
    }
    if collect_attention:
        msg["collect_attention"] = collect_attention
    if trace_id:
        # Cross-thread span correlation: the worker re-enters this trace
        # (obs.trace_scope) so submit → claim → infer → push share one id.
        msg["trace_id"] = trace_id
    if deadline:
        # Deadline.to_wire(): the worker re-anchors the remaining budget to
        # its own monotonic clock and sheds expired jobs before dispatch.
        msg["deadline"] = deadline
    if published_unix is not None:
        # Wall-clock submit stamp (cross-process, so epoch not monotonic —
        # same rationale as Deadline.issued_unix): the worker's claim path
        # turns it into vmt_queue_wait_ms, the publish→claim delay that
        # intake-anchored e2e latency cannot see.
        msg["published_unix"] = published_unix
    if tenant:
        # Cost-attribution billing dimension (obs/attrib.py): who to
        # charge this job's device-seconds to. Absent means "anon" —
        # the attributor defaults it, so old producers stay valid.
        msg["tenant"] = tenant
    if cache_key:
        # Result-cache/singleflight key (serve/resultcache.py): this job
        # is the leader for the key — the worker writes the result
        # through at completion and fans every terminal frame out to the
        # key's coalesced followers. Absent means uncacheable (e.g.
        # attention-collecting submits) — terminals stay point-to-point.
        msg["cache_key"] = cache_key
    return msg
