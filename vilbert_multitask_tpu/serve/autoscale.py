"""Closed-loop autoscaler: SLO burn rates drive the replica count.

The sensors have existed since the obs tier landed (multi-window burn
rates in ``obs/slo.py``, the ``vmt_queue_wait_ms`` histogram, poison
quarantine counters, per-replica breakers) and the actuators since the
pool tier (``ReplicaPool.add_replica`` / ``retire_replica``) — this
module closes the loop. A flash crowd used to shed 429s until a human
added replicas; now a target-tracking controller does it in one
AOT-boot latency.

Control loop (rides the obs sampler tick — NO new threads, exactly like
``ReplicaPool.probe()``)::

    sensors                 policy                     actuators
    -------                 ------                     ---------
    queue-wait p95     ┐
    SLO burn (2 win)   ├──▶  hysteresis band     ──▶  pool.add_replica()
    breaker states     │     + sustain counters  ──▶  pool.retire_replica()
    poison/dead rate   ┘     + cooldowns

Policy shape:

* **Target tracking with hysteresis.** A tick is a *breach* when
  queue-wait p95 rises above ``target * band_high`` or the worst SLO
  burns over threshold on BOTH windows; a *slack* tick needs p95 below
  ``target * band_low`` AND burn under threshold. Between the bands the
  controller holds — the dead zone is what stops limit-cycling around
  the target.
* **Sustain + cooldown.** Scale-out needs ``breach_ticks`` consecutive
  breach ticks, scale-in ``slack_ticks`` consecutive slack ticks (the
  slow direction — capacity is cheap to keep for another window, and
  re-adding it costs a boot). Every action starts both cooldown clocks:
  another scale-out waits ``cooldown_out_s``, a scale-in
  ``cooldown_in_s`` — so freshly added capacity gets a chance to absorb
  the queue before the controller reads the resulting calm as slack.
* **Health gating.** A poison-job storm or a flapping replica breaker
  reads as "unhealthy, don't scale", never "overloaded, add replicas":
  scaling out would boot fresh replicas straight into the same
  poisoned intake. Any open breaker or a poison/dead-letter rate above
  ``max_poison_rate_per_s`` pins the controller to hold (both
  directions — retiring capacity mid-incident is no better).

Every decision is recorded: the ``vmt_autoscale_decisions_total``
counter labeled ``{action,reason}``, the ``vmt_pool_target_replicas``
gauge next to the pool's actual, an ``autoscale`` flight-recorder
trigger on actions and health-gated holds, and a bounded ring of full
decision records (inputs observed, thresholds, action, cooldown state)
served by ``GET /debug/autoscale``.

The policy itself is pure — :func:`decide` maps (policy, state, inputs,
now) to a decision record with no clocks, pool, or sockets — so
``tests/test_autoscale.py`` drives it with a fake clock and hand-built
inputs, no sleeps.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, TYPE_CHECKING

from vilbert_multitask_tpu import obs

if TYPE_CHECKING:  # pragma: no cover — import cycle guard, types only
    from vilbert_multitask_tpu.config import ServingConfig

ACTION_SCALE_OUT = "scale_out"
ACTION_SCALE_IN = "scale_in"
ACTION_HOLD = "hold"

DECISIONS = obs.REGISTRY.counter(
    "vmt_autoscale_decisions_total",
    "Autoscaler decisions by action and reason.",
    labelnames=("action", "reason"))
TARGET_REPLICAS = obs.REGISTRY.gauge(
    "vmt_pool_target_replicas",
    "Replica count the autoscaler is steering toward (compare with "
    "vmt_pool_ready_replicas: a gap is a scale event in progress).")


@dataclasses.dataclass(frozen=True)
class AutoscaleInputs:
    """One tick's sensor readings — everything :func:`decide` sees.

    ``queue_wait_p95_ms`` is None on an empty window (no claims — idle
    trough or cold start), which the policy reads as slack: no traffic
    needs no extra capacity.
    """

    queue_wait_p95_ms: Optional[float] = None
    burn_fast: float = 0.0
    burn_slow: float = 0.0
    ready_replicas: int = 1
    live_replicas: int = 1
    booting_replicas: int = 0
    open_breakers: int = 0
    poison_rate_per_s: float = 0.0
    queue_depth: int = 0
    can_add: bool = True


@dataclasses.dataclass
class ControllerState:
    """The controller's memory between ticks: sustain counters and the
    cooldown clock. Mutated only by :func:`decide`."""

    breach_ticks: int = 0
    slack_ticks: int = 0
    last_action_t: Optional[float] = None
    last_action: Optional[str] = None


class AutoscalePolicy:
    """The knob view: every ``autoscale_*`` ServingConfig field, read
    once at construction (the VMT122 audit tracks these reads)."""

    def __init__(self, serving: "ServingConfig"):
        self.enabled = bool(serving.autoscale_enabled)
        self.min_replicas = max(1, int(serving.autoscale_min_replicas))
        self.max_replicas = int(serving.autoscale_max_replicas)
        self.target_p95_ms = float(serving.autoscale_target_queue_wait_p95_ms)
        self.burn_threshold = float(serving.autoscale_burn_threshold)
        self.band_high = float(serving.autoscale_band_high)
        self.band_low = float(serving.autoscale_band_low)
        self.breach_ticks = max(1, int(serving.autoscale_breach_ticks))
        self.slack_ticks = max(1, int(serving.autoscale_slack_ticks))
        self.cooldown_out_s = float(serving.autoscale_cooldown_out_s)
        self.cooldown_in_s = float(serving.autoscale_cooldown_in_s)
        self.max_poison_rate = float(serving.autoscale_max_poison_rate_per_s)
        self.window_s = float(serving.autoscale_window_s)
        self.history = max(1, int(serving.autoscale_decision_history))

    def snapshot(self) -> Dict[str, Any]:
        return {
            "enabled": self.enabled,
            "min_replicas": self.min_replicas,
            "max_replicas": self.max_replicas,
            "target_queue_wait_p95_ms": self.target_p95_ms,
            "burn_threshold": self.burn_threshold,
            "band_high": self.band_high,
            "band_low": self.band_low,
            "breach_ticks": self.breach_ticks,
            "slack_ticks": self.slack_ticks,
            "cooldown_out_s": self.cooldown_out_s,
            "cooldown_in_s": self.cooldown_in_s,
            "max_poison_rate_per_s": self.max_poison_rate,
            "window_s": self.window_s,
        }


def classify(policy: AutoscalePolicy, inputs: AutoscaleInputs) -> str:
    """One tick's signal: ``breach`` / ``slack`` / ``in_band``.

    Burn must clear the threshold on BOTH windows to count as a breach
    (the same both-windows rule the pager uses: fast alone is a blip,
    slow alone is old news) — and must be calm on both to count toward
    slack.
    """
    burn = min(inputs.burn_fast, inputs.burn_slow)
    p95 = inputs.queue_wait_p95_ms
    if (p95 is not None and p95 > policy.target_p95_ms * policy.band_high) \
            or burn >= policy.burn_threshold:
        return "breach"
    if (p95 is None or p95 < policy.target_p95_ms * policy.band_low) \
            and burn < policy.burn_threshold:
        return "slack"
    return "in_band"


def _healthy(policy: AutoscalePolicy, inputs: AutoscaleInputs
             ) -> Optional[str]:
    """None when the pool looks healthy, else the gating reason."""
    if inputs.open_breakers > 0:
        return "breaker_open"
    if inputs.poison_rate_per_s >= policy.max_poison_rate:
        return "poison_storm"
    return None


def decide(policy: AutoscalePolicy, state: ControllerState,
           inputs: AutoscaleInputs, now: float) -> Dict[str, Any]:
    """The pure control step: classify, sustain, gate, act.

    Mutates ``state`` (sustain counters, cooldown stamp) and returns the
    full decision record — the exact dict the decision ring keeps and
    ``/debug/autoscale`` serves.
    """
    signal = classify(policy, inputs)
    if signal == "breach":
        state.breach_ticks += 1
        state.slack_ticks = 0
    elif signal == "slack":
        state.slack_ticks += 1
        state.breach_ticks = 0
    else:
        state.breach_ticks = 0
        state.slack_ticks = 0

    since_action = (None if state.last_action_t is None
                    else now - state.last_action_t)
    cool_out = (since_action is not None
                and since_action < policy.cooldown_out_s)
    cool_in = (since_action is not None
               and since_action < policy.cooldown_in_s)

    action, reason = ACTION_HOLD, "in_band"
    unhealthy = _healthy(policy, inputs)
    if state.breach_ticks >= policy.breach_ticks:
        if unhealthy is not None:
            # The load signal says "add capacity"; the health signal says
            # the capacity we have is being poisoned or is flapping.
            # Health wins: never scale into an incident.
            reason = unhealthy
        elif inputs.live_replicas >= policy.max_replicas:
            reason = "at_max"
        elif cool_out:
            reason = "cooldown_out"
        elif inputs.booting_replicas > 0:
            # A replica is already warming — adding another before the
            # first one lands is how controllers overshoot.
            reason = "boot_in_progress"
        elif not inputs.can_add:
            reason = "no_engine_factory"
        else:
            action, reason = ACTION_SCALE_OUT, "sustained_breach"
    elif state.slack_ticks >= policy.slack_ticks:
        if unhealthy is not None:
            reason = unhealthy
        elif inputs.live_replicas <= policy.min_replicas:
            reason = "at_min"
        elif cool_in:
            reason = "cooldown_in"
        else:
            action, reason = ACTION_SCALE_IN, "sustained_slack"
    elif signal == "breach":
        reason = "breach_building"
    elif signal == "slack":
        reason = "slack_building"

    if action != ACTION_HOLD:
        state.last_action_t = now
        state.last_action = action
        state.breach_ticks = 0
        state.slack_ticks = 0

    target = inputs.live_replicas
    if action == ACTION_SCALE_OUT:
        target += 1
    elif action == ACTION_SCALE_IN:
        target -= 1
    target = min(max(target, policy.min_replicas), policy.max_replicas)

    return {
        "t": round(now, 3),
        "action": action,
        "reason": reason,
        "signal": signal,
        "target_replicas": target,
        "inputs": dataclasses.asdict(inputs),
        "thresholds": {
            "target_p95_ms": policy.target_p95_ms,
            "breach_above_ms": policy.target_p95_ms * policy.band_high,
            "slack_below_ms": policy.target_p95_ms * policy.band_low,
            "burn_threshold": policy.burn_threshold,
            "breach_ticks_needed": policy.breach_ticks,
            "slack_ticks_needed": policy.slack_ticks,
            "max_poison_rate_per_s": policy.max_poison_rate,
        },
        "counters": {"breach_ticks": state.breach_ticks,
                     "slack_ticks": state.slack_ticks},
        "cooldown": {
            "since_last_action_s": (None if since_action is None
                                    else round(since_action, 3)),
            "out_active": cool_out,
            "in_active": cool_in,
        },
    }


class Autoscaler:
    """The loop's plumbing around :func:`decide`: sensor collection from
    live instruments, actuation against the pool, and the decision ring.

    ``tick()`` is called from the app's sampler tick (the same place
    ``pool.probe()`` rides) and returns sample keys for the timeseries —
    the autoscaler owns no thread. ``engine_factory`` builds the engine
    for a scale-out (sharing params/AOT cache with the boot replicas);
    without one the controller still observes and records but can only
    scale in.
    """

    def __init__(self, pool, serving: "ServingConfig", *,
                 slos=None, queue=None,
                 engine_factory: Optional[Callable[[], Any]] = None,
                 clock: Callable[[], float] = time.monotonic):
        self._serving = serving
        self.policy = AutoscalePolicy(serving)
        self.pool = pool
        self._slos = slos  # SloEvaluator (or None in bare tests)
        self._queue = queue
        self._engine_factory = engine_factory
        self._clock = clock
        self.state = ControllerState()
        # Bounded by construction (the VMT115 contract): the debug
        # endpoint serves the tail, history beyond it is the recorder's
        # and the counter's job.
        self.decisions: deque = deque(maxlen=self.policy.history)
        # (t, vmt_poison_jobs_total) marks for the windowed poison rate.
        self._poison_marks: deque = deque(maxlen=256)
        self._lock = threading.Lock()
        self.target_replicas = self._live_count()
        TARGET_REPLICAS.set(float(self.target_replicas))

    # ------------------------------------------------------------ sensors
    def _live_count(self) -> int:
        return sum(1 for r in self.pool.replicas_info()
                   if r["state"] != "dead")

    def _poison_rate(self, now: float) -> float:
        total = float(obs.POISON_COUNTER.value())
        marks = self._poison_marks
        marks.append((now, total))
        horizon = now - self.policy.window_s
        oldest = None
        for t, v in marks:
            if t >= horizon:
                oldest = (t, v)
                break
        if oldest is None or now - oldest[0] <= 0:
            return 0.0
        return max(0.0, (total - oldest[1]) / (now - oldest[0]))

    def observe(self, now: Optional[float] = None) -> AutoscaleInputs:
        """One sensor sweep over the live instruments."""
        if now is None:
            now = self._clock()
        p95 = obs.QUEUE_WAIT.window_percentile(0.95, self.policy.window_s)
        burn_fast = burn_slow = worst = 0.0
        if self._slos is not None:
            for slo in self._slos.slos:
                f, _, _ = slo.burn_rate(self._slos.fast_window_s)
                s, _, _ = slo.burn_rate(self._slos.slow_window_s)
                if min(f, s) >= worst:
                    worst = min(f, s)
                    burn_fast, burn_slow = f, s
        infos = self.pool.replicas_info()
        depth = 0
        if self._queue is not None:
            try:
                depth = int(self._queue.counts().get("pending", 0))
            except Exception:  # noqa: BLE001 — a sensor must not kill the tick
                depth = 0
        return AutoscaleInputs(
            queue_wait_p95_ms=p95,
            burn_fast=burn_fast,
            burn_slow=burn_slow,
            ready_replicas=sum(1 for r in infos if r["state"] == "ready"),
            live_replicas=sum(1 for r in infos if r["state"] != "dead"),
            booting_replicas=sum(1 for r in infos
                                 if r["state"] in ("booting", "warming")),
            open_breakers=sum(1 for r in infos
                              if r.get("breaker") == "open"),
            poison_rate_per_s=self._poison_rate(now),
            queue_depth=depth,
            can_add=self._engine_factory is not None,
        )

    # ------------------------------------------------------------ the loop
    def tick(self) -> Dict[str, float]:
        """One control step; returns sample keys for the timeseries."""
        now = self._clock()
        inputs = self.observe(now)
        with self._lock:
            decision = decide(self.policy, self.state, inputs, now)
            self.decisions.append(decision)
            self.target_replicas = decision["target_replicas"]
        DECISIONS.inc(action=decision["action"], reason=decision["reason"])
        TARGET_REPLICAS.set(float(self.target_replicas))
        action = decision["action"]
        if action != ACTION_HOLD or decision["reason"] in (
                "breaker_open", "poison_storm"):
            # Flight-recorder trigger: actions and health-gated holds are
            # the moments an operator replays (recorder_min_interval_s
            # already throttles repeats).
            obs.record_event("autoscale", action=action,
                             reason=decision["reason"],
                             target_replicas=self.target_replicas,
                             queue_wait_p95_ms=inputs.queue_wait_p95_ms,
                             burn_fast=round(inputs.burn_fast, 3),
                             burn_slow=round(inputs.burn_slow, 3),
                             poison_rate_per_s=round(
                                 inputs.poison_rate_per_s, 3))
        if action == ACTION_SCALE_OUT:
            self._do_scale_out(decision)
        elif action == ACTION_SCALE_IN:
            self._do_scale_in(decision)
        return {
            "autoscale_target_replicas": float(self.target_replicas),
            "autoscale_breach_ticks": float(self.state.breach_ticks),
            "autoscale_slack_ticks": float(self.state.slack_ticks),
            "autoscale_queue_wait_p95_ms": float(
                inputs.queue_wait_p95_ms or 0.0),
            "autoscale_burn": float(min(inputs.burn_fast,
                                        inputs.burn_slow)),
            "autoscale_poison_rate_per_s": float(inputs.poison_rate_per_s),
        }

    # --------------------------------------------------------- actuators
    def _do_scale_out(self, decision: Dict[str, Any]) -> None:
        t0 = time.perf_counter()
        try:
            rep = self.pool.add_replica(self._engine_factory(), warm=True)
        except Exception as e:  # noqa: BLE001 — a failed boot must not
            decision["actuated"] = {"error": repr(e)}  # kill the sampler
            DECISIONS.inc(action="scale_out_failed", reason="actuator_error")
            obs.record_event("autoscale_actuator_failed",
                             action=ACTION_SCALE_OUT, error=repr(e))
            return
        boot_s = round(time.perf_counter() - t0, 3)
        decision["actuated"] = {"replica": rep.name, "state": rep.state,
                                "boot_s": boot_s}
        if rep.state == "dead":
            # add_replica contains boot failures as a DEAD replica; the
            # controller must not read that as capacity.
            DECISIONS.inc(action="scale_out_failed", reason="boot_failed")

    def _do_scale_in(self, decision: Dict[str, Any]) -> None:
        try:
            info = self.pool.retire_replica()
        except (ValueError, TimeoutError, KeyError) as e:
            decision["actuated"] = {"error": repr(e)}
            DECISIONS.inc(action="scale_in_failed", reason="actuator_error")
            obs.record_event("autoscale_actuator_failed",
                             action=ACTION_SCALE_IN, error=repr(e))
            return
        decision["actuated"] = {"replica": info["name"],
                                "drain_s": info["drain_s"]}

    # ------------------------------------------------------ introspection
    def debug_payload(self, limit: int = 50) -> Dict[str, Any]:
        """The ``GET /debug/autoscale`` body: policy, live state, and the
        last-N decision records, newest last."""
        with self._lock:
            recs = list(self.decisions)[-max(1, int(limit)):]
            state = {
                "breach_ticks": self.state.breach_ticks,
                "slack_ticks": self.state.slack_ticks,
                "last_action": self.state.last_action,
                "last_action_t": self.state.last_action_t,
            }
        return {
            "enabled": self.policy.enabled,
            "target_replicas": self.target_replicas,
            "actual_replicas": self._live_count(),
            "policy": self.policy.snapshot(),
            "state": state,
            "decisions": recs,
        }

    def decisions_list(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self.decisions)
