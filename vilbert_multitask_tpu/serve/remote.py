"""Remote worker mode: drain the job queue over HTTP from another host.

Reference capability: the broker is a *network* service (demo/sender.py:12-15
connects to RabbitMQ over TCP; the Django web tier and the GPU worker are
separate processes on separate boxes, worker.py:661-676). The TPU build's
durable queue is an embedded sqlite file on the web host — this module gives
it the network face: a worker anywhere reaches the web host's ``/worker/*``
endpoints (serve/http_api.py) to claim jobs, record audit rows, save answers
and push websocket frames, while inference runs on the worker's own chips.

Design: :class:`ServeWorker` already talks to exactly three collaborators —
queue (claim/ack/nack), store (create_question/save_answer), hub (publish).
The remote mode implements those three interfaces as thin HTTP shims, so the
entire job pipeline (intake, micro-batching, failure handling, rendering) is
the SAME code serving locally and remotely — no second worker implementation
to drift.

Caveat (documented in ARCHITECTURE.md): grounding-box rendering reads the
source image from local disk; on a worker host without the media volume the
render step degrades gracefully (no result_images), exactly like the local
path when an image file is missing.

Run: ``python -m vilbert_multitask_tpu.serve.remote --url http://web:8400``.
"""

from __future__ import annotations

import argparse
import json
import logging
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional, Sequence

from vilbert_multitask_tpu.resilience import CircuitBreaker, RetryPolicy
from vilbert_multitask_tpu.resilience.faults import fault_point
from vilbert_multitask_tpu.serve.queue import Job

log = logging.getLogger(__name__)

# Transient transport failures worth retrying (web-host restart, TCP blip).
# CircuitOpenError and FaultInjected both subclass ConnectionError, so a
# breaker-shed or injected call takes the same handling as real loss.
_NET_ERRORS = (urllib.error.URLError, ConnectionError, TimeoutError, OSError)


class WorkerApiClient:
    """JSON-over-HTTP client for the web host's ``/worker/*`` endpoints.

    Network errors retry through the shared :class:`RetryPolicy` — full
    jitter, so N workers that lost the web host together do NOT hammer it
    back in lockstep when it returns (the old hand-rolled loop here slept
    ``base * 2**attempt`` un-jittered: a thundering herd). A web-host
    restart or TCP blip must not kill a TPU worker that took minutes to
    warm up; the :class:`CircuitBreaker` makes a DEAD web host cheap to
    wait out (fail-fast instead of a connect timeout per call). HTTP
    *status* errors (401 bad token, 400 bad request) do NOT retry: they
    are deterministic and the caller needs to see them.
    """

    def __init__(self, base_url: str, *, token: Optional[str] = None,
                 timeout_s: float = 30.0,
                 retry: Optional[RetryPolicy] = None,
                 breaker: Optional[CircuitBreaker] = None):
        self.base_url = base_url.rstrip("/")
        self.token = token
        self.timeout_s = timeout_s
        self.retry = retry or RetryPolicy()
        self.breaker = breaker or CircuitBreaker(name="remote.transport")

    def post(self, path: str, payload: Dict[str, Any]) -> Dict[str, Any]:
        def attempt() -> Dict[str, Any]:
            # Fault site BEFORE the request: an injected flap models the
            # connection dying, never a half-applied server-side effect.
            fault_point("remote.post")
            req = urllib.request.Request(
                self.base_url + path,
                data=json.dumps(payload).encode(),
                headers={
                    "Content-Type": "application/json",
                    **({"Authorization": f"Bearer {self.token}"}
                       if self.token else {}),
                },
                method="POST",
            )
            with urllib.request.urlopen(
                    req, timeout=self.timeout_s) as resp:
                return json.loads(resp.read() or b"{}")

        return self.retry.call(
            attempt, site="remote.post", retry_on=_NET_ERRORS,
            # HTTPError subclasses URLError: without this it would retry.
            no_retry=(urllib.error.HTTPError,), breaker=self.breaker)


class RemoteQueue:
    """DurableQueue's consumer interface over HTTP (claim/ack/nack/release).

    Failure posture follows at-least-once delivery: a claim that can't reach
    the web host reports "queue drained" (the loop sleeps and retries); a
    lost ack/nack is swallowed with a warning — the visibility timeout
    redelivers the job, which is the same guarantee the local sqlite queue
    gives a worker that crashes between claim and ack."""

    def __init__(self, client: WorkerApiClient):
        self._c = client

    def claim(self, exclude: Sequence[int] = (),
              claimed_by: Optional[str] = None) -> Optional[Job]:
        try:
            out = self._c.post("/worker/claim",
                               {"exclude": list(exclude),
                                "claimed_by": claimed_by})
        except _NET_ERRORS as e:
            log.warning("claim unreachable (%s); treating as drained", e)
            return None
        j = out.get("job")
        if j is None:
            return None
        return Job(id=int(j["id"]), body=j["body"],
                   attempts=int(j["attempts"]),
                   deliveries=int(j.get("deliveries", 0)))

    def pop_dead_letters(self) -> List[Job]:
        """Poison-quarantine notifications (exactly-one-notifier: the web
        host's ``dead_notified`` column hands each job to one caller).
        Unreachable web host → empty list; the jobs stay claimable by the
        next poll."""
        try:
            out = self._c.post("/worker/dead_letters", {})
        except _NET_ERRORS as e:
            log.warning("dead_letters unreachable (%s)", e)
            return []
        return [Job(id=int(j["id"]), body=j["body"],
                    attempts=int(j["attempts"]),
                    deliveries=int(j.get("deliveries", 0)))
                for j in out.get("jobs", [])]

    def ack(self, job_id: int) -> None:
        try:
            self._c.post("/worker/ack", {"job_id": job_id})
        except _NET_ERRORS as e:
            log.warning("ack(%d) lost (%s); job will redeliver", job_id, e)

    def nack(self, job_id: int) -> str:
        try:
            return self._c.post("/worker/nack", {"job_id": job_id}).get(
                "status", "gone")
        except _NET_ERRORS as e:
            log.warning("nack(%d) lost (%s); visibility timeout will "
                        "requeue", job_id, e)
            return "gone"

    def release(self, job_id: int) -> None:
        try:
            self._c.post("/worker/release", {"job_id": job_id})
        except _NET_ERRORS as e:
            log.warning("release(%d) lost (%s)", job_id, e)


class RemoteStore:
    """ResultStore's worker-side interface over HTTP."""

    def __init__(self, client: WorkerApiClient):
        self._c = client

    def create_question(self, task_id: int, input_text: str,
                        input_images: List[str], socket_id: str,
                        queue_job_id: Optional[int] = None) -> int:
        out = self._c.post("/worker/question", {
            "task_id": task_id, "input_text": input_text,
            "input_images": list(input_images), "socket_id": socket_id,
            "queue_job_id": queue_job_id,
        })
        return int(out["qa_id"])

    def save_answer(self, qa_id: int, answer: Dict[str, Any],
                    answer_images: Optional[List[str]] = None) -> None:
        self._c.post("/worker/answer", {
            "qa_id": qa_id, "answer": answer,
            "answer_images": answer_images or [],
        })


class RemoteHub:
    """PushHub's publish interface over HTTP — frames fan out to the web
    host's websocket clients. Best-effort like the local hub: a dead web
    host must not crash the job cycle (the queue redelivers on nack)."""

    def __init__(self, client: WorkerApiClient):
        self._c = client

    def publish(self, socket_id: str, payload: Dict[str, Any]) -> int:
        try:
            out = self._c.post("/worker/push",
                               {"socket_id": socket_id, "frame": payload})
            return int(out.get("subscribers", 0))
        except (urllib.error.URLError, OSError, ValueError):
            return 0


def build_remote_worker(base_url: str, *, cfg=None, engine=None,
                        feature_root: str = "features",
                        checkpoint_path: Optional[str] = None,
                        token: Optional[str] = None):
    """A ServeWorker whose queue/store/hub live on ``base_url``."""
    from vilbert_multitask_tpu.config import FrameworkConfig
    from vilbert_multitask_tpu.engine.runtime import InferenceEngine
    from vilbert_multitask_tpu.features.store import FeatureStore
    from vilbert_multitask_tpu.serve.worker import ServeWorker

    cfg = cfg or FrameworkConfig()
    s = cfg.serving
    client = WorkerApiClient(
        base_url, token=token,
        retry=RetryPolicy(max_attempts=s.retry_max_attempts,
                          base_delay_s=s.retry_base_delay_s,
                          max_delay_s=s.retry_max_delay_s),
        breaker=CircuitBreaker(name="remote.transport",
                               failure_threshold=s.breaker_failure_threshold,
                               window_s=s.breaker_window_s,
                               reset_timeout_s=s.breaker_reset_timeout_s))
    if engine is None:
        params = None
        if checkpoint_path is not None:
            from vilbert_multitask_tpu.checkpoint import restore_params

            params = restore_params(checkpoint_path)
        engine = InferenceEngine(cfg, params=params,
                                 feature_store=FeatureStore(feature_root))
    return ServeWorker(engine, RemoteQueue(client), RemoteStore(client),
                       RemoteHub(client), cfg.serving)


def main(argv=None) -> None:
    p = argparse.ArgumentParser(
        description="ViLBERT multi-task remote TPU worker")
    p.add_argument("--url", required=True,
                   help="web host base URL, e.g. http://web:8400")
    p.add_argument("--features", default="features")
    p.add_argument("--checkpoint", default=None)
    p.add_argument("--token", default=None,
                   help="bearer token if the web host sets worker_token")
    p.add_argument("--poll", type=float, default=0.5,
                   help="idle poll interval (s); remote claims are HTTP "
                        "requests, so idle polling is throttled vs the "
                        "local worker's 0.05s sqlite poll")
    p.add_argument("--no-warmup", action="store_true")
    args = p.parse_args(argv)

    # This process is its own fleet incarnation: mint the identity and
    # stamp exposition samples and spans, mirroring ServeApp.start().
    # Claims this worker posts carry the same ident in claimed_by.
    from vilbert_multitask_tpu import obs

    identity = obs.process_identity("remote-worker")
    obs.REGISTRY.set_default_labels(**identity.labels())
    obs.default_tracer().set_default_attrs(
        instance=identity.ident, role=identity.role)
    worker = build_remote_worker(
        args.url, feature_root=args.features,
        checkpoint_path=args.checkpoint, token=args.token)
    if args.checkpoint is None:
        print("WARNING: no --checkpoint given; serving randomly initialized "
              "weights (answers will be meaningless)")
    if not args.no_warmup:
        print("warming shape buckets...")
        worker.engine.warmup()
    print(f"draining {args.url} ...")
    worker.run_forever(poll_interval_s=args.poll)


if __name__ == "__main__":
    main()
