"""Realtime result push: per-socket groups + websocket bridge.

Reference capability: the Channels/Redis fanout — ``log_to_terminal`` sends
a JSON frame to the Redis group named by the client's socket id
(reference demo/utils.py:5-6); clients join their group by sending the bare
socket id as the first websocket frame (demo/consumers.py:8-12,
result.html:83-88); frames carry ``info`` / ``terminal`` / ``result`` keys
(result.html:96-111).

Redesign: the broker hop is gone. ``PushHub`` is an in-process, thread-safe
group router (worker thread → hub → websocket event loop), and
``WebSocketBridge`` speaks the same client protocol over the ``websockets``
library. Multi-process deployments fan out by running one bridge per web
process and routing jobs by socket id at the queue — cross-host tensors never
ride this path (SURVEY.md §2.3: DCN carries job/control traffic only).
"""

from __future__ import annotations

import asyncio
import json
import queue as queue_mod
import threading
from collections import defaultdict
from typing import Any, Dict, List, Optional

from vilbert_multitask_tpu.resilience.faults import FaultInjected, fault_point


class PushHub:
    """socket_id → subscriber queues; publish is non-blocking."""

    def __init__(self, max_queued: int = 256):
        self.max_queued = max_queued
        self._lock = threading.Lock()
        self._groups: Dict[str, List[queue_mod.Queue]] = defaultdict(list)

    def subscribe(self, socket_id: str) -> queue_mod.Queue:
        q: queue_mod.Queue = queue_mod.Queue(self.max_queued)
        with self._lock:
            self._groups[socket_id].append(q)
        return q

    def unsubscribe(self, socket_id: str, q: queue_mod.Queue) -> None:
        with self._lock:
            subs = self._groups.get(socket_id)
            if subs and q in subs:
                subs.remove(q)
            if subs is not None and not subs:
                del self._groups[socket_id]

    def publish(self, socket_id: str, payload: Dict[str, Any]) -> int:
        """Send to every subscriber of the group; slow consumers drop oldest
        (the reference's Redis groups drop silently on backpressure too)."""
        try:
            payload = fault_point("push.publish", payload)
        except FaultInjected:
            # Push is best-effort by contract — an injected fault here
            # models a dropped frame, never an error into the job cycle.
            return 0
        with self._lock:
            subs = list(self._groups.get(socket_id, ()))
        for q in subs:
            try:
                q.put_nowait(payload)
            except queue_mod.Full:
                try:
                    q.get_nowait()
                    q.put_nowait(payload)
                except (queue_mod.Empty, queue_mod.Full):
                    # Racing publisher refilled the slot first — drop this
                    # frame for the slow consumer; push is best-effort and
                    # must never raise into the worker's job cycle.
                    pass
        return len(subs)


def log_to_terminal(hub: PushHub, socket_id: str, message: Dict[str, Any]) -> None:
    """The reference helper's exact contract (demo/utils.py:5-6): publish a
    dict frame — callers use {"terminal": ...}, {"result": ...}, {"info": ...}."""
    hub.publish(socket_id, message)


def fan_out(hub: PushHub, socket_ids: List[str],
            message: Dict[str, Any]) -> int:
    """Publish one frame to MANY groups — the coalescing tier's terminal
    fan-out (worker._fan_to_followers): every follower of a singleflight
    leader hears the leader's result/dead-letter/deadline frame. Each
    group gets its own dict copy (subscriber queues outlive this call;
    a shared mutable frame would alias across consumers). Returns total
    subscriber deliveries, same best-effort contract as publish."""
    delivered = 0
    for sid in socket_ids:
        delivered += hub.publish(sid, dict(message))
    return delivered


class WebSocketBridge:
    """Asyncio websocket server bridging :class:`PushHub` to browsers.

    Client protocol (reference result.html:83-111): first text frame is the
    bare socket id; every server frame afterwards is a JSON object.
    """

    def __init__(self, hub: PushHub, host: str = "127.0.0.1", port: int = 8401):
        self.hub = hub
        self.host = host
        self.port = port
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()
        self._stop: Optional[asyncio.Event] = None
        self.bound_port: Optional[int] = None  # actual port (for port=0)

    async def _handle(self, websocket):
        socket_id = (await websocket.recv()).strip()
        sub = self.hub.subscribe(socket_id)
        loop = asyncio.get_running_loop()

        def next_frame():
            # Short timeout bounds how long a cancelled connection pins its
            # executor thread; frames themselves arrive with no added latency.
            try:
                return sub.get(timeout=1.0)
            except queue_mod.Empty:
                return None

        async def pump():
            while True:
                try:
                    payload = await loop.run_in_executor(None, next_frame)
                except RuntimeError:
                    return  # executor gone: interpreter/bridge shutting down
                if payload is not None:
                    await websocket.send(json.dumps(payload))

        # Race the pump against connection close so idle clients that
        # disconnect don't leak their subscription (nothing is ever sent to
        # an idle group, so a send-side ConnectionClosed never fires).
        pump_task = asyncio.ensure_future(pump())
        closed_task = asyncio.ensure_future(websocket.wait_closed())
        try:
            await asyncio.wait({pump_task, closed_task},
                               return_when=asyncio.FIRST_COMPLETED)
        finally:
            pump_task.cancel()
            closed_task.cancel()
            self.hub.unsubscribe(socket_id, sub)

    async def _serve(self):
        import websockets

        self._stop = asyncio.Event()
        async with websockets.serve(self._handle, self.host, self.port) as server:
            socks = getattr(server, "sockets", None) or server.server.sockets
            self.bound_port = socks[0].getsockname()[1]
            self._started.set()
            await self._stop.wait()

    def start(self) -> None:
        try:
            import websockets  # noqa: F401
        except ImportError:
            # No websockets lib in this environment: degrade to HTTP-only
            # serving instead of failing boot. In-process consumers (result
            # polling, the soak's direct hub subscription) still get every
            # frame — only the browser bridge is absent. bound_port=0 keeps
            # /config well-formed.
            self.bound_port = 0
            return

        def run():
            self._loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self._loop)
            self._loop.run_until_complete(self._serve())

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="ws-bridge")
        self._thread.start()
        if not self._started.wait(timeout=10):
            raise RuntimeError("websocket bridge failed to start")

    def stop(self) -> None:
        if self._loop and self._stop:
            self._loop.call_soon_threadsafe(self._stop.set)
        if self._thread:
            self._thread.join(timeout=5)
