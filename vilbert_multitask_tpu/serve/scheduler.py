"""Continuous batching: the deadline-aware cross-request scheduler.

``step_batch`` (worker.py) drains the queue in lockstep — claim N, prep N,
forward once, persist N, repeat — so the device idles through every claim
and every SQLite write, and a job arriving one tick after a batch closed
waits a whole cycle. The soak showed the cost: 44 qps served against a
217-408 qps engine ceiling (ARCHITECTURE "Round-5 hardware findings").
This module replaces that loop with the Orca/vLLM-shaped pipelined data
plane the 12-in-1 shared trunk makes possible (any task mix packs into one
forward):

    intake pool (N threads)        scheduler (dispatch thread)   completion
    claim -> deadline check        adaptive window + EDF pack    _finish_job
    -> feature I/O + prep    ==>   -> chunk_plan -> run_many ==> persist+push
    feeds _ready               results stream out per member     ack

Three rules govern the dispatch stage:

- **window**: fire when a bucket fills, when the oldest ready job has
  lingered a full window, or when any member's deadline slack drops under
  ``sched_near_deadline_ms``. The window adapts AIMD-style — a full batch
  doubles it (backlog: linger to pack more), a partial batch halves it
  (idle: fire immediately) — between ``sched_window_min_s`` and
  ``sched_window_max_s``.
- **EDF**: members pack in earliest-deadline-first order (the
  ``resilience.Deadline`` riding every job body is the key); expired
  members shed pre-pack via the worker's normal expiry path, so a forward
  is never burned on a long-gone client.
- **exactly one terminal state**: every claimed job ends in exactly one of
  result / dead-letter / deadline push — results stream member-by-member
  into the completion queue as chunks drain (engine ``on_result``), and a
  mid-batch failure fails only the members that had NOT already streamed.

Lock discipline (vmtlint VMT116 ``blocking-call-under-scheduler-lock``):
``_cond`` guards only the ready list, the window, and the stat counters —
never device dispatch, SQLite I/O, or sleeps. Expiry pushes, intake I/O,
and ``run_many`` all happen outside it; the completion queue's blocking
``put`` is the one intentional backpressure point and sits outside too.
"""

from __future__ import annotations

import math
import queue as stdlib_queue
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import List, Optional, Tuple

from vilbert_multitask_tpu import obs
from vilbert_multitask_tpu.serve.pool import NoReadyReplica
from vilbert_multitask_tpu.serve.push import log_to_terminal
from vilbert_multitask_tpu.serve.queue import Job


class ReadyItem:
    """One claimed + prepped job parked in the ready-queue.

    ``solo`` marks attention-map requests: they need a per-request forward
    flag, so they skip shared intake here (``step_one`` runs the whole
    pipeline for them) and never pack into a shared chunk.

    ``tenant`` is the job body's billing dimension, reused as the QoS
    class the deficit tier budgets by; ``deferred`` flips when a fire
    passed this item over for tenant-budget reasons (not row pressure
    alone), so an expiry while deferred sheds as ``tenant_budget``
    instead of ``deadline``.
    """

    __slots__ = ("job", "qa_id", "prepared", "t0", "deadline", "enq_t",
                 "solo", "tenant", "deferred")

    def __init__(self, job: Job, qa_id, prepared, t0, deadline, enq_t,
                 solo: bool = False, tenant: str = "anon"):
        self.job = job
        self.qa_id = qa_id
        self.prepared = prepared
        self.t0 = t0
        self.deadline = deadline
        self.enq_t = enq_t
        self.solo = solo
        self.tenant = tenant
        self.deferred = False

    def rows(self) -> int:
        return self.prepared.n_images if self.prepared is not None else 1

    def expiry(self) -> float:
        """EDF sort key: absolute perf-counter expiry, +inf when the job
        carries no deadline (budgetless jobs pack last, never shed)."""
        return (self.deadline.expires_at() if self.deadline is not None
                else math.inf)


def fire_decision(now: float, *, rows: int, oldest_enq_t: float,
                  nearest_expiry: float, max_rows: int, window_s: float,
                  near_deadline_s: float) -> Tuple[bool, float]:
    """Pure window policy: should a non-empty ready set fire now?

    Returns ``(fire, wait_s)`` — when not firing, ``wait_s`` is how long
    the dispatcher may sleep before one of the fire conditions can first
    become true (new arrivals re-wake it earlier via the condvar).
    ``nearest_expiry`` is +inf when no member carries a deadline.
    """
    if rows >= max_rows:
        return True, 0.0  # a bucket is full — lingering buys nothing
    if nearest_expiry - now <= near_deadline_s:
        return True, 0.0  # EDF front would miss its deadline waiting
    window_wait = (oldest_enq_t + window_s) - now
    if window_wait <= 0.0:
        return True, 0.0  # oldest member waited out the whole window
    deadline_wait = nearest_expiry - now - near_deadline_s
    return False, max(min(window_wait, deadline_wait), 0.0)


def select_batch(ready: List[ReadyItem], now: float, max_rows: int, *,
                 deficits: "Optional[dict]" = None,
                 weights: "Optional[dict]" = None,
                 default_weight: float = 1.0
                 ) -> Tuple[List[ReadyItem], List[ReadyItem],
                            List[ReadyItem]]:
    """Pure packing: ``(batch, expired, rest)``.

    Members sort earliest-deadline-first; already-expired members are
    split out for shedding (the caller expires them OUTSIDE the scheduler
    lock — expiry pushes/acks block). Packing stops charging the row
    budget once ``max_rows`` is reached; later members stay ready, still
    in EDF order, for the next fire.

    With ``deficits`` (the caller's persistent tenant→credit map) a
    weighted-deficit tier sits ABOVE the deadline ordering: each fire
    grants every present tenant ``max_rows * w/Σw`` rows of credit
    (weights from ServingConfig.tenant_weights, ``default_weight`` for
    unlisted tenants), then repeatedly packs the EDF head of the
    highest-credit tenant, spending its credit per row. The tier is
    work-conserving — the device never idles for fairness; under
    contention a hot tenant's surplus items are the ones passed over
    (marked ``deferred``, shed as ``tenant_budget`` if they expire
    waiting). A tenant whose backlog fully drains in a fire resets to
    zero credit and leaves the map, bounding its cardinality to tenants
    with live backlog. ``deficits=None`` is the pure-EDF legacy path.
    """
    batch: List[ReadyItem] = []
    expired: List[ReadyItem] = []
    rest: List[ReadyItem] = []
    live: List[ReadyItem] = []
    for item in sorted(ready, key=ReadyItem.expiry):
        if item.deadline is not None and item.expiry() <= now:
            expired.append(item)
        else:
            live.append(item)
    if deficits is None:
        rows = 0
        for item in live:
            if rows < max_rows:
                batch.append(item)
                rows += item.rows()
            else:
                rest.append(item)
        return batch, expired, rest
    # --- tenant-weighted deficit tier (DRR) above EDF ---
    weights = weights or {}
    present: "dict[str, List[ReadyItem]]" = {}
    for item in live:
        present.setdefault(item.tenant, []).append(item)
    if present:
        total_w = sum(max(weights.get(t, default_weight), 1e-9)
                      for t in present)
        for t in present:
            share = max(weights.get(t, default_weight), 1e-9) / total_w
            # Credit carries over between fires (a starved tenant's
            # backlog catches up) but is capped so an idle-then-bursty
            # tenant cannot hoard the whole device.
            deficits[t] = min(deficits.get(t, 0.0) + max_rows * share,
                              2.0 * max_rows)
    rows = 0
    while rows < max_rows:
        cands = [t for t, items in present.items() if items]
        if not cands:
            break
        # Highest credit wins the slot; earliest deadline breaks ties.
        t = max(cands, key=lambda c: (deficits.get(c, 0.0),
                                      -present[c][0].expiry()))
        item = present[t].pop(0)
        batch.append(item)
        rows += item.rows()
        deficits[t] = deficits.get(t, 0.0) - item.rows()
    for t, items in list(present.items()):
        if items:
            for item in items:
                item.deferred = True
                rest.append(item)
        else:
            # Backlog fully served: classic DRR resets the credit, and
            # dropping the entry bounds the map to live-backlog tenants.
            deficits.pop(t, None)
    rest.sort(key=ReadyItem.expiry)
    return batch, expired, rest


def adapt_window(window_s: float, fill: float, *, lo: float, hi: float
                 ) -> float:
    """Pure AIMD window update: full batches stretch (backlog — linger to
    pack the next one fuller), partial batches shrink (idle — fire fast)."""
    if fill >= 1.0:
        return min(window_s * 2.0, hi)
    return max(window_s / 2.0, lo)


class ContinuousScheduler:
    """The three-stage data plane around one :class:`ServeWorker`.

    ``run()`` owns the dispatch loop in the calling thread (the serve
    worker thread), spawns ``sched_intake_threads`` intake threads and one
    completion thread, and tears all of them down on ``stop_event``:
    intake stops claiming first, in-hand ready jobs release back to
    pending (no attempt charged), the completion queue drains, and only
    then does run() return — the same graceful-drain contract
    ``step_batch`` honored.

    ``clock`` is injectable for window/EDF tests; spans keep their own
    ``time.perf_counter`` so traces stay real under a fake clock.
    """

    def __init__(self, worker, *, stop_event: Optional[threading.Event] = None,
                 poll_interval_s: float = 0.05, clock=time.perf_counter):
        self.worker = worker
        self.serving = worker.serving
        self.stop = stop_event if stop_event is not None else threading.Event()
        self.poll_interval_s = poll_interval_s
        self.clock = clock
        # _cond guards _ready, _window_s, and _stats — NOTHING blocking
        # runs under it (VMT116).
        self._cond = threading.Condition()
        self._ready: List[ReadyItem] = []
        self._window_s = self.serving.sched_window_min_s
        self._stats = {"batches": 0, "jobs": 0, "shed": 0, "released": 0,
                       "solo": 0}
        # Tenant-weighted fairness state (select_batch's deficit tier):
        # the persistent tenant→credit map, the configured weights, and
        # a per-tenant queue-wait EWMA for the sampler. All guarded by
        # _cond like the rest of the scheduler state.
        self._fairness = bool(
            getattr(self.serving, "tenant_fairness_enabled", False))
        self._weights = dict(
            getattr(self.serving, "tenant_weights", None) or {})
        self._default_weight = float(
            getattr(self.serving, "tenant_default_weight", 1.0))
        self._deficits: dict = {}
        self._tenant_wait_ms: dict = {}
        self._completions: stdlib_queue.Queue = stdlib_queue.Queue(
            maxsize=self.serving.sched_completion_depth)
        # Replica-pool mode: when the worker's engine is a ReplicaPool
        # (duck-typed on the checkout seam), batches PIN to one replica —
        # checkout here, dispatch on an executor thread (one in-flight
        # batch per replica slot), checkin in the dispatch task. The
        # dispatch loop keeps selecting the next batch while replicas
        # compute concurrently. Legacy single engines dispatch inline.
        self.pool = (worker.engine
                     if hasattr(worker.engine, "checkout") else None)
        self._executor: Optional[ThreadPoolExecutor] = None
        if self.pool is not None:
            slots = (len(self.pool.replicas)
                     * self.serving.pool_max_inflight_per_replica)
            self._executor = ThreadPoolExecutor(
                max_workers=max(1, slots),
                thread_name_prefix="sched-dispatch")

    # -------------------------------------------------------- intake stage
    def _intake_loop(self) -> None:
        """Claim continuously; prep on this thread; park ready items.

        Backpressure: while the ready set is at ``sched_ready_depth`` this
        thread idles instead of claiming — ready jobs stay 'inflight' in
        the durable queue, so they keep counting against the HTTP door's
        AdmissionController depth (pending + inflight); the knob bounds
        claim run-ahead, it does not bypass admission.

        Runs under :func:`obs.crash_guard`: the exc tier proved the
        claim at the top of this loop sits OUTSIDE the intake
        try/except, so an injected ``queue.claim`` fault (or any remote
        transport error) would kill the thread silently. The guard
        records a ``thread_died`` bundle and flips ``/healthz`` instead.
        """
        with obs.crash_guard(threading.current_thread().name):
            self._intake_pump()

    def _intake_pump(self) -> None:
        while not self.stop.is_set():
            with self._cond:
                backlog = len(self._ready)
            if backlog >= self.serving.sched_ready_depth:
                self.stop.wait(self.poll_interval_s)
                continue
            job = self.worker._claim()
            if job is None:
                self.stop.wait(self.poll_interval_s)
                continue
            if self.worker._check_deadline(job):
                continue  # expired on arrival: terminal push already sent
            enq_t = self.clock()
            deadline = self.worker._deadline_of(job)
            tenant = str(job.body.get("tenant") or "anon")
            if job.body.get("collect_attention"):
                # Per-request forward flag: step_one runs the whole
                # pipeline solo at dispatch, so no shared intake here.
                item = ReadyItem(job, None, None, None, deadline, enq_t,
                                 solo=True, tenant=tenant)
            else:
                try:
                    with obs.trace_scope(job.body.get("trace_id")), \
                            obs.span("worker.intake", job_id=job.id,
                                     task_id=job.body.get("task_id", "")):
                        qa_id, prepared, t0 = self.worker._intake(job)
                except Exception:
                    self.worker._fail_job(job)
                    continue
                item = ReadyItem(job, qa_id, prepared, t0, deadline, enq_t,
                                 tenant=tenant)
            with self._cond:
                self._ready.append(item)
                self._cond.notify()

    # ------------------------------------------------------ dispatch stage
    def _next_batch(self) -> Tuple[List[ReadyItem], List[ReadyItem]]:
        """Block until the window policy fires; returns (batch, expired).

        Both lists are selected under ``_cond`` but everything done WITH
        them (expiry pushes, device dispatch) happens after release.
        Returns two empty lists once ``stop`` is set.
        """
        max_rows = self.worker.engine.cfg.engine.max_batch_rows()
        with self._cond:
            while not self.stop.is_set():
                if not self._ready:
                    self._cond.wait(self.poll_interval_s)
                    continue
                now = self.clock()
                fire, wait_s = fire_decision(
                    now,
                    rows=sum(i.rows() for i in self._ready),
                    oldest_enq_t=min(i.enq_t for i in self._ready),
                    nearest_expiry=min(i.expiry() for i in self._ready),
                    max_rows=max_rows,
                    window_s=self._window_s,
                    near_deadline_s=self.serving.sched_near_deadline_ms / 1e3,
                )
                if not fire:
                    self._cond.wait(min(wait_s, self.poll_interval_s))
                    continue
                batch, expired, rest = select_batch(
                    self._ready, now, max_rows,
                    deficits=self._deficits if self._fairness else None,
                    weights=self._weights,
                    default_weight=self._default_weight)
                # Slice-assign keeps the one list object (and is the
                # truncation idiom VMT115 audits in this plane).
                self._ready[:] = rest
                if self._fairness:
                    # In-memory gauge set — non-blocking, fine under
                    # _cond (VMT116 audits blocking calls only).
                    for t, credit in self._deficits.items():
                        obs.TENANT_DEFICIT.set(credit, tenant=t)
                if batch:
                    fill = min(
                        sum(i.rows() for i in batch) / max_rows, 1.0)
                    self._window_s = adapt_window(
                        self._window_s, fill,
                        lo=self.serving.sched_window_min_s,
                        hi=self.serving.sched_window_max_s)
                return batch, expired
        return [], []

    def _checkout_for_dispatch(self):
        """Pool checkout that stays responsive to the drain signal: wait in
        poll-interval slices up to the configured checkout timeout."""
        deadline = self.clock() + self.serving.pool_checkout_timeout_s
        while not self.stop.is_set():
            remaining = deadline - self.clock()
            if remaining <= 0:
                break
            try:
                return self.pool.checkout(
                    timeout_s=min(self.poll_interval_s, remaining))
            except NoReadyReplica:
                continue
        raise NoReadyReplica("no ready replica before drain/timeout")

    def _dispatch(self, batch: List[ReadyItem]) -> None:
        """One fire: solos serve individually, the rest pack through
        ``run_many`` with results streaming to the completion stage.

        Pool mode pins the packed batch to ONE checked-out replica and
        runs it on the executor, so the dispatch loop can fire the next
        batch onto another replica while this one computes."""
        now = self.clock()
        for item in batch:
            obs.SCHED_WAIT.observe(max(now - item.enq_t, 0.0) * 1e3)
            obs.job_charge(item.job.body.get("trace_id", ""),
                           "ready_wait", max(now - item.enq_t, 0.0))
        with self._cond:
            # Per-tenant queue-wait EWMA for the sampler: the fairness
            # tier's observable effect is exactly this number staying
            # flat for light tenants while a hot tenant backlogs.
            for item in batch:
                wait_ms = max(now - item.enq_t, 0.0) * 1e3
                prev = self._tenant_wait_ms.get(item.tenant)
                self._tenant_wait_ms[item.tenant] = (
                    wait_ms if prev is None
                    else 0.8 * prev + 0.2 * wait_ms)
        packed = [i for i in batch if not i.solo]
        solos = [i for i in batch if i.solo]
        for item in solos:
            with self._cond:
                self._stats["solo"] += 1
                self._stats["jobs"] += 1
            self.worker.step_one(item.job)
        if not packed:
            return
        if self.pool is None:
            self._dispatch_packed(packed, None)
            return
        try:
            rep = self._checkout_for_dispatch()
        except NoReadyReplica:
            # Nothing can take the batch right now (swap-drain, breaker
            # storm, or shutdown): release every member for redelivery —
            # no attempt charged, and the delivery-count quarantine still
            # bounds jobs that land here forever.
            for item in packed:
                self.worker._failover_job(item.job, "none")
            return
        self._executor.submit(self._dispatch_packed, packed, rep)

    def _dispatch_packed(self, packed: List[ReadyItem], rep) -> None:
        """Forward one packed batch on one engine (a checked-out replica,
        or the worker's own engine in legacy mode) and stream results."""
        t_pack = time.perf_counter()
        engine = rep.engine if rep is not None else self.worker.engine
        reqs = [i.prepared for i in packed]
        plan = engine.chunk_plan([r.n_images for r in reqs])
        top_bucket = 0
        for idxs in plan:
            rows = sum(reqs[i].n_images for i in idxs)
            bucket = engine.cfg.engine.row_bucket_for(rows)
            top_bucket = max(top_bucket, bucket)
            obs.BATCH_FILL.observe(rows / bucket, bucket=str(bucket))
            obs.BATCHES_DISPATCHED.inc()
        with self._cond:
            self._stats["batches"] += len(plan)
            self._stats["jobs"] += len(packed)
        streamed = set()

        def _on_result(pos: int, result) -> None:
            streamed.add(pos)
            # Blocking put IS the completion backpressure: a stalled
            # persist/push stage eventually stalls dispatch instead of
            # piling unpersisted results without bound.
            self._completions.put((packed[pos], result))

        rep_name = rep.name if rep is not None else ""
        t_fwd = time.perf_counter()
        for item in packed:
            obs.job_charge(item.job.body.get("trace_id", ""), "pack",
                           t_fwd - t_pack)
        rows_total = sum(r.n_images for r in reqs)

        def _charge_forward(wall_s, members) -> None:
            # Amortized device share per member (attrib double-entry: the
            # FULL wall lands on the busy ledger, only listed members are
            # billed — a mid-batch failure's unstreamed rows show as waste).
            obs.job_batch(
                wall_s,
                [(i.job.body.get("trace_id", ""), i.prepared.n_images)
                 for i in members],
                batch_rows=rows_total, bucket=top_bucket, replica=rep_name)

        try:
            with obs.span("worker.batch_forward", n_jobs=len(packed),
                          job_ids=[i.job.id for i in packed],
                          replica=rep_name):
                engine.run_many(reqs, on_result=_on_result)
            # Attribute the shared forward window into each member's own
            # trace (same contract as step_batch) so per-request
            # waterfalls stay contiguous under batching.
            dur_fwd = time.perf_counter() - t_fwd
            for item in packed:
                obs.default_tracer().record_span(
                    "worker.infer", t_fwd, dur_fwd,
                    trace_id=item.job.body.get("trace_id"),
                    job_id=item.job.id, task_id=item.prepared.spec.task_id,
                    batched=True, n_jobs=len(packed))
            _charge_forward(dur_fwd, packed)
            if rep is not None:
                self.pool.checkin(
                    rep, ok=True,
                    elapsed_ms=(time.perf_counter() - t_fwd) * 1e3)
        except Exception as e:  # noqa: BLE001 — split below
            _charge_forward(time.perf_counter() - t_fwd,
                            [i for pos, i in enumerate(packed)
                             if pos in streamed])
            if rep is not None:
                self.pool.checkin(rep, ok=False, error=e)
                rep.failovers += 1
            # Exactly-one-terminal: members that already streamed get
            # their terminal state from the completion stage; only the
            # rest terminate here. With a pool the REPLICA is the suspect
            # (release + redeliver; delivery_count bounds poison jobs) —
            # legacy mode keeps the nack/dead-letter path.
            for pos, item in enumerate(packed):
                if pos not in streamed:
                    if rep is not None:
                        self.worker._failover_job(item.job, rep.name)
                    else:
                        self.worker._fail_job(item.job)

    # ---------------------------------------------------- completion stage
    def _completion_loop(self) -> None:
        """Persist + push off the dispatch thread, so the next batch's
        forward overlaps this batch's DB writes and websocket frames.

        Guarded like the intake loop: ``_fail_job`` in the except arm
        reaches the queue's nack (remote transport in split deploys), so
        even the recovery path can raise — the guard makes that death
        loud instead of stranding every future completion."""
        with obs.crash_guard(threading.current_thread().name):
            self._completion_pump()

    def _completion_pump(self) -> None:
        while True:
            msg = self._completions.get()
            if msg is None:
                return
            item, result = msg
            try:
                with obs.trace_scope(item.job.body.get("trace_id")):
                    self.worker._finish_job(item.job, item.qa_id,
                                            item.prepared, result, item.t0)
                self.worker.queue.ack(item.job.id)
                self.worker._untrack(item.job.id)
            except Exception:
                self.worker._fail_job(item.job)

    # -------------------------------------------------------------- driver
    def run(self) -> None:
        intakes = [
            threading.Thread(target=self._intake_loop,
                             name=f"sched-intake-{i}", daemon=True)
            for i in range(max(1, self.serving.sched_intake_threads))
        ]
        completion = threading.Thread(target=self._completion_loop,
                                      name="sched-completion", daemon=True)
        for t in intakes:
            t.start()
        completion.start()
        try:
            while not self.stop.is_set():
                batch, expired = self._next_batch()
                for item in expired:
                    with self._cond:
                        self._stats["shed"] += 1
                    # An expiry while tenant-budget-deferred is the
                    # fairness tier's shed, not plain overload — keep
                    # the classes separate in vmt_shed_total{reason}.
                    self.worker._expire_job(
                        item.job,
                        reason=("tenant_budget" if item.deferred
                                else "deadline"))
                if batch:
                    self._dispatch(batch)
        finally:
            self.stop.set()
            # Drain order matters: intake stops claiming first, THEN the
            # remaining ready jobs release (a racing intake thread could
            # otherwise re-park a job after its release), then the
            # completion queue finishes every already-forwarded result.
            for t in intakes:
                t.join()
            if self._executor is not None:
                # In-flight replica batches finish (their results are
                # already streaming into the completion queue) before the
                # sentinel below — a shutdown must never orphan a batch
                # between forward and persist.
                self._executor.shutdown(wait=True)
            with self._cond:
                leftovers = list(self._ready)
                self._ready.clear()
                self._stats["released"] += len(leftovers)
            abandoned_by = (getattr(self.worker.engine, "replica_id", None)
                            or "scheduler")
            for item in leftovers:
                self.worker.queue.release(item.job.id)
                obs.record_event("job_abandoned", job_id=item.job.id,
                                 trace_id=item.job.body.get("trace_id"),
                                 replica=abandoned_by)
                frame = {
                    "terminal": "Server draining; job requeued for the "
                                "next worker.",
                    "requeued": True,
                    "abandoned_by": abandoned_by,
                    "question": item.job.body.get("question", ""),
                }
                log_to_terminal(
                    self.worker.hub, item.job.body.get("socket_id", ""),
                    frame)
                # Requeue, not a terminal: coalesced followers stay
                # attached and hear the notice; the next worker's
                # terminal fan-out settles them.
                self.worker._fan_to_followers(item.job.body, [frame],
                                              final=False)
                self.worker._untrack(item.job.id)
            self._completions.put(None)
            completion.join()

    # --------------------------------------------------------------- stats
    def stats(self) -> dict:
        """Scheduler state for the time-series sampler. ``*_total`` keys
        get ``_per_s`` rates derived by the sampler."""
        with self._cond:
            vals = {
                "sched_ready": float(len(self._ready)),
                "sched_window_ms": self._window_s * 1e3,
                "sched_batches_total": float(self._stats["batches"]),
                "sched_jobs_total": float(self._stats["jobs"]),
                "sched_solo_total": float(self._stats["solo"]),
                "sched_shed_total": float(self._stats["shed"]),
                "sched_released_total": float(self._stats["released"]),
                "sched_completion_backlog":
                    float(self._completions.qsize()),
            }
            # Per-tenant queue-wait (EWMA over dispatched items) and live
            # deficit credit — cardinality bounded by tenants actually
            # seen / holding backlog.
            for t, v in self._tenant_wait_ms.items():
                vals[f"sched_tenant_wait_ms.{t}"] = float(v)
            for t, v in self._deficits.items():
                vals[f"sched_tenant_deficit.{t}"] = float(v)
            return vals
