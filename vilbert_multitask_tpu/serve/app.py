"""Application composition: engine + queue + worker + HTTP + websocket.

Reference capability: the deployment described by SURVEY.md §1 — Django
(wsgi/asgi), a RabbitMQ broker, Redis, Postgres, and a GPU worker process —
collapsed into one self-contained serving binary per host: the TPU engine and
all tiers share the process; durability lives in the sqlite queue/store
files. ``python -m vilbert_multitask_tpu.serve.app`` boots everything.
"""

from __future__ import annotations

import argparse
import dataclasses
import os
import threading
import time
from typing import Any, Callable, Dict, Optional

from vilbert_multitask_tpu import obs
from vilbert_multitask_tpu.config import FrameworkConfig, config_fingerprint
from vilbert_multitask_tpu.engine.runtime import InferenceEngine
from vilbert_multitask_tpu.features.store import FeatureStore
from vilbert_multitask_tpu.serve.autoscale import Autoscaler
from vilbert_multitask_tpu.serve.db import ResultStore
from vilbert_multitask_tpu.serve.http_api import ApiServer
from vilbert_multitask_tpu.serve.pool import ReplicaPool
from vilbert_multitask_tpu.serve.push import PushHub, WebSocketBridge
from vilbert_multitask_tpu.serve.queue import DurableQueue
from vilbert_multitask_tpu.serve.resultcache import ResultCache
from vilbert_multitask_tpu.serve.worker import ServeWorker

_FLEET_FLUSH_ERRORS = obs.REGISTRY.counter(
    "vmt_fleet_flush_errors_total",
    "Sampler ticks whose fleet-spine flush failed (local tick unaffected).")
_TRACESTORE_FLUSH_ERRORS = obs.REGISTRY.counter(
    "vmt_tracestore_flush_errors_total",
    "Sampler ticks whose trace-store flush failed (local tick unaffected).")
_AUTOSCALE_TICK_ERRORS = obs.REGISTRY.counter(
    "vmt_autoscale_tick_errors_total",
    "Sampler ticks whose autoscale control step raised (tick unaffected).")


class ServeApp:
    def __init__(self, cfg: Optional[FrameworkConfig] = None, *,
                 engine: Optional[InferenceEngine] = None,
                 feature_root: str = "features",
                 checkpoint_path: Optional[str] = None,
                 live_extract: bool = False,
                 detector_checkpoint: Optional[str] = None,
                 engine_factory: Optional[Callable[[], Any]] = None):
        self.cfg = cfg or FrameworkConfig()
        s = self.cfg.serving
        # Persistent XLA compile cache on by default for the serving binary:
        # restarts skip the per-bucket compiles (the boot-latency item from
        # round 2's verdict). An explicit EngineConfig value wins.
        if self.cfg.engine.compilation_cache_dir is None:
            cache_dir = os.path.join(
                os.path.dirname(s.queue_db_path) or "serve_state", "xla_cache")
            self.cfg = dataclasses.replace(
                self.cfg, engine=dataclasses.replace(
                    self.cfg.engine, compilation_cache_dir=cache_dir))
        # AOT executable cache (engine/aotcache.py) on by default too:
        # NEXT TO THE CHECKPOINT when one is given — the executables are
        # as much a build artifact of the deployed weights as the weights
        # themselves, and a prewarm CI step populates them in the same
        # place every replica host mounts. No checkpoint (random-weights
        # dev boots) → under serve_state with the other durable files.
        # An explicit EngineConfig value wins.
        if self.cfg.engine.aot_cache_dir is None:
            aot_dir = (
                os.path.join(os.path.dirname(os.path.abspath(
                    checkpoint_path)), "aot_cache")
                if checkpoint_path is not None else
                os.path.join(os.path.dirname(s.queue_db_path)
                             or "serve_state", "aot_cache"))
            self.cfg = dataclasses.replace(
                self.cfg, engine=dataclasses.replace(
                    self.cfg.engine, aot_cache_dir=aot_dir))
        self.boot_info: dict = {"phase": "booting"}
        self.extractor = None  # set when live_extract builds a detector
        self.hub = PushHub()
        self.queue = DurableQueue(
            s.queue_db_path, queue_name=s.queue_name,
            max_delivery_attempts=s.max_delivery_attempts,
            max_deliveries=s.queue_max_deliveries)
        self.store = ResultStore(s.results_db_path)
        if engine is None:
            # Multi-device host → serve through the dp×tp mesh; a 1-chip box
            # gets plain single-device jit. Same binary either way (the
            # MeshConfig dp=-1 default absorbs whatever is visible). The mesh
            # is built BEFORE the restore so checkpoint leaves land directly
            # in their sharded placement — no replicated staging copy on one
            # chip's HBM.
            import jax

            mesh = None
            if jax.device_count() > 1:
                from vilbert_multitask_tpu.parallel import build_mesh

                mesh = build_mesh(self.cfg.mesh)
            params = None
            restore = None
            if checkpoint_path is not None:
                from vilbert_multitask_tpu.checkpoint import (
                    restore_params_async,
                )

                # Serving restore casts to the engine's param-storage dtype
                # host-side (bf16 ships half the checkpoint bytes; "int8"
                # quantizes to per-channel pairs, ~¼ of f32); the on-disk
                # checkpoint stays the f32 master. Async: the restore's
                # disk/PCIe time overlaps the AOT cache prefetch below —
                # the two longest boot phases run concurrently.
                restore = restore_params_async(
                    checkpoint_path, mesh=mesh,
                    dtype=self.cfg.engine.param_dtype)
            # ONE AotCache shared by the whole pool: replicas compile the
            # same programs, so the first to miss populates the entry the
            # rest deserialize. prefetch() pulls the entry bytes off disk
            # while the checkpoint restore is still running.
            aot = None
            if self.cfg.engine.aot_cache_dir:
                from vilbert_multitask_tpu.engine import aotcache

                aot = aotcache.AotCache(
                    self.cfg.engine.aot_cache_dir,
                    aotcache.compile_fingerprint(
                        self.cfg, mesh=mesh,
                        heads=self.cfg.engine.fused_task_heads))
                self.boot_info["aot_prefetched"] = aot.prefetch()
            if restore is not None:
                params = restore.join()
            store = FeatureStore(feature_root)
            if live_extract:
                # Novel uploads with no precomputed .npy run through the
                # live detector (reference worker.py:59-223 capability;
                # detect/extractor.py). Random weights unless a converted
                # detector checkpoint is given.
                from vilbert_multitask_tpu.config import DetectorConfig
                from vilbert_multitask_tpu.detect import (
                    FallbackFeatureStore,
                    LiveFeatureExtractor,
                )

                det_params = None
                if detector_checkpoint is not None:
                    from vilbert_multitask_tpu.checkpoint import (
                        restore_params,
                    )

                    det_params = restore_params(detector_checkpoint)
                # The detector's fc6 width IS the trunk's region-feature
                # width — derive it, never assume the 2048 default.
                det_cfg = dataclasses.replace(
                    DetectorConfig(),
                    representation_size=self.cfg.model.v_feature_size)
                self.extractor = LiveFeatureExtractor(det_cfg,
                                                      params=det_params)
                store = FallbackFeatureStore(store, self.extractor,
                                             media_root=s.media_root)
                self.boot_info["live_extract"] = True
            t0 = time.perf_counter()
            with obs.span("serve.boot"):
                # pool_replicas engines share ONE param tree (engine 0
                # commits it to device / the mesh; the rest reuse the
                # committed arrays — random-init would otherwise give each
                # replica different weights) and one feature store. Each
                # keeps its own compile cache, input cache, and breaker.
                engines = []
                for i in range(max(1, s.pool_replicas)):
                    engines.append(InferenceEngine(
                        self.cfg, params=params, mesh=mesh,
                        feature_store=store, replica_id=f"r{i}",
                        aot_cache=aot))
                    if params is None:
                        params = engines[0].params
                engine = engines
                if restore is not None:
                    # Surface the overlapped restore in engine 0's
                    # boot-phase split alongside cache_load/compile/upload.
                    engines[0].book_boot_time("restore_s", restore.seconds)
            self.boot_info["engine_init_s"] = round(
                time.perf_counter() - t0, 1)
            if engine_factory is None:
                # Scale-out builds engines exactly like the boot replicas:
                # shared param tree, mesh, feature store, and AOT cache —
                # a new replica warm-boots from the same executables in
                # seconds instead of recompiling for minutes.
                def engine_factory(_params=params, _mesh=mesh,
                                   _store=store, _aot=aot):
                    return InferenceEngine(self.cfg, params=_params,
                                           mesh=_mesh, feature_store=_store,
                                           aot_cache=_aot)
        # The serving plane always programs against a ReplicaPool — with
        # one replica it degenerates to a thin facade over the engine; the
        # checkout/checkin seam, health states, and failover semantics stay
        # identical at every pool size. Callers may inject a prebuilt
        # engine, a list of engines, or an existing pool.
        if isinstance(engine, ReplicaPool):
            self.engine = engine
        else:
            engines = list(engine) if isinstance(engine, (list, tuple)) \
                else [engine]
            self.engine = ReplicaPool(engines, serving=s)
        self.boot_info["replicas"] = [r.name for r in self.engine.replicas]
        self._refresh_boot_phases()
        self.fingerprint = config_fingerprint(self.cfg)
        # Result cache + singleflight registry: a second table pair in the
        # SAME WAL sqlite as the jobs queue (one db to mount, one recovery
        # story). Keyed on (task, image identity, canonical question,
        # fingerprint:generation) — a rolling swap bumps model_gen so every
        # pre-swap entry turns stale atomically. Coalescing rides the cache
        # (followers attach to the leader's cache row), so coalesce without
        # the cache is unsupported by construction.
        self.model_gen = 0
        self.cache: Optional[ResultCache] = None
        if s.result_cache_enabled:
            self.cache = ResultCache(
                s.queue_db_path,
                fingerprint=self._cache_fingerprint(),
                max_rows=s.result_cache_max_rows,
                ttl_s=s.result_cache_ttl_s,
                lease_s=s.coalesce_lease_s)
        self.worker = ServeWorker(self.engine, self.queue, self.store,
                                  self.hub, s, cache=self.cache)
        # Live-health plane (obs/): the time-series store + sampler, the
        # SLO evaluator, and the flight recorder. Built here so /debug/slo
        # and /healthz see them from the first request; the sampler thread
        # and the recorder's global installation happen in start().
        # Placeholders so _build_slos's page hook can close over them; the
        # real instances are built after the fleet spine (shared db path).
        self.attrib: Optional[obs.CostAttributor] = None
        self.tracestore: Optional[obs.TraceStore] = None
        self.timeseries = obs.TimeSeriesStore(points=s.timeseries_points)
        self.slos = self._build_slos()
        self.sampler = obs.Sampler(self.timeseries, self._sample,
                                   cadence_s=s.sampler_cadence_s)
        # Closed-loop autoscaler (serve/autoscale.py): its control step
        # rides _sample() — the same no-new-threads deal as pool.probe().
        # Off by default; the knob block in ServingConfig documents the
        # policy.
        self.autoscaler: Optional[Autoscaler] = None
        if s.autoscale_enabled:
            self.autoscaler = Autoscaler(
                self.engine, s, slos=self.slos, queue=self.queue,
                engine_factory=engine_factory)
        # Fleet observability: this process's identity plus its handle on
        # the shared metrics spine (a WAL sqlite next to the queue db).
        # Every sampler tick flushes instruments/timeseries/spans/heartbeat
        # there; ?scope=fleet queries on any peer merge them back.
        self.identity = obs.process_identity("serve")
        self.fleet: Optional[obs.FleetSpine] = None
        if s.fleet_enabled:
            self.fleet = obs.FleetSpine(
                s.fleet_db_path or obs.default_spine_path(s.queue_db_path),
                self.identity,
                heartbeat_stale_s=s.fleet_heartbeat_stale_s,
                max_spans_per_ident=s.fleet_max_spans,
                spans_per_flush=s.fleet_spans_per_flush,
                timeseries_window_s=s.fleet_timeseries_window_s,
                timeseries=self.timeseries)
        # Cost-attribution plane: per-job stage/device-second records
        # (obs/attrib.py) feeding the durable tail-sampled trace store
        # (obs/tracestore.py) on the SAME sqlite file as the fleet spine —
        # one db to mount, and ?scope=fleet trace reads come for free.
        if s.attrib_enabled:
            self.tracestore = obs.TraceStore(
                s.fleet_db_path or obs.default_spine_path(s.queue_db_path),
                self.identity.ident,
                keep_top_k=s.tracestore_keep_top_k,
                sample_rate=s.tracestore_sample_rate,
                retention_s=s.tracestore_retention_s)
            self.attrib = obs.CostAttributor(on_finish=self._offer_trace)
        rec_dir = s.recorder_dir
        if rec_dir == "serve_state/postmortem":
            # Default follows the queue db (tests and the soak point that
            # at a tmpdir; bundles must land there too, not in CWD).
            rec_dir = os.path.join(
                os.path.dirname(s.queue_db_path) or "serve_state",
                "postmortem")
        self.recorder = obs.FlightRecorder(
            rec_dir, max_bundles=s.recorder_max_bundles,
            max_bytes=s.recorder_max_bytes, spans=s.recorder_spans,
            min_interval_s=s.recorder_min_interval_s,
            sources={
                "timeseries": self.timeseries.snapshot,
                "config_fingerprint": lambda: self.fingerprint,
                "boot_info": lambda: dict(self.boot_info),
                "identity": self.identity.as_dict,
                "fleet": lambda: (self.fleet.snapshot()
                                  if self.fleet is not None else {}),
            })
        self.api = ApiServer(
            self.queue, self.store, self.hub, s,
            metrics=self.worker.metrics, boot_info=self.boot_info,
            stats_fn=lambda: {"input_cache": self.engine.input_cache_stats},
            slos=self.slos, timeseries=self.timeseries,
            pool=self.engine, swap_fn=self.rolling_swap, fleet=self.fleet,
            attrib=self.attrib, tracestore=self.tracestore,
            cache=self.cache, autoscaler=self.autoscaler)
        self.ws = WebSocketBridge(self.hub, s.http_host, s.ws_port)
        self.http_port: Optional[int] = None  # actual bound port after start
        self._stop = threading.Event()
        self._worker_thread: Optional[threading.Thread] = None

    def _refresh_boot_phases(self) -> None:
        """Fold the engines' boot-phase split (restore_s / cache_load_s /
        compile_s / upload_s, engine/aotcache.py) into ``/healthz``'s boot
        section. Summed across the pool — warmup phases accumulate, so this
        runs again after :meth:`warm`. Tolerates injected test doubles."""
        phases: dict = {}
        for rep in getattr(self.engine, "replicas", []):
            times = getattr(rep.engine, "boot_times", None)
            if not times:
                continue
            for phase, seconds in dict(times).items():
                phases[phase] = round(phases.get(phase, 0.0) + seconds, 3)
        if phases:
            self.boot_info["boot_phases"] = phases

    # ------------------------------------------------------- live health
    def _build_slos(self) -> "obs.SloEvaluator":
        """The serving plane's three SLOs (targets in ServingConfig):
        availability, e2e latency vs. target, deadline-slack floor."""
        s = self.cfg.serving
        m = self.worker.metrics
        slos = [
            obs.availability_slo(
                "availability", m.latency, m.failure_events,
                error_budget=s.slo_availability_budget),
            obs.latency_slo(
                "e2e_latency", m.latency, target_ms=s.slo_e2e_target_ms,
                error_budget=s.slo_e2e_budget),
            obs.slack_floor_slo(
                "deadline_slack", obs.DEADLINE_SLACK,
                floor_ms=s.slo_slack_floor_ms,
                error_budget=s.slo_slack_budget),
        ]
        # One availability objective PER REPLICA, fed by the pool's
        # labelled dispatch histograms: a single sick replica burns its
        # own budget visibly instead of hiding inside the fleet average.
        pool = self.engine
        for rep in pool.replicas:
            def counts(window_s: float, _name=rep.name,
                       _ok=pool.dispatch_ms, _fail=pool.dispatch_fail):
                return (_ok.window_count(window_s, replica=_name),
                        _fail.window_count(window_s, replica=_name))
            slos.append(obs.Slo(
                f"replica_{rep.name}_availability",
                f"dispatches on replica {rep.name} succeed", counts,
                error_budget=s.slo_availability_budget))
        def on_page(name: str, report: dict) -> None:
            # Default recorder trigger, plus: the page's exemplar traces
            # get pinned so the store force-keeps their next offers even
            # when the tail sampler would have dropped them.
            obs.SloEvaluator._page_event(name, report)
            if self.tracestore is not None:
                self.tracestore.pin(report.get("exemplar_trace_ids", []))
        return obs.SloEvaluator(
            slos, fast_window_s=s.slo_fast_window_s,
            slow_window_s=s.slo_slow_window_s,
            warn_burn=s.slo_warn_burn, page_burn=s.slo_page_burn,
            on_page=on_page)

    def _offer_trace(self, cost: "obs.JobCost") -> None:
        """Attributor → store handoff (runs on the finishing worker
        thread, outside the attributor lock): the completed cost record
        plus its spans still in the local tracer ring."""
        store = self.tracestore
        if store is None:
            return
        store.offer(cost, obs.default_tracer().spans())

    def _sample(self) -> dict:
        """One sampler tick's worth of live signals. ``*_total`` keys get
        ``*_per_s`` rate series derived by the sampler (sheds/sec, qps)."""
        vals: dict = {}
        counts = self.queue.counts()
        for state in ("pending", "inflight", "dead"):
            vals[f"queue_{state}"] = float(counts.get(state, 0))
        vals["worker_inflight"] = float(self.worker.inflight_count())
        for key, v in obs.BREAKER_GAUGE.collect().items():
            vals[f"breaker_{key[0]}"] = float(v)
        vals["sheds_total"] = sum(obs.SHED_COUNTER.collect().values())
        m = self.worker.metrics
        vals["requests_total"] = float(
            sum(m.latency.series_counts().values()))
        vals["failures_total"] = float(m.failure_events.count())
        vals.update(self.engine.live_stats())
        # Thread-liveness reconciliation: republishes vmt_thread_alive
        # for every guarded loop, so a crash-guarded death (or a silent
        # one) is visible in /healthz within one sampler cadence.
        vals.update(obs.watchdog().probe())
        # Scheduler plane (empty dict while the legacy loop runs): ready
        # depth, adaptive window, and *_total dispatch counters.
        vals.update(self.worker.scheduler_stats())
        # Result-cache plane: row/follower depths plus the three cache
        # counters (the sampler derives hit/miss/coalesce rates from the
        # *_total keys — the zipf soak's gates read those).
        if self.cache is not None:
            vals.update(self.cache.stats())
            vals["result_cache_hits_total"] = sum(
                obs.RESULT_CACHE_HITS.collect().values())
            vals["result_cache_misses_total"] = sum(
                obs.RESULT_CACHE_MISSES.collect().values())
            vals["coalesced_submits_total"] = sum(
                obs.COALESCED_SUBMITS.collect().values())
        # Per-tenant queueing delay (publish→claim p50), the deficit
        # scheduler's user-facing effect: a tenant throttled below its
        # weighted share queues longer, and that shows up HERE before it
        # shows up as sheds. Label sets merge across tasks per tenant.
        by_tenant: Dict[str, list] = {}
        for key in obs.QUEUE_WAIT.series_counts():
            task, tenant = key
            by_tenant.setdefault(tenant, []).extend(
                obs.QUEUE_WAIT.samples(task=task, tenant=tenant))
        for tenant, samples in by_tenant.items():
            p50 = obs.percentile(samples, 50.0)
            if p50 is not None:
                vals[f"queue_wait_p50_ms_tenant_{tenant}"] = float(p50)
        # Burn-rate states ride the same cadence, so PAGE transitions trip
        # the recorder even when nobody is scraping /debug/slo.
        worst = self.slos.worst_state()
        vals["slo_worst"] = float(
            {"ok": 0, "warn": 1, "page": 2}.get(worst, 0))
        # Autoscaler control step: sensors read the instruments the lines
        # above just refreshed (live_stats ran pool.probe), actions land
        # on the pool inline — no thread of its own. Isolated failure
        # domain: a raising actuator must not cost the tick.
        if self.autoscaler is not None:
            try:
                vals.update(self.autoscaler.tick())
            except Exception:  # noqa: BLE001
                _AUTOSCALE_TICK_ERRORS.inc()
        # Publish this tick to the fleet spine (heartbeat + instrument
        # snapshots + timeseries deltas + fresh spans). Isolated failure
        # domain: a locked/corrupt spine db must not cost the LOCAL tick.
        if self.fleet is not None:
            try:
                self.fleet.flush({"phase": self.boot_info.get("phase"),
                                  "slo_worst": worst})
            except Exception:  # noqa: BLE001
                _FLEET_FLUSH_ERRORS.inc()
        # Trace-store flush rides the same tick, isolated the same way.
        if self.tracestore is not None:
            try:
                self.tracestore.flush()
            except Exception:  # noqa: BLE001
                _TRACESTORE_FLUSH_ERRORS.inc()
        return vals

    def warm(self) -> None:
        """Pre-compile every shape bucket (and the live detector, if
        enabled); timings land in ``/healthz``. Compile-at-request is
        debug-only everywhere in this binary — a first upload must never
        pay the detector JIT inside the worker thread."""
        prev_phase = self.boot_info.get("phase")
        self.boot_info["phase"] = "warming"
        t0 = time.perf_counter()
        with obs.span("serve.warmup",
                      buckets=list(self.cfg.engine.all_row_buckets())):
            self.engine.warmup()
            if self.extractor is not None:
                self.extractor.warmup()
                self.boot_info["detector_warm"] = True
        self.boot_info.update(
            warmup_s=round(time.perf_counter() - t0, 1),
            buckets=list(self.cfg.engine.all_row_buckets()),
            pallas=self.engine.pallas_enabled,
            kernel_fallback=self.engine.kernel_fallback,
        )
        self._refresh_boot_phases()
        # Warming before start() returns to "booting" (still not serving);
        # a live re-warm must not flip an already-ready replica out of the
        # load balancer.
        self.boot_info["phase"] = ("ready" if prev_phase == "ready"
                                   else "booting")

    def rolling_swap(self, checkpoint_path: Optional[str] = None,
                     params=None) -> dict:
        """Zero-downtime checkpoint swap across the replica pool.

        Loads the new tree once (host-side), then walks the pool's
        drain → load → ready sequence one replica at a time — at least one
        replica stays ready throughout (n >= 2), and since HTTP ingest only
        enqueues, no request observes the swap at all. Same-shape trees
        swap with ZERO recompiles (compiled programs take params as a call
        argument — engine.load_params). The restore casts to the engine's
        param_dtype, so an int8 deployment re-quantizes the incoming f32
        checkpoint here — swapped replicas serve the same storage mode they
        booted with, never a silently-widened tree."""
        if params is None:
            if checkpoint_path is None:
                raise ValueError("rolling_swap needs checkpoint_path or "
                                 "params")
            from vilbert_multitask_tpu.checkpoint import restore_params

            params = restore_params(checkpoint_path,
                                    mesh=self.engine.mesh,
                                    dtype=self.cfg.engine.param_dtype)
        t0 = time.perf_counter()
        obs.record_event("rolling_swap_start",
                         checkpoint=checkpoint_path or "<in-memory>")
        report = self.engine.rolling_swap(
            lambda eng: eng.load_params(params))
        report["total_s"] = round(time.perf_counter() - t0, 3)
        report["checkpoint"] = checkpoint_path or "<in-memory>"
        # The swap changed what the model computes: bump the generation so
        # the cache-key fingerprint rotates, and drop every entry minted
        # under the old generation in one transaction. A post-swap replay
        # of a pre-swap request is therefore a MISS (fresh forward pass),
        # never a stale hit. In-flight leaders keep their follower rows —
        # their old-generation result still fans out, it just isn't cached.
        self.model_gen += 1
        if self.cache is not None:
            dropped = self.cache.invalidate(self._cache_fingerprint())
            obs.RESULT_CACHE_INVALIDATIONS.inc(dropped)
            report["cache_invalidated"] = dropped
        self.boot_info["last_swap"] = report
        return report

    def _cache_fingerprint(self) -> str:
        """Cache-key config component: the static config fingerprint plus
        the rolling-swap generation. Both a config change (across restarts)
        and a live swap (within one process) rotate every key."""
        return f"{self.fingerprint}:g{self.model_gen}"

    def _run_worker(self) -> None:
        """Thread entry for the in-process worker. The crash guard lives
        HERE, not in ``run_forever``: remote deployments call
        ``run_forever`` synchronously from their own main thread and must
        see exceptions, while this daemon thread's only observer is the
        watchdog."""
        with obs.crash_guard("serve-worker"):
            self.worker.run_forever(stop_event=self._stop)

    def start(self, worker: bool = True) -> None:
        """Boot the tiers; ``worker=False`` serves HTTP/ws only (an external
        worker — serve/remote.py, or the chaos soak's scripted one — drains
        the queue instead)."""
        # Fleet-inventory identity: which build/config this replica is.
        import jax

        from vilbert_multitask_tpu import __version__

        obs.REGISTRY.gauge(
            "vmt_build_info",
            "Build/config identity labels (value is always 1).",
            labelnames=("version", "backend", "param_dtype",
                        "config_fingerprint"),
        ).set(1, version=__version__, backend=jax.default_backend(),
              param_dtype=self.cfg.engine.param_dtype,
              config_fingerprint=self.fingerprint)
        self.boot_info["config_fingerprint"] = self.fingerprint
        self.boot_info["identity"] = self.identity.as_dict()
        # Process-identity stamping: every exposition sample gains
        # instance/role labels (merged at render time, so instrument
        # schemas and observe calls are untouched), and every span gains
        # matching attrs — the fleet merge's join keys. stop() clears
        # both (the registry/tracer are process globals).
        obs.REGISTRY.set_default_labels(**self.identity.labels())
        obs.default_tracer().set_default_attrs(
            instance=self.identity.ident, role=self.identity.role)
        # The flight recorder goes live before any tier can trip it.
        obs.install_recorder(self.recorder)
        # Same discipline for cost attribution: the module-plane helper
        # sites in worker/scheduler become live before the first claim.
        if self.attrib is not None:
            obs.set_attributor(self.attrib)
        # Websocket first: /config must never advertise an unbound ws port
        # (the browser caches it and would reconnect to ws://host:0 forever).
        self.ws.start()
        self.api.ws_port = self.ws.bound_port
        self.http_port = self.api.start()
        # Replicas still 'booting' here were never warmed (--no-warmup /
        # test boots): admit them as ready, compile-at-request.
        self.engine.mark_ready()
        if worker:
            self._worker_thread = threading.Thread(
                target=self._run_worker,
                daemon=True, name="serve-worker")
            self._worker_thread.start()
        self.sampler.start()
        self.boot_info["phase"] = "ready"
        # First heartbeat immediately: peers must see this process in
        # ?scope=fleet without waiting out a sampler cadence.
        if self.fleet is not None:
            try:
                self.fleet.flush({"phase": "ready"})
            except Exception:  # noqa: BLE001
                _FLEET_FLUSH_ERRORS.inc()

    def stop(self) -> None:
        """Graceful drain: signal the worker to stop CLAIMING, give it
        ``drain_grace_s`` to finish jobs in hand, then release anything
        still claimed back to pending (terminal "requeued" push, no
        delivery attempt charged) before tearing the web tiers down."""
        # Snapshot the pre-drain state while the queues/inflight are still
        # interesting (a SIGTERM during an incident is the bundle you want).
        obs.record_event("drain", phase=self.boot_info.get("phase"),
                         inflight=self.worker.inflight_count())
        self.boot_info["phase"] = "draining"
        self._stop.set()
        if self._worker_thread:
            self._worker_thread.join(timeout=self.cfg.serving.drain_grace_s)
        # After the join (clean or timed out): anything still tracked as
        # in-flight goes back to the queue for the next worker. A clean
        # drain finds the set empty — at-least-once makes this idempotent.
        self.worker.abandon_inflight()
        self.api.stop()
        self.ws.stop()
        self.sampler.stop()
        # Withdraw from the fleet (heartbeat/instruments/timeseries rows;
        # spans stay stitchable) and un-stamp the process-global registry
        # and tracer — other apps in this process must not inherit a dead
        # incarnation's identity labels.
        if self.fleet is not None:
            try:
                self.fleet.retire()
            except Exception:  # noqa: BLE001 — teardown is best-effort
                _FLEET_FLUSH_ERRORS.inc()
        # Final trace-store flush (keeps buffered since the last tick must
        # survive the shutdown), then detach the module-plane attributor —
        # but only OUR OWN installation, like the recorder below.
        if self.tracestore is not None:
            try:
                self.tracestore.flush()
            except Exception:  # noqa: BLE001 — teardown is best-effort
                _TRACESTORE_FLUSH_ERRORS.inc()
        if self.attrib is not None and obs.get_attributor() is self.attrib:
            obs.set_attributor(None)
        obs.REGISTRY.set_default_labels()
        obs.default_tracer().set_default_attrs()
        # Uninstall only our own recorder (another app may have replaced
        # it); close() drains queued triggers and joins the writer thread.
        if obs.active_recorder() is self.recorder:
            obs.clear_recorder()
        else:
            self.recorder.close()


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description="ViLBERT multi-task TPU server")
    p.add_argument("--features", default="features",
                   help="precomputed region-feature directory (.npy/.vlfr)")
    p.add_argument("--checkpoint", default=None,
                   help="Orbax checkpoint dir (from checkpoint.convert_and_"
                        "save); omitting it serves RANDOM weights")
    p.add_argument("--no-warmup", action="store_true",
                   help="skip pre-compiling shape buckets at boot (first "
                        "live request per bucket then pays the compile — "
                        "directly against the p50 target; debug only)")
    p.add_argument("--live-extract", action="store_true",
                   help="run the JAX Faster R-CNN on uploads with no "
                        "precomputed features (detect/); random weights "
                        "unless --detector-checkpoint is given")
    p.add_argument("--detector-checkpoint", default=None,
                   help="Orbax checkpoint dir for the live detector")
    args = p.parse_args(argv)

    app = ServeApp(feature_root=args.features,
                   checkpoint_path=args.checkpoint,
                   live_extract=args.live_extract,
                   detector_checkpoint=args.detector_checkpoint)
    if args.checkpoint is None:
        print("WARNING: no --checkpoint given; serving randomly initialized "
              "weights (answers will be meaningless)")
    if not args.no_warmup:
        print("warming shape buckets...")
        app.warm()
        print(f"boot: {app.boot_info}")
    app.start()
    s = app.cfg.serving
    print(f"http://{s.http_host}:{app.http_port}  "
          f"ws://{s.http_host}:{app.ws.bound_port}  queue={s.queue_db_path}")
    # Graceful drain on SIGTERM (the orchestrator's stop signal): stop
    # claiming, finish in-flight within drain_grace_s, release the rest
    # with a terminal push, exit 0. Ctrl-C takes the same path.
    import signal

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda signum, frame: stop.set())
    try:
        stop.wait()
    except KeyboardInterrupt:
        pass
    print(f"draining (grace {s.drain_grace_s:.0f}s)...")
    app.stop()


if __name__ == "__main__":
    main()
