"""Replica pool: health-gated multi-replica serving behind the queue seam.

The pool owns N :class:`~vilbert_multitask_tpu.engine.runtime.InferenceEngine`
replicas (separate devices, mesh shards, or plain CPU threads in dryrun) and
presents the SAME surface the single-engine stack already programs against —
``ServeWorker(engine=pool)`` and ``app.engine = pool`` work unchanged.  What
changes is what happens underneath every dispatch:

- **checkout/checkin** — the one seam through which engine handles may leave
  the pool.  ``checkout()`` blocks for a ready replica (least-loaded among
  ready; a degraded replica is admitted only while its breaker is half-open,
  which IS the recovery probe), ``checkin(ok=...)`` returns the handle and
  feeds the replica's circuit breaker.  Holding a handle outside this seam
  is a replica-affinity leak (vmtlint VMT117).
- **health state machine** — ``booting → warming → ready`` at boot, then
  ``ready ⇄ degraded`` as the per-replica breaker opens/recovers,
  ``draining → warming → ready`` through a rolling swap, and ``dead`` when
  the replica is killed.  :meth:`probe` rides the obs sampler cadence (the
  pool spawns no threads of its own) and publishes ``vmt_replica_state``.
- **failover** — a replica-caused dispatch failure raises
  :class:`ReplicaFailover`; the worker answers with ``queue.release()`` (the
  abandon path: no attempt charged, job redelivered elsewhere).  Exactly one
  terminal per job survives a replica kill because streamed members keep
  their results and only unstreamed members fail over.  Poison jobs that
  kill every replica are bounded by the queue's ``delivery_count``
  quarantine, not by the pool.
- **rolling swap** — :meth:`rolling_swap` updates params one replica at a
  time: wait for another ready replica, drain this one, load, flip back to
  ready.  Zero downtime: the pool never passes through a zero-ready state
  (for n >= 2), and HTTP ingest never blocks on it anyway (enqueue-only).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from vilbert_multitask_tpu import obs
from vilbert_multitask_tpu.resilience import (
    BreakerBoard,
    DeadlineExceeded,
    ReplicaKilled,
)

__all__ = [
    "STATE_BOOTING", "STATE_WARMING", "STATE_READY", "STATE_DEGRADED",
    "STATE_DRAINING", "STATE_DEAD",
    "NoReadyReplica", "ReplicaFailover", "Replica", "ReplicaPool",
]

# Health states, with the gauge codes `vmt_replica_state` publishes.
STATE_BOOTING = "booting"
STATE_WARMING = "warming"
STATE_READY = "ready"
STATE_DEGRADED = "degraded"
STATE_DRAINING = "draining"
STATE_DEAD = "dead"

STATE_CODES: Dict[str, int] = {
    STATE_BOOTING: 0, STATE_WARMING: 1, STATE_READY: 2,
    STATE_DEGRADED: 3, STATE_DRAINING: 4, STATE_DEAD: 5,
}


class NoReadyReplica(RuntimeError):
    """checkout() timed out with no replica admitting work.

    Transient by construction (replicas recover via half-open probes or a
    swap completes) — callers treat it like a replica failure: release the
    job and let redelivery find a healthier moment.
    """


class ReplicaFailover(RuntimeError):
    """A dispatch failed for replica-local reasons; the job must move.

    Carries the replica name for the ``requeued`` push-frame provenance
    stamp.  The worker's answer is ``queue.release()`` — redelivery without
    charging an attempt — because the JOB is presumed innocent until its
    ``delivery_count`` says otherwise (poison quarantine lives in the
    queue, not here).
    """

    def __init__(self, message: str, replica: str = "?"):
        super().__init__(message)
        self.replica = replica


class Replica:
    """One engine plus the pool-side health bookkeeping around it."""

    def __init__(self, name: str, engine, breaker):
        self.name = name
        self.engine = engine
        self.breaker = breaker
        self.state = STATE_BOOTING
        self.inflight = 0
        self.killed = False
        self.dispatches = 0       # checkins with ok=True
        self.failures = 0         # checkins with ok=False
        self.failovers = 0        # jobs this replica bounced via failover
        self.swaps = 0            # rolling param swaps survived
        self.last_error = ""

    def snapshot(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "state": self.state,
            "breaker": self.breaker.state,
            "inflight": self.inflight,
            "dispatches": self.dispatches,
            "failures": self.failures,
            "failovers": self.failovers,
            "swaps": self.swaps,
            "last_error": self.last_error,
        }


class ReplicaPool:
    """N engines behind the single-engine facade the serve plane expects.

    Host-side work that has nothing to do with device placement —
    tokenisation (:meth:`prepare`/:meth:`prepare_from_store`), chunk
    planning, config access — delegates to replica 0; every engine shares
    the config/tokenizer/store, so any replica would answer identically.
    Device dispatch (:meth:`run`/:meth:`run_many`) goes through
    checkout/checkin and may land on any ready replica.
    """

    def __init__(self, engines: Sequence[Any], serving=None,
                 clock: Callable[[], float] = time.monotonic):
        if not engines:
            raise ValueError("ReplicaPool needs at least one engine")
        self._serving = serving or engines[0].cfg.serving
        self._clock = clock
        self._board = BreakerBoard(
            "replica",
            failure_threshold=self._serving.pool_breaker_failure_threshold,
            window_s=self._serving.pool_breaker_window_s,
            reset_timeout_s=self._serving.pool_breaker_reset_timeout_s,
        )
        self.replicas: List[Replica] = [
            self._make_replica(i, eng) for i, eng in enumerate(engines)
        ]
        self._cond = threading.Condition()
        self._swap_lock = threading.Lock()
        # Per-replica dispatch outcome histograms feed the per-replica
        # availability SLOs (window_count with the replica label).
        self.dispatch_ms = obs.REGISTRY.histogram(
            "vmt_replica_dispatch_ms",
            "Successful pool dispatches per replica (ms).",
            labelnames=("replica",))
        self.dispatch_fail = obs.REGISTRY.histogram(
            "vmt_replica_dispatch_failures",
            "Failed pool dispatches per replica (for availability SLOs).",
            labelnames=("replica",))
        for rep in self.replicas:
            obs.REPLICA_STATE.set(STATE_CODES[rep.state], replica=rep.name)

    def _make_replica(self, i: int, eng) -> Replica:
        name = getattr(eng, "replica_id", None) or f"r{i}"
        if getattr(eng, "replica_id", None) is None:
            try:
                eng.replica_id = name
            except AttributeError:
                pass
        return Replica(name, eng, self._board.get(name))

    # ------------------------------------------------------------------
    # Engine facade: host-side delegation to replica 0.

    @property
    def _host(self):
        return self.replicas[0].engine

    @property
    def cfg(self):
        return self._host.cfg

    @property
    def mesh(self):
        return self._host.mesh

    @property
    def pallas_enabled(self) -> bool:
        return bool(getattr(self._host, "pallas_enabled", False))

    @property
    def kernel_fallback(self) -> bool:
        return bool(getattr(self._host, "kernel_fallback", False))

    @property
    def stage_times(self):
        return self._host.stage_times

    def prepare(self, *args, **kwargs):
        return self._host.prepare(*args, **kwargs)

    def prepare_from_store(self, *args, **kwargs):
        return self._host.prepare_from_store(*args, **kwargs)

    def chunk_plan(self, *args, **kwargs):
        return self._host.chunk_plan(*args, **kwargs)

    def decode(self, *args, **kwargs):
        return self._host.decode(*args, **kwargs)

    @property
    def input_cache_stats(self) -> Dict[str, int]:
        return self._host.input_cache_stats

    # ------------------------------------------------------------------
    # Boot.

    def warmup(self, buckets=None, parallel=None) -> None:
        """Warm every replica, walking each through booting→warming→ready.

        Each replica first tries the AOT boot-from-cache path
        (:meth:`_boot_from_cache`): on a warm cache every program
        deserializes and warmup is SKIPPED — replicas come up in seconds.
        Otherwise serial warmup, as before: with the persistent
        compilation cache on, replica 1..n-1 hit the cache replica 0
        populated, so serial warmup costs ~one compile total, and the pool
        becomes partially available as soon as the first replica flips
        ready.
        """
        for rep in self.replicas:
            if rep.state == STATE_DEAD:
                continue
            self._set_state(rep, STATE_WARMING)
            try:
                if not self._boot_from_cache(rep, buckets):
                    rep.engine.warmup(buckets=buckets, parallel=parallel)
            except Exception as e:  # noqa: BLE001 — a bad replica must not
                rep.last_error = repr(e)  # sink the whole boot.
                self._set_state(rep, STATE_DEAD)
                obs.record_event("replica_boot_failed", replica=rep.name,
                                 error=repr(e))
                continue
            self._set_state(rep, STATE_READY)

    def _boot_from_cache(self, rep: Replica, buckets=None) -> bool:
        """Try the engine's AOT warm-boot path; True means every warmup
        program deserialized from the executable cache and warmup can be
        skipped.  Soft: engines without the capability (test doubles,
        cache off) or any loader failure → False → plain warmup."""
        boot = getattr(rep.engine, "boot_from_cache", None)
        if boot is None:
            return False
        try:
            ok = bool(boot(buckets=buckets))
        except Exception as e:  # noqa: BLE001 — cache trouble must never
            obs.record_event(       # be worse than a cold boot.
                "replica_cache_boot_failed", replica=rep.name,
                error=repr(e))
            return False
        if ok:
            obs.record_event("replica_boot_from_cache", replica=rep.name)
        return ok

    def add_replica(self, engine, warm: bool = True) -> Replica:
        """Scale-out: attach one more engine to the live pool (the
        autoscaler's actuator, ROADMAP item 2).  The new replica boots
        from the AOT cache when it can — seconds, not minutes — and only
        flips ready once warm; in-flight traffic on existing replicas is
        untouched.  With ``warm=False`` the replica goes straight to
        ready and pays compiles on first dispatch (the ``--no-warmup``
        contract)."""
        with self._cond:
            names = {r.name for r in self.replicas}
            i = len(self.replicas)
            while f"r{i}" in names:
                i += 1
        rep = self._make_replica(i, engine)
        with self._cond:
            self.replicas.append(rep)
        obs.REPLICA_STATE.set(STATE_CODES[rep.state], replica=rep.name)
        if not warm:
            self._set_state(rep, STATE_READY)
            return rep
        self._set_state(rep, STATE_WARMING)
        try:
            if not self._boot_from_cache(rep, None):
                rep.engine.warmup()
        except Exception as e:  # noqa: BLE001 — same containment as warmup()
            rep.last_error = repr(e)
            self._set_state(rep, STATE_DEAD)
            obs.record_event("replica_boot_failed", replica=rep.name,
                             error=repr(e))
            return rep
        self._set_state(rep, STATE_READY)
        return rep

    def mark_ready(self) -> None:
        """No-warmup boot path: flip still-booting replicas straight to
        ready (the first live request per bucket then pays the compile —
        same debug-only contract as ``--no-warmup``)."""
        with self._cond:
            for rep in self.replicas:
                if rep.state == STATE_BOOTING:
                    self._set_state_locked(rep, STATE_READY)
            self._cond.notify_all()

    # ------------------------------------------------------------------
    # The checkout/checkin seam.

    def _admissible(self, rep: Replica) -> bool:
        if rep.killed or rep.inflight >= \
                self._serving.pool_max_inflight_per_replica:
            return False
        if rep.state == STATE_READY:
            return True
        # A degraded replica takes work only while its breaker is probing
        # (half-open) — that single dispatch IS the recovery probe.
        return rep.state == STATE_DEGRADED and rep.breaker.state == "half_open"

    def checkout(self, timeout_s: Optional[float] = None) -> Replica:
        """Block for the least-loaded admissible replica.

        Raises :class:`NoReadyReplica` on timeout.  Engine handles obtained
        here must return through :meth:`checkin` in the same function
        (vmtlint VMT117 enforces this in serve/).
        """
        if timeout_s is None:
            timeout_s = self._serving.pool_checkout_timeout_s
        deadline = self._clock() + timeout_s
        with self._cond:
            while True:
                ready = [r for r in self.replicas if self._admissible(r)]
                if ready:
                    rep = min(ready, key=lambda r: (r.inflight, r.dispatches))
                    rep.inflight += 1
                    return rep
                remaining = deadline - self._clock()
                if remaining <= 0 or not self._cond.wait(timeout=remaining):
                    raise NoReadyReplica(
                        f"no ready replica within {timeout_s:.1f}s "
                        f"(states: {[r.state for r in self.replicas]})")

    def checkin(self, rep: Replica, ok: bool = True,
                error: Optional[BaseException] = None,
                elapsed_ms: float = 0.0) -> None:
        """Return a checked-out replica and feed its breaker."""
        if ok:
            rep.breaker.record_success()
        else:
            rep.breaker.record_failure()
        with self._cond:
            rep.inflight = max(0, rep.inflight - 1)
            if ok:
                rep.dispatches += 1
                self.dispatch_ms.observe(elapsed_ms, replica=rep.name)
                if rep.state == STATE_DEGRADED:
                    # Successful half-open probe: breaker closed, recover.
                    self._set_state_locked(rep, STATE_READY)
            else:
                rep.failures += 1
                rep.last_error = repr(error) if error is not None else ""
                self.dispatch_fail.observe(elapsed_ms, replica=rep.name)
                if (isinstance(error, ReplicaKilled) or rep.killed
                        or getattr(rep.engine, "killed", False)):
                    rep.killed = True
                    self._set_state_locked(rep, STATE_DEAD)
                elif rep.breaker.state != "closed" and \
                        rep.state in (STATE_READY, STATE_DEGRADED):
                    self._set_state_locked(rep, STATE_DEGRADED)
            self._cond.notify_all()

    # ------------------------------------------------------------------
    # Dispatch: the facade the legacy (non-scheduler) worker path uses.

    def run(self, req, **kwargs):
        rep = self.checkout()
        t0 = time.perf_counter()
        try:
            out = rep.engine.run(req, **kwargs)
        except DeadlineExceeded:
            # The JOB ran out of budget — the replica is fine.
            self.checkin(rep, ok=True,
                         elapsed_ms=(time.perf_counter() - t0) * 1e3)
            raise
        except Exception as e:  # noqa: BLE001 — classified below
            self.checkin(rep, ok=False, error=e,
                         elapsed_ms=(time.perf_counter() - t0) * 1e3)
            rep.failovers += 1
            raise ReplicaFailover(
                f"replica {rep.name} failed mid-dispatch: {e!r}",
                replica=rep.name) from e
        self.checkin(rep, ok=True,
                     elapsed_ms=(time.perf_counter() - t0) * 1e3)
        return out

    def run_many(self, reqs, *, on_result=None, **kwargs):
        rep = self.checkout()
        t0 = time.perf_counter()
        try:
            out = rep.engine.run_many(reqs, on_result=on_result, **kwargs)
        except DeadlineExceeded:
            self.checkin(rep, ok=True,
                         elapsed_ms=(time.perf_counter() - t0) * 1e3)
            raise
        except Exception as e:  # noqa: BLE001 — classified below
            self.checkin(rep, ok=False, error=e,
                         elapsed_ms=(time.perf_counter() - t0) * 1e3)
            rep.failovers += 1
            raise ReplicaFailover(
                f"replica {rep.name} failed mid-batch: {e!r}",
                replica=rep.name) from e
        self.checkin(rep, ok=True,
                     elapsed_ms=(time.perf_counter() - t0) * 1e3)
        return out

    # ------------------------------------------------------------------
    # Health: probe rides the obs sampler; kill simulates silent death.

    def probe(self) -> Dict[str, float]:
        """One health sweep: reconcile states, publish gauges, sample.

        Called from the app's sampler tick (and from :meth:`live_stats`),
        so a dead replica is visible in /healthz within one sampler
        cadence without the pool owning a thread.
        """
        sample: Dict[str, float] = {}
        with self._cond:
            for rep in self.replicas:
                if rep.killed or getattr(rep.engine, "killed", False):
                    rep.killed = True
                    if rep.state != STATE_DEAD:
                        self._set_state_locked(rep, STATE_DEAD)
                elif rep.state == STATE_READY and \
                        rep.breaker.state == "open":
                    self._set_state_locked(rep, STATE_DEGRADED)
                obs.REPLICA_STATE.set(STATE_CODES[rep.state],
                                      replica=rep.name)
                sample[f"replica_{rep.name}_state"] = \
                    float(STATE_CODES[rep.state])
                sample[f"replica_{rep.name}_inflight"] = float(rep.inflight)
                sample[f"replica_{rep.name}_dispatches_total"] = \
                    float(rep.dispatches)
                sample[f"replica_{rep.name}_failovers_total"] = \
                    float(rep.failovers)
            self._cond.notify_all()
        sample["pool_ready_replicas"] = float(self.ready_count())
        sample["pool_dead_replicas"] = float(
            sum(1 for r in self.replicas if r.state == STATE_DEAD))
        sample["pool_failovers_total"] = float(
            sum(r.failovers for r in self.replicas))
        return sample

    def kill(self, name: str) -> Replica:
        """Chaos hook: mark a replica dead-but-silent.

        Sets the engine's ``killed`` flag so the NEXT forward raises
        :class:`ReplicaKilled` mid-batch — the pool discovers the death
        through dispatch failure or the next probe, exactly like a real
        silent hardware loss.  The state flip happens there, not here.
        """
        rep = self._by_name(name)
        try:
            rep.engine.killed = True
        except AttributeError:
            rep.killed = True  # engines without the flag die loudly
        obs.record_event("replica_kill", replica=name)
        return rep

    # ------------------------------------------------------------------
    # Rolling checkpoint swap.

    def rolling_swap(self, load_fn: Callable[[Any], None],
                     drain_timeout_s: Optional[float] = None
                     ) -> Dict[str, Any]:
        """Update every live replica's params with zero downtime.

        Per replica: wait for another live replica to be ready (so the
        pool never passes through zero-ready, n >= 2), stop admitting work
        (``draining``), wait out the in-flight dispatch, load
        (``warming``), flip back to ``ready``.  ``load_fn(engine)`` does
        the actual load — typically ``engine.load_params(new_tree)``,
        which is recompile-free for same-shape trees and re-applies the
        engine's param-storage cast (an int8 engine re-quantizes an
        incoming f32 tree; an already-quantized tree passes through).
        """
        if drain_timeout_s is None:
            drain_timeout_s = self._serving.pool_swap_drain_timeout_s
        report: Dict[str, Any] = {"replicas": [], "skipped": [],
                                  "min_ready_seen": len(self.replicas)}

        def note_ready() -> None:
            report["min_ready_seen"] = min(report["min_ready_seen"],
                                           self.ready_count())

        with self._swap_lock:
            for rep in list(self.replicas):
                if rep.state == STATE_DEAD:
                    report["skipped"].append(rep.name)
                    continue
                others = [r for r in self.replicas
                          if r is not rep and r.state != STATE_DEAD]
                with self._cond:
                    if others:
                        # Zero-downtime invariant: never drain the last
                        # ready replica.
                        self._wait_locked(
                            lambda: any(r.state == STATE_READY
                                        for r in others),
                            drain_timeout_s,
                            f"no other replica became ready to cover "
                            f"{rep.name}'s swap")
                    self._set_state_locked(rep, STATE_DRAINING)
                    note_ready()
                    self._wait_locked(lambda: rep.inflight == 0,
                                      drain_timeout_s,
                                      f"{rep.name} did not drain")
                    self._set_state_locked(rep, STATE_WARMING)
                note_ready()
                t0 = time.perf_counter()
                try:
                    load_fn(rep.engine)
                except Exception as e:  # noqa: BLE001 — bad checkpoint must
                    rep.last_error = repr(e)  # not take the replica down
                    self._set_state(rep, STATE_DEGRADED)  # with it.
                    obs.record_event("replica_swap_failed", replica=rep.name,
                                     error=repr(e))
                    raise
                rep.swaps += 1
                # A same-shape load_params keeps every compiled program;
                # but if the swap handed this replica a cold engine (no
                # compiled programs — e.g. a config-bumped rebuild), pull
                # its executables from the AOT cache before flipping ready
                # so the first post-swap dispatch doesn't pay a compile.
                try:
                    cold = not rep.engine.live_stats().get(
                        "engine_compiled_programs", 0.0)
                except Exception:  # noqa: BLE001 — doubles without
                    cold = False   # live_stats() can't be cold-detected.
                if cold:
                    self._boot_from_cache(rep, None)
                self._set_state(rep, STATE_READY)
                note_ready()
                obs.record_event("replica_swap", replica=rep.name,
                                 load_s=round(time.perf_counter() - t0, 3))
                report["replicas"].append(
                    {"name": rep.name,
                     "load_s": round(time.perf_counter() - t0, 3)})
        return report

    # ------------------------------------------------------------------
    # Scale-in: drain-then-remove (the autoscaler's shrink actuator).

    def retire_replica(self, name: Optional[str] = None,
                       drain_timeout_s: Optional[float] = None
                       ) -> Dict[str, Any]:
        """Drain one replica and REMOVE it from the pool — the inverse of
        :meth:`add_replica`.

        Reuses the rolling-swap DRAINING machinery: the victim stops
        admitting work, the in-flight dispatches finish, then the replica
        leaves ``self.replicas`` and its ``vmt_replica_state`` series is
        withdrawn — a retired replica must not haunt /healthz or fleet
        views as a ghost. Unnamed, the least-loaded READY replica is
        picked (same ordering as checkout, inverted). Refuses to shrink
        the live pool below ``autoscale_min_replicas`` or to retire the
        last READY replica; a drain timeout puts the victim back into
        rotation rather than stranding it DRAINING.
        """
        if drain_timeout_s is None:
            drain_timeout_s = self._serving.pool_swap_drain_timeout_s
        min_live = max(1, int(self._serving.autoscale_min_replicas))
        # Serialize against rolling swaps: both walk replicas through
        # DRAINING, and a swap iterating a list the retire just mutated
        # is the kind of race this lock exists for.
        if not self._swap_lock.acquire(timeout=drain_timeout_s):
            raise TimeoutError(
                f"retire stalled: a rolling swap held the pool for "
                f"{drain_timeout_s:.1f}s")
        t0 = time.perf_counter()
        try:
            with self._cond:
                if name is None:
                    ready = [r for r in self.replicas
                             if r.state == STATE_READY]
                    if not ready:
                        raise ValueError("no READY replica to retire")
                    rep = min(ready,
                              key=lambda r: (r.inflight, r.dispatches))
                else:
                    rep = self._by_name(name)
                live = sum(1 for r in self.replicas
                           if r.state != STATE_DEAD)
                if rep.state != STATE_DEAD and live <= min_live:
                    raise ValueError(
                        f"refusing to retire {rep.name}: {live} live "
                        f"replica(s) <= autoscale_min_replicas="
                        f"{min_live}")
                if rep.state == STATE_READY and not any(
                        r.state == STATE_READY for r in self.replicas
                        if r is not rep):
                    raise ValueError(
                        f"refusing to retire {rep.name}: it is the "
                        f"last READY replica")
                self._set_state_locked(rep, STATE_DRAINING)
                self._cond.notify_all()
                try:
                    self._wait_locked(
                        lambda: rep.inflight == 0, drain_timeout_s,
                        f"{rep.name} did not drain for retirement")
                except TimeoutError:
                    # Abandon the retirement, not the replica: back into
                    # rotation rather than stuck DRAINING forever.
                    self._set_state_locked(rep, STATE_READY)
                    self._cond.notify_all()
                    raise
                self.replicas.remove(rep)
                self._cond.notify_all()
        finally:
            self._swap_lock.release()
        # Withdraw the state series AFTER removal — probe() iterates
        # self.replicas, so it can no longer re-publish the ghost.
        obs.REPLICA_STATE.remove(replica=rep.name)
        drain_s = round(time.perf_counter() - t0, 3)
        obs.record_event("replica_retired", replica=rep.name,
                         drain_s=drain_s, dispatches=rep.dispatches)
        return {"name": rep.name, "drain_s": drain_s,
                "dispatches": rep.dispatches, "state": rep.state}

    # ------------------------------------------------------------------
    # Introspection (for /healthz, the sampler, and tests).

    def ready_count(self) -> int:
        return sum(1 for r in self.replicas if r.state == STATE_READY)

    def replicas_info(self) -> List[Dict[str, Any]]:
        with self._cond:
            return [r.snapshot() for r in self.replicas]

    def live_stats(self) -> Dict[str, float]:
        """Per-replica engine stats prefixed by name, plus pool health.

        This is what the sampler tick collects (the app passes
        ``engine.live_stats`` as the stats_fn), so probing piggybacks on
        the existing cadence.
        """
        out: Dict[str, float] = {}
        for i, rep in enumerate(self.replicas):
            try:
                stats = rep.engine.live_stats()
            except Exception:  # noqa: BLE001 — a dying replica's stats are
                stats = {}     # not worth failing the sampler tick over.
            for k, v in stats.items():
                out[f"{rep.name}_{k}"] = v
            if i == 0:
                # Replica 0's raw keys stay un-prefixed too so existing
                # dashboards (and tests) keyed on e.g. ``engine_compiled``
                # keep working.
                out.update(stats)
        out.update(self.probe())
        return out

    # ------------------------------------------------------------------
    # Internals.

    def _by_name(self, name: str) -> Replica:
        for r in self.replicas:
            if r.name == name:
                return r
        raise KeyError(f"no replica named {name!r}")

    def _set_state(self, rep: Replica, state: str) -> None:
        with self._cond:
            self._set_state_locked(rep, state)
            self._cond.notify_all()

    def _set_state_locked(self, rep: Replica, state: str) -> None:
        prev, rep.state = rep.state, state
        obs.REPLICA_STATE.set(STATE_CODES[state], replica=rep.name)
        if prev != state:
            obs.record_event("replica_state", replica=rep.name,
                             prev=prev, state=state)

    def _wait_locked(self, pred: Callable[[], bool], timeout_s: float,
                     what: str) -> None:
        deadline = self._clock() + timeout_s
        while not pred():
            remaining = deadline - self._clock()
            if remaining <= 0 or not self._cond.wait(timeout=remaining):
                if not pred():
                    raise TimeoutError(
                        f"rolling swap stalled: {what} "
                        f"within {timeout_s:.1f}s")
