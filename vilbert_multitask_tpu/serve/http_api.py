"""HTTP tier: job submission, task metadata, uploads, media.

Reference capability: the Django views + URL map (reference demo/urls.py:7-11,
demo/views.py):

- ``POST /``                      submit a job {socket_id, task_id, question,
                                  image_list[]} → enqueue (views.py:19-42)
- ``GET  /get_task_details/<id>/`` task metadata JSON (views.py:45-61)
- ``GET  /get_demo_images/``       random sample of demo images (views.py:64-81)
- ``POST /upload_image/``          multipart upload, uuid-renamed into media
                                   (views.py:84-106) → {"file_paths": [...]}
- ``GET  /media/...``              media serving (vilbert_multitask/urls.py:27-31)

Redesign: stdlib ``ThreadingHTTPServer`` + JSON bodies (the browser-facing
HTML shell is not part of the framework contract; the API is). Submission
returns the queued job id — the answer itself still arrives over the
websocket, preserving the reference's fire-and-forget shape (SURVEY.md §3.1).
"""

from __future__ import annotations

import email
import email.policy
import json
import mimetypes
import os
import random
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional, Tuple

from vilbert_multitask_tpu import obs
from vilbert_multitask_tpu.config import ServingConfig, TASK_REGISTRY
from vilbert_multitask_tpu.resilience import AdmissionController, Deadline
from vilbert_multitask_tpu.serve.db import ResultStore
from vilbert_multitask_tpu.serve.push import PushHub, log_to_terminal
from vilbert_multitask_tpu.serve.queue import DurableQueue, make_job_message
from vilbert_multitask_tpu.serve.resultcache import ResultCache, cache_key


class ApiServer:
    def __init__(
        self,
        queue: DurableQueue,
        store: ResultStore,
        hub: PushHub,
        serving: Optional[ServingConfig] = None,
        metrics=None,
        boot_info: Optional[Dict[str, Any]] = None,
        stats_fn=None,
        slos=None,
        timeseries=None,
        pool=None,
        swap_fn=None,
        fleet=None,
        attrib=None,
        tracestore=None,
        cache: Optional[ResultCache] = None,
        autoscaler=None,
    ):
        self.queue = queue
        self.store = store
        self.hub = hub
        self.serving = serving or ServingConfig()
        self.metrics = metrics
        # Live-health wiring (ServeApp): the SLO evaluator behind
        # /debug/slo and the 503-on-PAGE readiness rule, and the sampler's
        # time-series store behind /debug/timeseries.
        self.slos = slos
        self.timeseries = timeseries
        # Live reference filled in by ServeApp as boot stages finish
        # (engine init / warmup timings, kernel path) — surfaced in /healthz.
        self.boot_info = boot_info if boot_info is not None else {}
        # Optional live-stats callable merged into /metrics (ServeApp wires
        # the engine's device input-cache counters through this).
        self.stats_fn = stats_fn
        # Replica pool (ServeApp wires its ReplicaPool through): /healthz
        # reports per-replica states and readiness requires >=1 ready
        # replica; POST /admin/swap triggers swap_fn (a zero-downtime
        # rolling checkpoint swap).
        self.pool = pool
        self.swap_fn = swap_fn
        # Fleet spine (obs/fleet.py, ServeApp wires it): ?scope=fleet on
        # /metrics, /debug/timeseries, /healthz merges every live peer
        # sharing the spine db, and /debug/trace?trace_id= stitches one
        # timeline across processes.
        self.fleet = fleet
        # Cost-attribution plane (obs/attrib.py + obs/tracestore.py,
        # ServeApp wires both): /debug/costs windows the attributor's
        # completed ring, /debug/traces lists the durable tail-sampled
        # store, /debug/autopsy renders one trace's stage waterfall, and
        # /debug/trace?trace_id= falls back to the store when the span has
        # aged out of every live ring.
        self.attrib = attrib
        self.tracestore = tracestore
        # Durable result cache + singleflight registry (ServeApp wires
        # it; serve/resultcache.py). POST / consults it before any queue
        # publish: hits answer straight from sqlite (no queue, no TPU),
        # identical in-flight submits coalesce onto one leader job.
        self.cache = cache
        # Closed-loop autoscaler (serve/autoscale.py, ServeApp wires it):
        # /debug/autoscale serves the last-N decision records, /healthz
        # pairs its target replica count with the pool's actual.
        self.autoscaler = autoscaler
        # Actual websocket port for the browser client; ServeApp overwrites
        # this after the bridge binds (ws_port=0 picks a free port in tests).
        self.ws_port: int = self.serving.ws_port
        # Shed-before-enqueue (resilience/): overloaded submits get a fast
        # 429 + Retry-After instead of joining a backlog they'd time out in.
        self.admission = AdmissionController(
            max_queue_depth=self.serving.admission_max_queue_depth,
            max_queue_age_s=self.serving.admission_max_queue_age_s,
            retry_after_s=self.serving.admission_retry_after_s,
        )
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------- handlers
    def submit_job(self, payload: Dict[str, Any]) -> Tuple[int, Dict[str, Any]]:
        # Trace root: the id minted here rides in the queue job body and is
        # re-entered by the worker, correlating one request's spans across
        # the HTTP handler / worker thread boundary.
        trace_id = obs.new_trace_id()
        with obs.trace_scope(trace_id), obs.span("http.submit") as sp:
            code, body = self._submit_job(payload, trace_id, sp)
        if code == 200:
            body["trace_id"] = trace_id
        return code, body

    def _submit_job(self, payload: Dict[str, Any], trace_id: str,
                    sp) -> Tuple[int, Dict[str, Any]]:
        try:
            task_id = int(payload["task_id"])
            socket_id = str(payload.get("socket_id", ""))
            question = str(payload.get("question", ""))
            images = list(payload.get("image_list", []))
        except (KeyError, TypeError, ValueError):
            return 400, {"error": "need task_id, socket_id, question, image_list"}
        decision = self._admission_decision()
        if not decision.admitted:
            return 429, {
                "error": "overloaded; retry later",
                "reason": decision.reason,
                "retry_after_s": decision.retry_after_s,
            }
        try:
            budget = payload.get("deadline_s", self.serving.default_deadline_s)
            budget = None if budget is None else float(budget)
        except (TypeError, ValueError):
            return 400, {"error": "deadline_s must be a number"}
        spec = TASK_REGISTRY.get(task_id)
        if spec is None:
            return 400, {"error": f"unknown task_id {task_id}"}
        try:
            spec.validate_num_images(len(images))
        except ValueError as e:
            return 400, {"error": str(e)}
        if self.serving.lowercase_questions:
            question = question.lower()  # reference views.py:27
        log_to_terminal(self.hub, socket_id,
                        {"info": f"Starting {spec.name} job..."})
        collect = payload.get("collect_attention", False)
        # Optional caller-declared tenant for cost attribution
        # (vmt_device_seconds_total{task,tenant}); absent → "anon".
        tenant = str(payload.get("tenant", "") or "") or None
        # --- duplicate-traffic tier (serve/resultcache.py) ---
        # One atomic claim decides the submit's fate: a durable HIT is
        # answered right here (no queue, no TPU), an identical in-flight
        # submit ATTACHES as a follower of the one leader job (the
        # leader's terminal fans out to it), and everything else LEADS —
        # publishes the one real job with the key stamped on the body.
        # Attention-collecting jobs bypass the tier: their payload
        # (persisted per-request .npz maps) is per-submit state.
        key = None
        if self.cache is not None and not collect:
            key = cache_key(task_id, images, question,
                            self.cache.fingerprint)
            verdict_c, value = self.cache.admit(
                key, socket_id=socket_id, trace_id=trace_id,
                tenant=tenant, coalesce=self.serving.coalesce_enabled)
            if verdict_c == "hit":
                return self._serve_cache_hit(spec, socket_id, trace_id,
                                             tenant, value, sp)
            if verdict_c == "attach":
                obs.COALESCED_SUBMITS.inc()
                # The follower's cost record opens here; the leader's
                # terminal fan-out closes it with only a push charge —
                # its forward is the leader's, shared.
                obs.job_begin(trace_id, job_id=value,
                              task=str(task_id), tenant=tenant or "anon")
                sp.set(task_id=task_id, coalesced=True)
                return 200, {"job_id": value, "task": spec.name,
                             "cache": "coalesced"}
            obs.RESULT_CACHE_MISSES.inc()
        try:
            job_id = self.queue.publish(
                make_job_message(
                    images, question, task_id, socket_id,
                    # "full" passes through (complete per-head maps
                    # persisted); any other truthy value → compact summary.
                    collect_attention=("full" if collect == "full"
                                       else bool(collect)),
                    trace_id=trace_id,
                    tenant=tenant,
                    # The deadline is minted HERE — queueing time counts
                    # against the budget, so a job stuck behind a backlog
                    # expires instead of burning a forward for a long-gone
                    # client.
                    deadline=(Deadline(budget).to_wire()
                              if budget and budget > 0 else None),
                    published_unix=time.time(),
                    cache_key=key))
        except Exception:
            # Leadership was claimed above: a failed publish must drop
            # the claim, or every future identical submit would attach
            # to a leader job that never existed.
            if self.cache is not None and key:
                self.cache.abandon(key)
            raise
        if self.cache is not None and key:
            self.cache.set_leader(key, job_id)
        sp.set(task_id=task_id, job_id=job_id, n_images=len(images))
        body = {"job_id": job_id, "task": spec.name}
        if key:
            body["cache"] = "miss"
        return 200, body

    def _serve_cache_hit(self, spec, socket_id: str, trace_id: str,
                         tenant: Optional[str], payload: Dict[str, Any],
                         sp) -> Tuple[int, Dict[str, Any]]:
        """Answer one submit straight from the durable result cache: the
        same result + completion frames the worker would push, plus the
        payload inline in the 200 body with the ``cache: hit`` marker.
        The cost record charges ONLY the push — zero forward/device
        share, so device-second conservation is untouched (device time
        accrues via job_batch alone)."""
        obs.RESULT_CACHE_HITS.inc()
        obs.job_begin(trace_id, task=str(spec.task_id),
                      tenant=tenant or "anon")
        t_push = time.perf_counter()
        log_to_terminal(self.hub, socket_id,
                        {"result": payload, "cache": "hit"})
        log_to_terminal(self.hub, socket_id,
                        {"terminal": "Task completed from result cache.",
                         "cache": "hit"})
        obs.job_charge(trace_id, "push", time.perf_counter() - t_push)
        obs.job_finish(trace_id, "ok")
        sp.set(task_id=spec.task_id, cache="hit")
        return 200, {"task": spec.name, "cache": "hit", "result": payload}

    def _admission_decision(self):
        counts = self.queue.counts()
        depth = counts.get("pending", 0) + counts.get("inflight", 0)
        return self.admission.admit(
            depth=depth, oldest_age_s=self.queue.oldest_pending_age_s())

    def task_details(self, task_id: int) -> Tuple[int, Dict[str, Any]]:
        task = self.store.get_task(task_id)
        if task is None:
            return 404, {"error": f"unknown task {task_id}"}
        return 200, task

    def demo_images(self, count: int = 8) -> Tuple[int, Dict[str, Any]]:
        demo_dir = os.path.join(self.serving.media_root, "demo")
        files = []
        if os.path.isdir(demo_dir):
            files = [
                os.path.join(demo_dir, f) for f in sorted(os.listdir(demo_dir))
                if f.lower().endswith((".jpg", ".jpeg", ".png"))
            ]
        if len(files) > count:
            files = random.sample(files, count)
        return 200, {
            "demo_images": files,
            # Browser-facing URLs paired index-for-index with the paths the
            # submit payload uses (paths key the feature store; urls render).
            "demo_image_urls": [
                "/media/demo/" + os.path.basename(f) for f in files
            ],
        }

    def save_upload(self, filename: str, data: bytes) -> str:
        """uuid-rename into media/demo (reference views.py:84-103)."""
        ext = os.path.splitext(filename)[1].lower() or ".jpg"
        out_dir = os.path.join(self.serving.media_root, "demo")
        os.makedirs(out_dir, exist_ok=True)
        path = os.path.join(out_dir, f"{uuid.uuid4()}{ext}")
        with open(path, "wb") as f:
            f.write(data)
        return path

    def health(self) -> Tuple[int, Dict[str, Any]]:
        """Readiness probe: 200 only when the process is past boot AND no
        PAGE-severity SLO is firing — what a load balancer polls before
        routing traffic to this replica. Body carries the evidence."""
        phase = self.boot_info.get("phase")
        booting = phase is not None and phase != "ready"
        # Breaker states as names (BREAKER_GAUGE stores the code).
        codes = {0: "closed", 1: "half_open", 2: "open"}
        breakers = {key[0]: codes.get(int(v), str(v))
                    for key, v in obs.BREAKER_GAUGE.collect().items()}
        slo_states = self.slos.states() if self.slos is not None else {}
        paging = sorted(name for name, state in slo_states.items()
                        if state == obs.STATE_PAGE)
        # Replica-pool readiness: at least one replica must be taking
        # work. Pool state is reconciled by the sampler's probe tick, so a
        # killed replica shows up here within one sampler cadence.
        no_replica = (self.pool is not None
                      and self.pool.ready_count() == 0)
        # Watchdog: any crash-guarded thread that died (by exception or
        # silently) makes the replica unready — a worker with no intake
        # threads drains nothing, whatever the pool says.
        wd = obs.watchdog()
        dead = wd.dead_threads()
        ready = (not booting and not paging and not no_replica
                 and not dead)
        body: Dict[str, Any] = {
            "ok": ready,
            "identity": obs.process_identity().as_dict(),
            "queue": self.queue.counts(),
            "boot": self.boot_info,
            "breakers": breakers,
            "slo": slo_states,
            "threads": {"alive": wd.alive_threads(), "dead": dead},
        }
        if self.pool is not None:
            body["replicas"] = self.pool.replicas_info()
            body["ready_replicas"] = self.pool.ready_count()
            # Target vs actual: an external probe seeing ready < target
            # reads "scale event in progress", not "degraded pool". With
            # no autoscaler the target IS the live replica count.
            body["pool_ready_replicas"] = self.pool.ready_count()
            body["pool_target_replicas"] = (
                self.autoscaler.target_replicas
                if self.autoscaler is not None else
                sum(1 for r in self.pool.replicas_info()
                    if r["state"] != "dead"))
        if not ready:
            body["reason"] = (
                "booting" if booting
                else "no_ready_replica" if no_replica
                else f"thread_died:{','.join(sorted(dead))}" if (
                    dead and not paging)
                else f"slo_page:{','.join(paging)}")
        return (200 if ready else 503), body

    def refresh_gauges(self) -> None:
        """Refresh point-in-time gauges on each Prometheus scrape (pull
        model: queue depth and cache occupancy are read, not pushed)."""
        g = obs.REGISTRY.gauge(
            "vmt_queue_jobs", "Durable queue jobs by state.",
            labelnames=("state",))
        counts = self.queue.counts()
        for state in ("pending", "inflight", "dead"):
            g.set(counts.get(state, 0), state=state)
        if self.metrics is not None and hasattr(self.metrics, "uptime_s"):
            obs.REGISTRY.gauge(
                "vmt_uptime_seconds",
                "Seconds since this serving process booted.",
            ).set(round(self.metrics.uptime_s(), 1))
        if self.slos is not None:
            # Scrapes see current SLO state/burn gauges even when no
            # sampler tick ran since the last change.
            self.slos.evaluate()
        if self.stats_fn is not None:
            try:
                stats = self.stats_fn()
            except Exception:  # noqa: BLE001 — stats best-effort
                stats = {}
            cache = stats.get("input_cache") or {}
            if cache:
                cg = obs.REGISTRY.gauge(
                    "vmt_input_cache", "Engine device input cache stats.",
                    labelnames=("key",))
                for key, value in cache.items():
                    cg.set(value, key=str(key))

    # ------------------------------------------------- cost attribution
    def debug_costs(self, window_s: Optional[float],
                    by: str) -> Tuple[int, Dict[str, Any]]:
        """``GET /debug/costs?window_s=&by=tenant|task``: windowed cost
        aggregates plus the device-second conservation verdict."""
        if self.attrib is None:
            return 200, {"enabled": False, "groups": {}}
        body = self.attrib.window(window_s, by=by)
        body["enabled"] = True
        if self.tracestore is not None:
            body["tracestore"] = self.tracestore.stats()
        return 200, body

    def debug_autoscale(self, limit: int) -> Tuple[int, Dict[str, Any]]:
        """``GET /debug/autoscale?limit=``: the controller's policy knobs,
        live sustain/cooldown state, target-vs-actual replica counts, and
        the last-N decision records (inputs observed, thresholds, action,
        cooldown state) — the ring the autoscaler keeps bounded."""
        if self.autoscaler is None:
            return 200, {"enabled": False, "decisions": []}
        return 200, self.autoscaler.debug_payload(limit=limit)

    def debug_traces(self, *, verdict: Optional[str], task: Optional[str],
                     tenant: Optional[str], scope: str,
                     limit: int) -> Tuple[int, Dict[str, Any]]:
        """``GET /debug/traces?verdict=slow&task=vqa``: stored-trace
        summaries (``scope=fleet`` is the liveness-blind default)."""
        if self.tracestore is None:
            return 200, {"enabled": False, "traces": []}
        # Push this process's buffered keeps first, same freshness
        # contract as the fleet flush on /debug/trace.
        try:
            self.tracestore.flush()
        except Exception:  # noqa: BLE001 — serve what's on disk
            obs.REGISTRY.counter("vmt_tracestore_flush_errors_total").inc()
        rows = self.tracestore.list(verdict=verdict, task=task,
                                    tenant=tenant, scope=scope, limit=limit)
        return 200, {"enabled": True, "scope": scope, "traces": rows,
                     "stats": self.tracestore.stats()}

    def stored_trace(self, trace_id: str) -> Optional[Dict[str, Any]]:
        """Chrome-trace doc rebuilt from the durable store — the
        ``/debug/trace`` fallback once a trace has aged out of every live
        span ring (including a dead peer's)."""
        if self.tracestore is None:
            return None
        try:
            self.tracestore.flush()
            rec = self.tracestore.get(trace_id)
        except Exception:  # noqa: BLE001
            rec = None
        if rec is None:
            return None
        events = [{
            "name": s.get("name", ""), "ph": "X", "cat": "obs",
            "ts": round(float(s.get("start_s", 0.0)) * 1e6, 3),
            "dur": round(float(s.get("dur_s", 0.0)) * 1e6, 3),
            "pid": 0, "tid": 0,
            "args": {"trace_id": s.get("trace_id"),
                     "span_id": s.get("span_id"),
                     "parent_id": s.get("parent_id"),
                     "thread_name": s.get("thread_name"),
                     **(s.get("attrs") or {})},
        } for s in rec.get("spans", [])]
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "stored": {k: rec.get(k) for k in
                           ("ident", "verdict", "keep_reason", "dur_ms",
                            "stored_unix")}}

    def autopsy(self, trace_id: str) -> Tuple[int, Dict[str, Any]]:
        """``GET /debug/autopsy?trace_id=``: one request's end-to-end
        waterfall — stage charges in pipeline order, device share,
        verdict, and the spans backing them (live record, falling back
        to the durable store)."""
        if not trace_id:
            return 400, {"error": "need trace_id"}
        cost: Optional[Dict[str, Any]] = None
        source = None
        if self.attrib is not None:
            rec = self.attrib.get(trace_id)
            if rec is not None:
                cost, source = rec.as_dict(), "live"
        spans = [s for s in obs.default_tracer().spans()
                 if s.trace_id == trace_id]
        span_dicts = [{"name": s.name, "start_s": s.start_s,
                       "dur_s": s.dur_s, "thread_name": s.thread_name,
                       "attrs": dict(s.attrs)} for s in spans]
        if (cost is None or not span_dicts) and self.tracestore is not None:
            try:
                self.tracestore.flush()
                stored = self.tracestore.get(trace_id)
            except Exception:  # noqa: BLE001
                stored = None
            if stored is not None:
                if cost is None and stored.get("cost"):
                    cost, source = stored["cost"], "store"
                if not span_dicts:
                    span_dicts = stored.get("spans", [])
        if cost is None and not span_dicts:
            return 404, {"error": f"no cost record or stored trace for "
                                  f"{trace_id}"}
        stages = (cost or {}).get("stages", {})
        waterfall = [{"stage": st, "ms": round(stages[st], 3)}
                     for st in obs.COST_STAGES if st in stages]
        return 200, {"trace_id": trace_id, "source": source,
                     "verdict": (cost or {}).get("verdict"),
                     "total_ms": (cost or {}).get("total_ms"),
                     "device_s": (cost or {}).get("device_s"),
                     "waterfall": waterfall, "cost": cost,
                     "spans": span_dicts}

    # --------------------------------------------------------------- server
    def _make_handler(self):
        api = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet
                pass

            def _json(self, code: int, payload: Dict[str, Any],
                      headers: Optional[Dict[str, str]] = None) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                path = self.path.rstrip("/") or "/"
                if path == "/":
                    # Browsers get the single-page demo app (the reference's
                    # index.html render, views.py:39-42); API clients keep
                    # the JSON contract.
                    if self._wants_html():
                        self._serve_static_page("index.html")
                        return
                    self._json(200, {
                        "tasks": api.store.list_tasks(),
                        "socket_id": str(uuid.uuid4()),
                    })
                elif path == "/config":
                    self._json(200, {
                        "ws_port": api.ws_port,
                        "socket_id": str(uuid.uuid4()),
                        "tasks": api.store.list_tasks(),
                        "max_upload_images": api.serving.max_upload_images,
                        "live_extract": bool(
                            api.boot_info.get("live_extract")),
                    })
                elif path.startswith("/get_task_details/"):
                    try:
                        task_id = int(path.split("/")[2])
                    except (IndexError, ValueError):
                        self._json(400, {"error": "bad task id"})
                        return
                    self._json(*api.task_details(task_id))
                elif path == "/get_demo_images":
                    self._json(*api.demo_images())
                elif self.path.startswith("/media/"):
                    self._serve_media()
                elif path == "/admin":
                    # The admin console page (reference: the Django admin
                    # UI, demo/admin.py) — browsers get HTML, API clients
                    # an index of the admin endpoints.
                    if self._wants_html():
                        self._serve_static_page("admin.html")
                        return
                    self._json(200, {"endpoints": [
                        "/admin/tasks", "/admin/questionanswer",
                        "POST /admin/tasks/<id>",
                        "POST /admin/questionanswer/<id>"]})
                elif path == "/admin/tasks":
                    # Browse surface over the task catalog
                    # (reference demo/admin.py:7-21 TaskAdmin list view).
                    self._json(200, {"tasks": api.store.list_tasks()})
                elif path.startswith("/admin/questionanswer"):
                    # QA audit-log browse (reference demo/admin.py:24-34
                    # QuestionAnswerAdmin: newest-first, readonly).
                    from urllib.parse import parse_qs, urlsplit

                    q = parse_qs(urlsplit(self.path).query)
                    try:
                        limit = int(q.get("limit", ["50"])[0])
                    except ValueError:
                        limit = 50
                    limit = max(1, min(limit, 500))
                    rows = api.store.recent(limit=limit)
                    # socket_id is the only credential for subscribing to a
                    # client's websocket stream — never expose it here.
                    for r in rows:
                        r.pop("socket_id", None)
                    self._json(200, {"rows": rows})
                elif path.startswith("/attention/"):
                    self._serve_attention(path)
                elif path == "/healthz" or path.startswith("/healthz?"):
                    # NB: ``path`` retains the query string (rstrip only
                    # trims slashes), hence the startswith branch.
                    from urllib.parse import parse_qs, urlsplit

                    q = parse_qs(urlsplit(self.path).query)
                    if q.get("scope", [""])[0] == "fleet":
                        if api.fleet is None:
                            self._json(503, {"error": "no fleet spine "
                                                      "configured"})
                            return
                        fleet = api.fleet.health()
                        self._json(200 if fleet["fleet_ready"] else 503,
                                   fleet)
                        return
                    self._json(*api.health())
                elif path == "/metrics" or path.startswith("/metrics?"):
                    from urllib.parse import parse_qs, urlsplit

                    q = parse_qs(urlsplit(self.path).query)
                    if q.get("scope", [""])[0] == "fleet":
                        # Fleet scope is always a scrape: merged Prometheus
                        # text across live peers (counters summed, gauges
                        # per-identity, histograms bucket-merged).
                        self._serve_fleet_prometheus()
                        return
                    if q.get("format", [""])[0] == "prometheus":
                        self._serve_prometheus()
                        return
                    if q.get("format", [""])[0] == "openmetrics":
                        # OpenMetrics exposition: same samples plus bucket
                        # exemplars linking straight to stored trace ids.
                        self._serve_openmetrics()
                        return
                    snap = (api.metrics.snapshot()
                            if api.metrics is not None else {})
                    snap["queue"] = api.queue.counts()
                    if api.stats_fn is not None:
                        try:
                            snap.update(api.stats_fn())
                        except Exception:  # noqa: BLE001 — stats best-effort
                            pass
                    self._json(200, snap)
                elif path == "/debug/slo":
                    if api.slos is None:
                        self._json(200, {"enabled": False, "slos": []})
                        return
                    reports = api.slos.evaluate()
                    states = [r["state"] for r in reports]
                    worst = (obs.STATE_PAGE if obs.STATE_PAGE in states
                             else obs.STATE_WARN if obs.STATE_WARN in states
                             else obs.STATE_OK)
                    self._json(200, {
                        "enabled": True,
                        "worst": worst,
                        "slos": reports,
                    })
                elif (path == "/debug/timeseries"
                      or path.startswith("/debug/timeseries?")):
                    from urllib.parse import parse_qs, urlsplit

                    q = parse_qs(urlsplit(self.path).query)
                    try:
                        window = float(q.get("window_s", ["0"])[0]) or None
                    except ValueError:
                        window = None
                    if q.get("scope", [""])[0] == "fleet":
                        if api.fleet is None:
                            self._json(200, {"enabled": False,
                                             "scope": "fleet", "series": {}})
                            return
                        body = api.fleet.timeseries(window)
                        body["enabled"] = True
                        self._json(200, body)
                        return
                    if api.timeseries is None:
                        self._json(200, {"enabled": False, "series": {}})
                        return
                    self._json(200, {
                        "enabled": True,
                        "series": api.timeseries.snapshot(window),
                    })
                elif path == "/debug/trace" or path.startswith("/debug/trace?"):
                    from urllib.parse import parse_qs, urlsplit

                    q = parse_qs(urlsplit(self.path).query)
                    try:
                        limit = int(q.get("limit", ["0"])[0]) or None
                    except ValueError:
                        limit = None
                    trace_id = q.get("trace_id", [""])[0] or None
                    fleet_scope = (q.get("scope", [""])[0] == "fleet"
                                   or trace_id is not None)
                    if fleet_scope and api.fleet is not None:
                        # Export this process's freshest spans first so a
                        # trace queried right after completion stitches
                        # without waiting out a sampler tick.
                        try:
                            api.fleet.flush()
                        except Exception:  # noqa: BLE001 — serve what's there
                            obs.REGISTRY.counter(
                                "vmt_fleet_flush_errors_total").inc()
                        doc = api.fleet.chrome_trace(trace_id, limit=limit)
                        if trace_id is not None and not any(
                                e.get("ph") == "X"
                                for e in doc.get("traceEvents", [])):
                            # Aged out of every peer's span window — the
                            # durable store is the last line of autopsy.
                            stored = api.stored_trace(trace_id)
                            if stored is not None:
                                self._json(200, stored)
                                return
                        self._json(200, doc)
                        return
                    if trace_id is not None:
                        spans = [s for s in obs.default_tracer().spans()
                                 if s.trace_id == trace_id]
                        if not spans:
                            stored = api.stored_trace(trace_id)
                            if stored is not None:
                                self._json(200, stored)
                                return
                        self._json(200, obs.chrome_trace(spans=spans))
                        return
                    self._json(200, obs.chrome_trace(limit=limit))
                elif (path == "/debug/costs"
                      or path.startswith("/debug/costs?")):
                    from urllib.parse import parse_qs, urlsplit

                    q = parse_qs(urlsplit(self.path).query)
                    try:
                        window = float(q.get("window_s", ["0"])[0]) or None
                    except ValueError:
                        window = None
                    self._json(*api.debug_costs(
                        window, q.get("by", ["task"])[0]))
                elif (path == "/debug/traces"
                      or path.startswith("/debug/traces?")):
                    from urllib.parse import parse_qs, urlsplit

                    q = parse_qs(urlsplit(self.path).query)
                    try:
                        limit = int(q.get("limit", ["50"])[0])
                    except ValueError:
                        limit = 50
                    self._json(*api.debug_traces(
                        verdict=q.get("verdict", [""])[0] or None,
                        task=q.get("task", [""])[0] or None,
                        tenant=q.get("tenant", [""])[0] or None,
                        scope=q.get("scope", ["fleet"])[0] or "fleet",
                        limit=max(1, min(limit, 500))))
                elif (path == "/debug/autopsy"
                      or path.startswith("/debug/autopsy?")):
                    from urllib.parse import parse_qs, urlsplit

                    q = parse_qs(urlsplit(self.path).query)
                    self._json(*api.autopsy(q.get("trace_id", [""])[0]))
                elif (path == "/debug/autoscale"
                      or path.startswith("/debug/autoscale?")):
                    from urllib.parse import parse_qs, urlsplit

                    q = parse_qs(urlsplit(self.path).query)
                    try:
                        limit = int(q.get("limit", ["50"])[0])
                    except ValueError:
                        limit = 50
                    self._json(*api.debug_autoscale(
                        limit=max(1, min(limit, 500))))
                else:
                    self._json(404, {"error": "not found"})

            def _wants_html(self) -> bool:
                """Browser-vs-API content negotiation (one place)."""
                return "text/html" in self.headers.get("Accept", "")

            def _serve_prometheus(self) -> None:
                api.refresh_gauges()
                self._send_prometheus(
                    obs.render_prometheus(extra=self._extra_instruments()))

            def _serve_openmetrics(self) -> None:
                api.refresh_gauges()
                self._send_text(
                    obs.render_openmetrics(extra=self._extra_instruments()),
                    obs.OPENMETRICS_CONTENT_TYPE)

            def _extra_instruments(self):
                return ([api.metrics.latency]
                        if api.metrics is not None
                        and hasattr(api.metrics, "latency") else [])

            def _serve_fleet_prometheus(self) -> None:
                if api.fleet is None:
                    self._json(503, {"error": "no fleet spine configured"})
                    return
                # Refresh local gauges and push them to the spine so the
                # answering process is never staler than its own scrape.
                api.refresh_gauges()
                try:
                    api.fleet.flush()
                except Exception:  # noqa: BLE001 — merge what peers wrote
                    obs.REGISTRY.counter(
                        "vmt_fleet_flush_errors_total").inc()
                self._send_prometheus(api.fleet.render_prometheus())

            def _send_prometheus(self, text: str) -> None:
                self._send_text(text, obs.PROMETHEUS_CONTENT_TYPE)

            def _send_text(self, text: str, ctype: str) -> None:
                body = text.encode()
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _serve_static_page(self, name: str):
                page = os.path.join(os.path.dirname(__file__), "static",
                                    name)
                try:
                    with open(page, "rb") as f:
                        body = f.read()
                except OSError:
                    self._json(500, {"error": "frontend asset missing"})
                    return
                self.send_response(200)
                self.send_header("Content-Type", "text/html; charset=utf-8")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _serve_attention(self, path: str):
                """JSON view of a request's persisted full attention maps
                (worker.save_full_attention). Default response is head-
                averaged per bridge — browser-heatmap sized; ``?heads=all``
                returns every head (the complete reference-contract payload,
                worker.py:288). The raw arrays are also downloadable as
                ``/media/attention/qa_<id>.npz``."""
                from urllib.parse import parse_qs, urlsplit

                try:
                    qa_id = int(urlsplit(path).path.split("/")[2])
                except (IndexError, ValueError):
                    self._json(400, {"error": "bad qa id"})
                    return
                npz = os.path.join(api.serving.media_root, "attention",
                                   f"qa_{qa_id}.npz")
                if not os.path.isfile(npz):
                    self._json(404, {"error": f"no attention maps for "
                                              f"qa {qa_id}; submit with "
                                              f"collect_attention='full'"})
                    return
                import numpy as np

                all_heads = parse_qs(urlsplit(self.path).query).get(
                    "heads", [""])[0] == "all"
                try:
                    with np.load(npz) as z:
                        bridges: Dict[int, Dict[str, Any]] = {}
                        for key in z.files:
                            name, direction = key.rsplit("_", 1)
                            idx = int(name.replace("bridge", ""))
                            arr = z[key]  # (H, Nq, Nk)
                            if not all_heads:
                                arr = arr.mean(axis=0)  # head-avg (Nq, Nk)
                            bridges.setdefault(idx, {})[direction] = (
                                np.round(arr, 5).tolist())
                except Exception as e:  # noqa: BLE001 — a corrupt archive
                    # (zipfile.BadZipFile, truncated stream) must yield a
                    # JSON 500, not a dropped connection.
                    self._json(500, {"error": f"attention maps for qa "
                                              f"{qa_id} unreadable: {e}"})
                    return
                self._json(200, {
                    "qa_id": qa_id,
                    "heads": "all" if all_heads else "mean",
                    "bridges": [bridges[i] for i in sorted(bridges)],
                })

            def _serve_media(self):
                from vilbert_multitask_tpu.utils import contained_path

                rel = self.path[len("/media/"):].lstrip("/")
                # containment check: resolved target must stay under media_root
                full = contained_path(
                    api.serving.media_root,
                    os.path.join(api.serving.media_root, rel))
                if full is None:
                    self._json(403, {"error": "forbidden"})
                    return
                if not os.path.isfile(full):
                    self._json(404, {"error": "not found"})
                    return
                ctype = mimetypes.guess_type(full)[0] or "application/octet-stream"
                with open(full, "rb") as f:
                    data = f.read()
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                raw = self.rfile.read(length)
                ctype = self.headers.get("Content-Type", "")
                path = self.path.rstrip("/") or "/"
                if path == "/":
                    try:
                        payload = json.loads(raw or b"{}")
                    except json.JSONDecodeError:
                        self._json(400, {"error": "invalid JSON"})
                        return
                    code, body = api.submit_job(payload)
                    headers = None
                    if code == 429:
                        # RFC 9110 §10.2.3: Retry-After in whole seconds.
                        headers = {"Retry-After": str(max(1, int(round(
                            body.get("retry_after_s", 1)))))}
                    self._json(code, body, headers=headers)
                elif path == "/upload_image":
                    self._handle_upload(raw, ctype)
                elif path.startswith("/worker/"):
                    self._handle_worker(path, raw)
                elif path == "/admin/swap":
                    self._handle_admin_swap(raw)
                elif path.startswith("/admin/"):
                    self._handle_admin_edit(path, raw)
                elif path == "/debug/profile/start":
                    try:
                        p = json.loads(raw or b"{}")
                    except json.JSONDecodeError:
                        self._json(400, {"error": "invalid JSON"})
                        return
                    log_dir = str(p.get("log_dir", "")) or os.path.join(
                        api.serving.media_root, "profiles")
                    os.makedirs(log_dir, exist_ok=True)
                    res = obs.start_profile(log_dir)
                    self._json(200 if res["ok"] else 409, res)
                elif path == "/debug/profile/stop":
                    res = obs.stop_profile()
                    self._json(200 if res["ok"] else 409, res)
                else:
                    self._json(404, {"error": "not found"})

            def _handle_admin_swap(self, raw: bytes):
                """POST /admin/swap {checkpoint_path}: rolling zero-downtime
                checkpoint swap across the replica pool (ServeApp wires
                ``swap_fn``). Runs in this handler thread — the server is
                threaded, so health/metrics/submits keep flowing while
                replicas drain and reload one at a time. Same admin-token
                gate as the admin edit surface."""
                token = getattr(api.serving, "admin_token", None)
                if token:
                    import hmac

                    auth = self.headers.get("Authorization", "")
                    if not hmac.compare_digest(auth, f"Bearer {token}"):
                        self._json(401, {"error": "bad admin token"})
                        return
                if api.swap_fn is None:
                    self._json(409, {"error": "no swap handler wired"})
                    return
                try:
                    p = json.loads(raw or b"{}")
                except json.JSONDecodeError:
                    self._json(400, {"error": "invalid JSON"})
                    return
                ckpt = p.get("checkpoint_path")
                if not ckpt:
                    self._json(400, {"error": "need checkpoint_path"})
                    return
                try:
                    report = api.swap_fn(checkpoint_path=str(ckpt))
                except (ValueError, FileNotFoundError, TimeoutError) as e:
                    self._json(409, {"error": f"swap failed: {e}"})
                    return
                self._json(200, {"ok": True, "swap": report})

            def _handle_admin_edit(self, path: str, raw: bytes):
                """Admin write surface (reference demo/admin.py:11-34: the
                Django admin edits Tasks rows and QuestionAnswer text).
                POST /admin/tasks/<id> and /admin/questionanswer/<id> take a
                JSON object of editable fields and return the updated row
                with the same scrubbing the browse endpoints apply.

                Gated behind ``ServingConfig.admin_token`` when set (the
                reference admin sits behind Django auth, demo/admin.py);
                unset keeps the open loopback-dev posture, but an edited
                row persists across reboots (the reseed never overwrites
                ``edited=1`` rows), so cross-host deployments must set it."""
                token = getattr(api.serving, "admin_token", None)
                if token:
                    import hmac

                    auth = self.headers.get("Authorization", "")
                    if not hmac.compare_digest(auth, f"Bearer {token}"):
                        self._json(401, {"error": "bad admin token"})
                        return
                parts = path.strip("/").split("/")
                if len(parts) != 3 or parts[1] not in (
                        "tasks", "questionanswer"):
                    self._json(404, {"error": "not found"})
                    return
                try:
                    row_id = int(parts[2])
                except ValueError:
                    self._json(400, {"error": "bad id"})
                    return
                try:
                    fields = json.loads(raw or b"{}")
                except json.JSONDecodeError:
                    self._json(400, {"error": "invalid JSON"})
                    return
                if not isinstance(fields, dict):
                    self._json(400, {"error": "body must be a JSON object"})
                    return
                try:
                    if parts[1] == "tasks":
                        row = api.store.update_task(row_id, fields)
                    else:
                        row = api.store.update_question(row_id, fields)
                except ValueError as e:
                    self._json(400, {"error": str(e)})
                    return
                if row is None:
                    self._json(404, {"error": f"no row {row_id}"})
                    return
                row.pop("socket_id", None)  # same scrub as the browse view
                self._json(200, {"row": row})

            def _handle_worker(self, path: str, raw: bytes):
                """Network face of the queue/store/hub for remote workers
                (serve/remote.py) — the reference's broker is reachable over
                TCP (demo/sender.py:12-15); this keeps web tier and TPU
                workers deployable on separate hosts."""
                token = getattr(api.serving, "worker_token", None)
                if token:
                    import hmac

                    auth = self.headers.get("Authorization", "")
                    if not hmac.compare_digest(auth, f"Bearer {token}"):
                        self._json(401, {"error": "bad worker token"})
                        return
                try:
                    p = json.loads(raw or b"{}")
                except json.JSONDecodeError:
                    self._json(400, {"error": "invalid JSON"})
                    return
                try:
                    if path == "/worker/claim":
                        claimed_by = p.get("claimed_by") or None
                        job = api.queue.claim(
                            exclude=[int(x) for x in p.get("exclude", [])],
                            claimed_by=(str(claimed_by)
                                        if claimed_by else None))
                        self._json(200, {"job": None if job is None else {
                            "id": job.id, "body": job.body,
                            "attempts": job.attempts,
                            "deliveries": job.deliveries}})
                    elif path == "/worker/dead_letters":
                        jobs = api.queue.pop_dead_letters()
                        self._json(200, {"jobs": [
                            {"id": j.id, "body": j.body,
                             "attempts": j.attempts,
                             "deliveries": j.deliveries} for j in jobs]})
                    elif path == "/worker/ack":
                        api.queue.ack(int(p["job_id"]))
                        self._json(200, {"ok": True})
                    elif path == "/worker/nack":
                        self._json(200,
                                   {"status": api.queue.nack(int(p["job_id"]))})
                    elif path == "/worker/release":
                        api.queue.release(int(p["job_id"]))
                        self._json(200, {"ok": True})
                    elif path == "/worker/question":
                        qa_id = api.store.create_question(
                            int(p["task_id"]), str(p.get("input_text", "")),
                            list(p.get("input_images", [])),
                            str(p.get("socket_id", "")),
                            queue_job_id=p.get("queue_job_id"))
                        self._json(200, {"qa_id": qa_id})
                    elif path == "/worker/answer":
                        api.store.save_answer(
                            int(p["qa_id"]), p.get("answer", {}),
                            list(p.get("answer_images", [])))
                        self._json(200, {"ok": True})
                    elif path == "/worker/push":
                        n = api.hub.publish(str(p.get("socket_id", "")),
                                            p.get("frame", {}))
                        self._json(200, {"subscribers": n})
                    else:
                        self._json(404, {"error": "not found"})
                except (KeyError, TypeError, ValueError) as e:
                    self._json(400, {"error": f"bad worker request: {e}"})

            def _handle_upload(self, raw: bytes, ctype: str):
                if "multipart/form-data" not in ctype:
                    self._json(400, {"error": "expected multipart/form-data"})
                    return
                with obs.span("http.upload", bytes=len(raw)) as sp:
                    msg = email.message_from_bytes(
                        b"Content-Type: " + ctype.encode() + b"\r\n\r\n" + raw,
                        policy=email.policy.HTTP,
                    )
                    paths = []
                    for part in msg.iter_parts():
                        name = part.get_filename()
                        if not name:
                            continue
                        if len(paths) >= api.serving.max_upload_images:
                            break  # reference caps uploads (demo_images.html:92-95)
                        paths.append(api.save_upload(
                            name, part.get_payload(decode=True) or b""))
                    sp.set(n_files=len(paths))
                self._json(200, {"file_paths": paths})

        return Handler

    def start(self) -> int:
        self._httpd = ThreadingHTTPServer(
            (self.serving.http_host, self.serving.http_port),
            self._make_handler(),
        )
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True, name="http-api")
        self._thread.start()
        return self._httpd.server_address[1]

    def stop(self) -> None:
        if self._httpd:
            self._httpd.shutdown()
            self._httpd.server_close()
        if self._thread:
            self._thread.join(timeout=5)
