"""Grounding-result rendering: draw top-k boxes onto image copies.

Reference capability: worker.py:591-600 — for tasks 4/11/16 the worker draws
the top-3 grounded boxes (red/green/blue, 3px) onto copies of the input image
with cv2 and saves ``media/refer_expressions_task/<uuid>.jpg``; the client
renders those files (result.html:113-168). PIL here (no cv2 dependency in
the serving path).
"""

from __future__ import annotations

import os
import uuid
from typing import Any, Dict, List

# Reference draws one box per copy in this order (worker.py:592-596).
_BOX_COLORS = [(255, 0, 0), (0, 255, 0), (0, 0, 255)]


def draw_grounding_boxes(
    image_path: str,
    boxes: List[Dict[str, Any]],
    out_dir: str,
    *,
    width: int = 3,
) -> List[str]:
    """One output image per top-k box, reference-style. Returns saved paths."""
    from PIL import Image, ImageDraw

    os.makedirs(out_dir, exist_ok=True)
    base = Image.open(image_path).convert("RGB")
    out_paths: List[str] = []
    for rank, box in enumerate(boxes[: len(_BOX_COLORS)]):
        img = base.copy()
        draw = ImageDraw.Draw(img)
        x1, y1, x2, y2 = box["box_xyxy"]
        # Clamp to the canvas so degenerate boxes still draw.
        x1, x2 = sorted((max(0, x1), min(img.width - 1, x2)))
        y1, y2 = sorted((max(0, y1), min(img.height - 1, y2)))
        draw.rectangle([x1, y1, x2, y2], outline=_BOX_COLORS[rank],
                       width=width)
        path = os.path.join(out_dir, f"{uuid.uuid4()}.jpg")
        img.save(path, "JPEG")
        out_paths.append(path)
    return out_paths
