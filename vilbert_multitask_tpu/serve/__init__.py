"""Serving tier: durable queue, result store, push hub, HTTP API, worker.

The TPU-native rebuild of the reference's L3-L6 stack (SURVEY.md §1):
Django+RabbitMQ+Redis+Postgres collapse into an embedded, broker-less stack
with the same wire contracts (queue message schema, websocket frame keys,
HTTP endpoints).
"""

from vilbert_multitask_tpu.serve.db import ResultStore
from vilbert_multitask_tpu.serve.http_api import ApiServer
from vilbert_multitask_tpu.serve.metrics import Metrics
from vilbert_multitask_tpu.serve.pool import (
    NoReadyReplica,
    Replica,
    ReplicaFailover,
    ReplicaPool,
)
from vilbert_multitask_tpu.serve.push import PushHub, WebSocketBridge, log_to_terminal
from vilbert_multitask_tpu.serve.queue import DurableQueue, Job, make_job_message
from vilbert_multitask_tpu.serve.render import draw_grounding_boxes
from vilbert_multitask_tpu.serve.scheduler import ContinuousScheduler
from vilbert_multitask_tpu.serve.worker import ServeWorker

__all__ = [
    "ApiServer",
    "ContinuousScheduler",
    "DurableQueue",
    "Job",
    "Metrics",
    "NoReadyReplica",
    "PushHub",
    "Replica",
    "ReplicaFailover",
    "ReplicaPool",
    "ResultStore",
    "ServeWorker",
    "WebSocketBridge",
    "draw_grounding_boxes",
    "log_to_terminal",
    "make_job_message",
]
