"""The queue worker: claim → infer → persist → push → ack.

Reference capability: ``callback`` (reference worker.py:542-658) — the
per-message pipeline that creates the DB row, extracts features, runs the
model, marshals the per-task answer, saves, and streams progress/results to
the client's websocket group — with the §2.4 parity traps fixed:

- ack/nack is explicit and poison jobs dead-letter after N attempts
  (reference leaves them redelivering forever, worker.py:650-655);
- a failed DB insert aborts the job instead of being swallowed and crashing
  later (worker.py:548-555 vs 579);
- label maps and features are engine-cached, not re-read per request.
"""

from __future__ import annotations

import os
import threading
import time
import traceback
from typing import Any, Dict, List, Optional

from vilbert_multitask_tpu import obs
from vilbert_multitask_tpu.config import ServingConfig, TASK_REGISTRY
from vilbert_multitask_tpu.engine.runtime import InferenceEngine
from vilbert_multitask_tpu.resilience import Deadline, DeadlineExceeded
from vilbert_multitask_tpu.resilience.faults import fault_point
from vilbert_multitask_tpu.serve.db import ResultStore
from vilbert_multitask_tpu.serve.metrics import Metrics
from vilbert_multitask_tpu.serve.pool import ReplicaFailover
from vilbert_multitask_tpu.serve.push import PushHub, fan_out, log_to_terminal
from vilbert_multitask_tpu.serve.queue import DurableQueue, Job
from vilbert_multitask_tpu.serve.render import draw_grounding_boxes
from vilbert_multitask_tpu.serve.resultcache import ResultCache


def _attention_summary(out) -> Dict[str, Any]:
    """Compact, JSON-safe view of the co-attention maps for one request.

    The reference computes per-layer maps on every forward
    (worker.py:288) but the demo never renders them; here the serving
    contract surfaces the useful slice — per-bridge, head-averaged [CLS]-row
    text→image attention over the regions (the grounding-relevant signal) —
    small enough to ride in the websocket result frame.
    """
    import numpy as np

    bridges = []
    for probs_t2v, _probs_v2t in out.attn_data_list:
        if probs_t2v is None:
            continue
        p = np.asarray(probs_t2v, np.float32)[0]  # (H, Nq, Nk), request row 0
        cls_over_regions = p.mean(axis=0)[0]  # head-avg, [CLS] query row
        bridges.append([round(float(x), 5) for x in cls_over_regions])
    return {"bridge_cls_to_regions": bridges,
            "n_bridges": len(bridges)}


def save_full_attention(out, qa_id: int, media_root: str) -> Dict[str, Any]:
    """Persist the COMPLETE per-bridge co-attention maps for one request.

    Both directions of every bridge, all heads, request row 0 —
    ``bridge{i}_t2v`` (H, Nt, Nv) and ``bridge{i}_v2t`` (H, Nv, Nt) — as a
    compressed ``.npz`` under ``media/attention/``. The reference's
    ``output_all_attention_masks=True`` contract (worker.py:288) made these
    maps exist on every forward and then dropped them; here a job opting in
    with ``collect_attention="full"`` gets the whole payload back through
    the API: the npz is downloadable at ``/media/attention/qa_<id>.npz`` and
    ``GET /attention/<qa_id>`` serves a JSON view for the browser.
    """
    import numpy as np

    arrays: Dict[str, Any] = {}
    for i, (probs_t2v, probs_v2t) in enumerate(out.attn_data_list):
        if probs_t2v is not None:
            arrays[f"bridge{i}_t2v"] = np.asarray(probs_t2v, np.float32)[0]
        if probs_v2t is not None:
            arrays[f"bridge{i}_v2t"] = np.asarray(probs_v2t, np.float32)[0]
    out_dir = os.path.join(media_root, "attention")
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, f"qa_{qa_id}.npz")
    # Write-then-rename: a worker killed mid-write must never leave a
    # truncated npz at the final path (every later GET would 500). The tmp
    # name keeps the .npz suffix — np.savez appends one otherwise and the
    # rename source would not exist.
    tmp = os.path.join(out_dir, f".qa_{qa_id}.tmp.npz")
    np.savez_compressed(tmp, **arrays)
    os.replace(tmp, path)
    return {"qa_id": qa_id,
            "full_map_npz": f"/media/attention/qa_{qa_id}.npz",
            "full_map_url": f"/attention/{qa_id}"}


class ServeWorker:
    """Single-process inference worker (one engine, one queue consumer)."""

    def __init__(
        self,
        engine: InferenceEngine,
        queue: DurableQueue,
        store: ResultStore,
        hub: PushHub,
        serving: Optional[ServingConfig] = None,
        metrics: Optional[Metrics] = None,
        cache: Optional[ResultCache] = None,
    ):
        self.engine = engine
        self.queue = queue
        self.store = store
        self.hub = hub
        self.serving = serving or ServingConfig()
        self.metrics = metrics or Metrics()
        # Durable result cache + singleflight follower registry
        # (serve/resultcache.py). When a finished job carries a
        # ``cache_key``, its result is written through here and every
        # terminal frame fans out to the key's coalesced followers.
        self.cache = cache
        # Claimed-but-unfinished jobs, for graceful drain: stop() releases
        # these back to the queue (no attempt charged) and tells the client.
        self._inflight_lock = threading.Lock()
        self._inflight: Dict[int, Job] = {}
        # Set by run_forever when serving.sched_enabled — the continuous
        # batching data plane (serve/scheduler.py) this worker drains
        # through; None while running the legacy step_batch loop.
        self.scheduler = None

    # ------------------------------------------------------------- job cycle
    def _intake(self, job: Job):
        """Validate + prepare one job: returns (qa_id, prepared, t0).

        t0 is captured before feature I/O so solo and batched paths record
        the same latency definition in :class:`Metrics`.
        """
        fault_point("worker.intake")
        body = job.body
        t0 = time.perf_counter()
        task_id = int(body["task_id"])  # reference eval()s this str; we don't
        question = body.get("question", "")
        socket_id = body.get("socket_id", "")
        image_paths = body["image_path"]
        if isinstance(image_paths, str):
            image_paths = [image_paths]
        spec = TASK_REGISTRY[task_id]
        spec.validate_num_images(len(image_paths))
        log_to_terminal(self.hub, socket_id,
                        {"terminal": f"Running {spec.name} inference..."})
        # Audit row first (reference worker.py:548-552), keyed by the queue
        # job id so redelivered attempts reuse one row.
        qa_id = self.store.create_question(task_id, question, image_paths,
                                           socket_id, queue_job_id=job.id)
        # One store read yields regions + content-stable device-cache
        # identities (file + mtime + size, captured at read time): repeat
        # queries about unchanged images skip the feature upload; an
        # edited/replaced file is a cache miss.
        prepared = self.engine.prepare_from_store(task_id, question,
                                                  image_paths)
        obs.job_charge(body.get("trace_id", ""), "intake",
                       time.perf_counter() - t0)
        return qa_id, prepared, t0

    def process_job(self, job: Job) -> Dict[str, Any]:
        """One message end-to-end; raises on failure (caller nacks)."""
        # Re-enter the trace minted at HTTP submit (queue.make_job_message
        # carried the id across the thread boundary); jobs published by
        # pre-tracing clients get a fresh id (trace_scope(None)).
        with obs.trace_scope(job.body.get("trace_id")), \
                obs.span("worker.job", job_id=job.id,
                         task_id=job.body.get("task_id", "")):
            with obs.span("worker.intake"):
                qa_id, prepared, t0 = self._intake(job)
            # collect_attention: falsy → none; truthy → summary in the result
            # frame; the string "full" additionally persists every per-bridge
            # per-head map (save_full_attention).
            collect = job.body.get("collect_attention", False)
            with obs.span("worker.infer",
                          task_id=job.body.get("task_id", "")):
                out, result = self.engine.run(
                    prepared, collect_attention=bool(collect),
                    deadline=self._deadline_of(job))
            attention = None
            if collect:
                attention = _attention_summary(out)
                if collect == "full":
                    attention.update(save_full_attention(
                        out, qa_id, self.serving.media_root))
            return self._finish_job(job, qa_id, prepared, result, t0,
                                    attention=attention)

    def _claim(self, exclude=()) -> Optional[Job]:
        """Claim with telemetry: the claim interval only becomes a span if a
        job came back (idle polls must not churn the span ring), and it
        joins the claimed job's trace after the fact (record_span)."""
        t0 = time.perf_counter()
        self._notify_dead_letters()
        ident = obs.process_identity().ident
        job = self.queue.claim(exclude=exclude, claimed_by=ident)
        if job is not None:
            obs.default_tracer().record_span(
                "worker.claim", t0, time.perf_counter() - t0,
                trace_id=job.body.get("trace_id"), job_id=job.id,
                attempts=job.attempts, claimed_by=ident)
            # Cost attribution opens at claim: every stage charge between
            # here and the terminal verdict lands on this record.
            trace_id = job.body.get("trace_id", "")
            obs.job_begin(trace_id, job_id=job.id,
                          task=str(job.body.get("task_id", "")),
                          tenant=str(job.body.get("tenant") or "anon"))
            published = job.body.get("published_unix")
            if published is not None:
                # Publish→claim latency. Wall-clock delta against the
                # submitter's epoch stamp — cross-process, so monotonic
                # clocks cannot be compared (same rationale as
                # Deadline.issued_unix); clamped because unsynced clocks
                # can run the difference slightly negative.
                wait_s = time.time() - float(published)  # vmtlint: disable=VMT109
                obs.QUEUE_WAIT.observe(
                    max(wait_s, 0.0) * 1e3,
                    task=str(job.body.get("task_id", "")),
                    tenant=str(job.body.get("tenant") or "anon"))
                obs.job_charge(trace_id, "queue_wait", max(wait_s, 0.0))
            with self._inflight_lock:
                self._inflight[job.id] = job
        return job

    def _notify_dead_letters(self) -> None:
        """Push terminal frames for jobs the queue quarantined as poison.

        The deliveries sweep inside ``claim()`` dead-letters jobs that
        exceeded ``queue_max_deliveries`` without any worker holding them —
        nobody is positioned to tell the client.  ``pop_dead_letters()``
        hands each such job to exactly one caller (the ``dead_notified``
        column makes the pop idempotent), so the frame is pushed once no
        matter how many workers poll."""
        pop = getattr(self.queue, "pop_dead_letters", None)
        if pop is None:
            return
        for job in pop():
            obs.record_event("poison_quarantined", job_id=job.id,
                             trace_id=job.body.get("trace_id"),
                             task_id=job.body.get("task_id", ""),
                             deliveries=job.deliveries)
            # Close any cost record a dead prior holder left open, so the
            # quarantine verdict (not an eviction) is what the store keeps.
            obs.job_finish(job.body.get("trace_id", ""), "dead_letter")
            frame = {
                "terminal": "Job quarantined: it was delivered "
                            f"{job.deliveries} times without completing "
                            "and will not be retried.",
                "error": "poison job dead-lettered after "
                         f"{job.deliveries} deliveries",
                "dead_letter": True,
                "process": obs.process_identity().ident,
                "question": job.body.get("question", ""),
            }
            log_to_terminal(self.hub, job.body.get("socket_id", ""), frame)
            # Quarantine is a terminal: followers coalesced onto this
            # job must hear it too, and the singleflight claim drops so
            # a retry submit republishes instead of attaching.
            self._fan_to_followers(job.body, [frame],
                                   verdict="dead_letter", drop_claim=True)

    def _failover_job(self, job: Job, replica: str) -> str:
        """Move a job off a failed replica: release (no attempt charged),
        stamp the culprit replica in the requeued frame, and count it.

        release(), not nack(): the REPLICA failed, not the job — at-least-
        once redelivery reruns it on a healthy replica.  A job that kills
        every replica it lands on is bounded by the queue's
        ``delivery_count`` quarantine (release never decrements it)."""
        obs.FAILOVER_COUNTER.inc(replica=replica)
        obs.default_tracer().record_span(
            "worker.failover", time.perf_counter(), 0.0,
            trace_id=job.body.get("trace_id"), job_id=job.id,
            replica=replica)
        self.queue.release(job.id)
        self._untrack(job.id)
        obs.job_finish(job.body.get("trace_id", ""), "failover")
        frame = {
            "terminal": f"Replica {replica} failed mid-inference; job "
                        "requeued on a healthy replica.",
            "requeued": True,
            "replica": replica,
            "process": obs.process_identity().ident,
            "question": job.body.get("question", ""),
        }
        log_to_terminal(self.hub, job.body.get("socket_id", ""), frame)
        # Not a terminal: the job reruns on a healthy replica, so
        # followers stay attached (peek) and just hear the requeue.
        self._fan_to_followers(job.body, [frame], final=False)
        return "requeued"

    # --------------------------------------------------- coalesced fan-out
    def _fan_to_followers(self, body: Dict[str, Any],
                          frames: List[Dict[str, Any]], *,
                          verdict: Optional[str] = None,
                          final: bool = True,
                          drop_claim: bool = False) -> None:
        """Fan the leader's frames out to every coalesced follower.

        ``final=True`` destructively pops the follower registry inside
        one write transaction, so each follower receives its terminal
        frames exactly once — exactly-one-terminal per *submit*, not
        just per job, no matter how many workers race the leader's
        terminal. ``final=False`` peeks (requeued/failover notices):
        followers stay attached for the eventual terminal.
        ``drop_claim`` additionally abandons the singleflight claim so
        the next identical submit retries instead of attaching to a key
        whose leader already failed. ``verdict`` closes each follower's
        cost record — a follower is charged ONLY the push (its forward
        was the leader's; device-second conservation is untouched
        because device time accrues via job_batch alone).
        """
        if self.cache is None:
            return
        key = body.get("cache_key")
        if not key:
            return
        followers = (self.cache.pop_followers(key) if final
                     else self.cache.peek_followers(key))
        if followers:
            t_push = time.perf_counter()
            sids = [f.socket_id for f in followers]
            for frame in frames:
                fan_out(self.hub, sids, dict(frame, coalesced=True))
            if verdict is not None:
                # The fan wall splits evenly: push is the ONLY stage a
                # follower is charged for.
                share = (time.perf_counter() - t_push) / len(followers)
                for f in followers:
                    obs.job_charge(f.trace_id or "", "push", share)
                    obs.job_finish(f.trace_id or "", verdict)
        if drop_claim:
            self.cache.abandon(key)

    def _untrack(self, job_id: int) -> None:
        with self._inflight_lock:
            self._inflight.pop(job_id, None)

    def inflight_count(self) -> int:
        """Jobs claimed but not yet finished (obs sampler probe)."""
        with self._inflight_lock:
            return len(self._inflight)

    # ------------------------------------------------------------- deadlines
    @staticmethod
    def _deadline_of(job: Job) -> Optional[Deadline]:
        return Deadline.from_wire(job.body.get("deadline"))

    def _check_deadline(self, job: Job) -> bool:
        """True if the job's deadline already expired (job terminated)."""
        dl = self._deadline_of(job)
        if dl is None:
            return False
        obs.DEADLINE_SLACK.observe(
            max(dl.remaining_s(), 0.0) * 1e3,
            task=str(job.body.get("task_id", "")))
        if not dl.expired():
            return False
        self._expire_job(job)
        return True

    def _expire_job(self, job: Job, *, reason: str = "deadline") -> None:
        """Terminate an expired job: terminal push + ack (the client gave
        up waiting; a forward would be pure waste). Ack, not nack — the
        outcome is final, not retryable. ``reason`` classifies the shed
        (``deadline`` for plain EDF expiry, ``tenant_budget`` when the
        deficit scheduler's fairness tier deferred the job past its
        deadline) so vmt_shed_total separates overload from QoS policy."""
        obs.SHED_COUNTER.inc(reason=reason)
        # One expiry is traffic; a burst is an incident. The spike tracker
        # dumps a postmortem bundle only when expiries cluster.
        obs.record_spike("deadline_spike",
                         trace_id=job.body.get("trace_id"),
                         task_id=job.body.get("task_id", ""))
        frame = {
            "terminal": "Deadline exceeded before the job could be "
                        "served; not retried.",
            "deadline_exceeded": True,
            "question": job.body.get("question", ""),
        }
        log_to_terminal(self.hub, job.body.get("socket_id", ""), frame)
        # Expiry is a terminal: every coalesced follower hears it
        # (exactly one terminal per submit) and the singleflight claim
        # drops so a fresh submit retries with a fresh deadline.
        self._fan_to_followers(job.body, [frame],
                               verdict="deadline", drop_claim=True)
        self.queue.ack(job.id)
        self._untrack(job.id)
        obs.job_finish(job.body.get("trace_id", ""), "deadline")

    def step(self) -> Optional[str]:
        """Claim and run one job. Returns 'acked'/'failed'/None."""
        job = self._claim()
        if job is None:
            return None
        return self.step_one(job)

    def metrics_failure_for(self, job: Job) -> None:
        try:
            self.metrics.record_failure(int(job.body.get("task_id", -1)))
        except (TypeError, ValueError):
            self.metrics.record_failure()

    # ------------------------------------------------------- micro-batching
    def step_batch(self, max_jobs: Optional[int] = None, *,
                   stop_event=None) -> int:
        """Drain up to ``max_jobs`` queued jobs and serve the packable ones
        through batched forwards (engine.run_many — mixed image counts
        share chunks, so NLVR2 pairs, retrieval candidate sets, and
        singles all pack into the same dispatches; see engine.chunk_plan);
        attention-map requests claimed along the way run individually
        (per-request forward flag). Returns jobs completed.

        This is the TPU-shaped replacement for the reference's strictly
        serial batch=1 loop (worker.py:70,489,672-673): under queue backlog
        the trunk runs once per bucket instead of once per request.
        """
        if max_jobs is None:
            # Drain to the engine's largest compiled row bucket: under deep
            # backlog the worker fills a whole throughput chunk (32 by
            # default) instead of capping at 8 and leaving the MXU starved.
            max_jobs = self.engine.cfg.engine.max_batch_rows()
        packable: List[tuple] = []  # (job, qa_id, prepared, t0)
        done = 0
        failed_ids: set = set()
        while len(packable) < max_jobs:
            if stop_event is not None and stop_event.is_set():
                # Graceful drain: stop CLAIMING; jobs already in hand below
                # still finish (stop() waits drain_grace_s for them).
                break
            job = self._claim(exclude=failed_ids)
            if job is None:
                break
            if self._check_deadline(job):
                done += 1  # terminated with a terminal push — a final state
                continue
            if job.body.get("collect_attention"):
                # attention maps are a per-request forward flag: serve solo
                if self.step_one(job) == "acked":
                    done += 1
                else:
                    failed_ids.add(job.id)  # don't spin its attempts away
                continue
            try:
                # Per-job trace scope: intake spans join the trace each job
                # carried from its own HTTP submit.
                with obs.trace_scope(job.body.get("trace_id")), \
                        obs.span("worker.intake", job_id=job.id,
                                 task_id=job.body.get("task_id", "")):
                    qa_id, prepared, t0 = self._intake(job)
                packable.append((job, qa_id, prepared, t0))
            except Exception:
                self._fail_job(job)
                failed_ids.add(job.id)
        if not packable:
            return done
        # Deadlines can lapse during intake (feature I/O) — re-check so the
        # batched forward never carries an already-dead request.
        still_live = []
        for entry in packable:
            if self._check_deadline(entry[0]):
                done += 1
            else:
                still_live.append(entry)
        packable = still_live
        if not packable:
            return done
        try:
            # One span for the shared batched forward: it serves many
            # traces at once, so it stands alone (its own trace id) with
            # the member jobs recorded as an attribute.
            t_fwd = time.perf_counter()
            with obs.span("worker.batch_forward", n_jobs=len(packable),
                          job_ids=[j.id for j, _, _, _ in packable]):
                results = self.engine.run_many(
                    [p for _, _, p, _ in packable])
            # ...and the same window attributed into each member's trace,
            # so a request's waterfall stays contiguous under batching.
            dur_fwd = time.perf_counter() - t_fwd
            for job, _, p, _ in packable:
                obs.default_tracer().record_span(
                    "worker.infer", t_fwd, dur_fwd,
                    trace_id=job.body.get("trace_id"), job_id=job.id,
                    task_id=p.spec.task_id, batched=True,
                    n_jobs=len(packable))
            # Amortize the shared forward into each member's cost record
            # (no streaming here: success means every member gets a share).
            rows_total = sum(p.n_images for _, _, p, _ in packable)
            obs.job_batch(
                dur_fwd,
                [(j.body.get("trace_id", ""), p.n_images)
                 for j, _, p, _ in packable],
                batch_rows=rows_total,
                bucket=self.engine.cfg.engine.row_bucket_for(rows_total),
                replica=getattr(self.engine, "replica_id", "") or "")
        except ReplicaFailover as e:
            # The REPLICA died under this batch, not the jobs: release the
            # whole batch for redelivery on a healthy replica. No member
            # streamed (this path has no on_result), so none is terminal yet.
            for job, _, _, _ in packable:
                self._failover_job(job, e.replica)
            return done
        except Exception:
            for job, _, _, _ in packable:
                self._fail_job(job)
            return done
        for (job, qa_id, prepared, t0), result in zip(packable, results):
            try:
                with obs.trace_scope(job.body.get("trace_id")):
                    self._finish_job(job, qa_id, prepared, result, t0)
                self.queue.ack(job.id)
                self._untrack(job.id)
                done += 1
            except Exception:
                self._fail_job(job)
        return done

    def _finish_job(self, job: Job, qa_id: int, req, result,
                    t0, attention: Optional[Dict[str, Any]] = None
                    ) -> Dict[str, Any]:
        """Marshal + persist + push for one completed request."""
        body = job.body
        socket_id = body.get("socket_id", "")
        trace_id = body.get("trace_id", "")
        t_dec = time.perf_counter()
        payload = result.to_json()
        payload["question"] = body.get("question", "")
        payload["task_name"] = req.spec.name
        if attention is not None:
            payload["attention"] = attention
        answer_images: List[str] = []
        if result.kind == "grounding" and result.boxes:
            src = req.images[0].path
            if os.path.exists(src):
                out_dir = os.path.join(self.serving.media_root,
                                       self.serving.refer_expr_dir)
                # Best-effort: jobs may reference a feature file (.npy/.vlfr)
                # rather than a decodable image — the box ANSWER is still
                # valid, only the rendered overlay is skipped.
                try:
                    answer_images = draw_grounding_boxes(
                        src, result.boxes, out_dir)
                except Exception as e:  # noqa: BLE001 — PIL raises a zoo
                    import logging

                    logging.getLogger(__name__).warning(
                        "grounding render skipped for %s: %s", src, e)
                    answer_images = []
            if answer_images:
                payload["result_images"] = answer_images
                # Web paths for the browser client (the reference hardcodes
                # a production hostname instead, result.html:116-123 — a
                # §2.4 trap knowingly fixed).
                payload["result_image_urls"] = [
                    "/media/" + "/".join(
                        (self.serving.refer_expr_dir, os.path.basename(p)))
                    for p in answer_images
                ]
        with obs.span("worker.persist", qa_id=qa_id,
                      task_id=req.spec.task_id):
            self.store.save_answer(qa_id, payload, answer_images)
        elapsed_ms = (time.perf_counter() - t0) * 1e3
        self.metrics.record(req.spec.task_id, elapsed_ms,
                            exemplar_trace_id=trace_id)
        obs.job_charge(trace_id, "decode", time.perf_counter() - t_dec)
        # Write-through BEFORE any push: once the first client can see
        # the answer, an identical submit must already be a cache hit.
        key = body.get("cache_key")
        if self.cache is not None and key:
            self.cache.complete(key, payload)
        t_push = time.perf_counter()
        with obs.span("worker.push", task_id=req.spec.task_id):
            log_to_terminal(self.hub, socket_id, {"result": payload})
            log_to_terminal(
                self.hub, socket_id,
                {"terminal": f"Task completed in {elapsed_ms:.0f} ms"})
            # Singleflight payoff: every coalesced follower gets the one
            # shared result — each charged only its own push.
            self._fan_to_followers(
                body,
                [{"result": payload},
                 {"terminal": f"Task completed in {elapsed_ms:.0f} ms "
                              "(coalesced)"}],
                verdict="ok")
        obs.job_charge(trace_id, "push", time.perf_counter() - t_push)
        obs.job_finish(trace_id, "ok")
        return payload

    def _fail_job(self, job: Job) -> str:
        """nack + telemetry; returns 'requeued' or 'dead'."""
        self.metrics_failure_for(job)
        # Freeze the evidence while the traceback is still current — by
        # the time a redelivery dead-letters, the interesting spans have
        # aged out of the ring.
        obs.record_event("worker_exception", job_id=job.id,
                         trace_id=job.body.get("trace_id"),
                         task_id=job.body.get("task_id", ""),
                         error=traceback.format_exc(limit=5))
        status = self.queue.nack(job.id)
        self._untrack(job.id)
        # A requeued attempt closes THIS record; the redelivery's claim
        # opens a fresh one under the same trace id.
        obs.job_finish(job.body.get("trace_id", ""),
                       "dead_letter" if status == "dead" else "requeued")
        if status == "dead":
            frame = {
                "terminal": "Job failed permanently.",
                "error": traceback.format_exc(limit=3),
                "question": job.body.get("question", ""),
            }
            log_to_terminal(self.hub, job.body.get("socket_id", ""), frame)
            # Dead-letter is a terminal: fan it to every coalesced
            # follower and drop the singleflight claim so the next
            # identical submit retries instead of attaching.
            self._fan_to_followers(job.body, [frame],
                                   verdict="dead_letter", drop_claim=True)
        return "requeued" if status == "pending" else status

    def step_one(self, job: Job) -> str:
        """Run one already-claimed job solo (ack/nack included).

        Returns 'acked', 'requeued', 'dead', or 'deadline'.
        """
        if self._check_deadline(job):
            return "deadline"
        try:
            self.process_job(job)
        except DeadlineExceeded:
            # The engine declined to dispatch — terminate, don't retry.
            self._expire_job(job)
            return "deadline"
        except ReplicaFailover as e:
            return self._failover_job(job, e.replica)
        except Exception:
            return self._fail_job(job)
        self.queue.ack(job.id)
        self._untrack(job.id)
        return "acked"

    def abandon_inflight(self, replica: Optional[str] = None) -> int:
        """Graceful-drain tail: release every still-claimed job back to
        pending (no delivery attempt charged — release(), not nack()) and
        tell each client its job was requeued, not lost. Returns the count.

        ``replica`` stamps WHO abandoned the job into the requeued frame
        (postmortem provenance: /debug/trace shows which replica/worker a
        bounced job last sat on). Defaults to the engine's replica id.

        At-least-once delivery makes this safe to call even for jobs that
        actually completed a moment ago: release() only touches rows still
        in 'inflight'.
        """
        if replica is None:
            replica = getattr(self.engine, "replica_id", None) or "worker"
        with self._inflight_lock:
            abandoned = list(self._inflight.values())
            self._inflight.clear()
        for job in abandoned:
            self.queue.release(job.id)
            obs.record_event("job_abandoned", job_id=job.id,
                             trace_id=job.body.get("trace_id"),
                             replica=replica)
            frame = {
                "terminal": "Server draining; job requeued for the next "
                            "worker.",
                "requeued": True,
                "abandoned_by": replica,
                "process": obs.process_identity().ident,
                "question": job.body.get("question", ""),
            }
            log_to_terminal(self.hub, job.body.get("socket_id", ""), frame)
            # Requeue, not a terminal: followers stay attached and the
            # claim survives — the next worker's terminal fans to them.
            self._fan_to_followers(job.body, [frame], final=False)
        return len(abandoned)

    def scheduler_stats(self) -> Dict[str, float]:
        """Continuous-batching scheduler state for the sampler (empty when
        running the legacy loop)."""
        sched = self.scheduler
        return sched.stats() if sched is not None else {}

    def run_forever(self, *, poll_interval_s: float = 0.05,
                    stop_event=None, batch_jobs: Optional[int] = None) -> None:
        """The consume loop (reference worker.py:672-673).

        With ``serving.sched_enabled`` (the default) this drains through
        the continuous-batching scheduler — pipelined intake, adaptive
        EDF window dispatch, async completion (serve/scheduler.py).
        Otherwise the legacy synchronous step_batch loop; ``batch_jobs``
        applies only there (defaults to the engine's largest compiled row
        bucket). ``stop_event`` is the drain signal either way: claiming
        stops the moment it is set, in-hand work finishes, and the loop
        exits clean."""
        if self.serving.sched_enabled:
            from vilbert_multitask_tpu.serve.scheduler import (
                ContinuousScheduler,
            )

            self.scheduler = ContinuousScheduler(
                self, stop_event=stop_event,
                poll_interval_s=poll_interval_s)
            try:
                self.scheduler.run()
            finally:
                self.scheduler = None
            return
        while stop_event is None or not stop_event.is_set():
            if self.step_batch(batch_jobs, stop_event=stop_event) == 0:
                time.sleep(poll_interval_s)
