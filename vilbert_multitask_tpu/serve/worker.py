"""The queue worker: claim → infer → persist → push → ack.

Reference capability: ``callback`` (reference worker.py:542-658) — the
per-message pipeline that creates the DB row, extracts features, runs the
model, marshals the per-task answer, saves, and streams progress/results to
the client's websocket group — with the §2.4 parity traps fixed:

- ack/nack is explicit and poison jobs dead-letter after N attempts
  (reference leaves them redelivering forever, worker.py:650-655);
- a failed DB insert aborts the job instead of being swallowed and crashing
  later (worker.py:548-555 vs 579);
- label maps and features are engine-cached, not re-read per request.
"""

from __future__ import annotations

import os
import time
import traceback
from typing import Any, Dict, List, Optional

from vilbert_multitask_tpu.config import ServingConfig, TASK_REGISTRY
from vilbert_multitask_tpu.engine.runtime import InferenceEngine
from vilbert_multitask_tpu.serve.db import ResultStore
from vilbert_multitask_tpu.serve.push import PushHub, log_to_terminal
from vilbert_multitask_tpu.serve.queue import DurableQueue, Job
from vilbert_multitask_tpu.serve.render import draw_grounding_boxes


class ServeWorker:
    """Single-process inference worker (one engine, one queue consumer)."""

    def __init__(
        self,
        engine: InferenceEngine,
        queue: DurableQueue,
        store: ResultStore,
        hub: PushHub,
        serving: Optional[ServingConfig] = None,
    ):
        self.engine = engine
        self.queue = queue
        self.store = store
        self.hub = hub
        self.serving = serving or ServingConfig()

    # ------------------------------------------------------------- job cycle
    def process_job(self, job: Job) -> Dict[str, Any]:
        """One message end-to-end; raises on failure (caller nacks)."""
        body = job.body
        task_id = int(body["task_id"])  # reference eval()s this str; we don't
        question = body.get("question", "")
        socket_id = body.get("socket_id", "")
        image_paths = body["image_path"]
        if isinstance(image_paths, str):
            image_paths = [image_paths]
        spec = TASK_REGISTRY[task_id]
        spec.validate_num_images(len(image_paths))

        t0 = time.perf_counter()
        log_to_terminal(self.hub, socket_id,
                        {"terminal": f"Running {spec.name} inference..."})
        # Keyed by the queue job id so redelivered attempts reuse one row.
        qa_id = self.store.create_question(task_id, question, image_paths,
                                           socket_id, queue_job_id=job.id)

        result = self.engine.predict(task_id, question, image_paths)
        payload = result.to_json()
        payload["question"] = question
        payload["task_name"] = spec.name

        answer_images: List[str] = []
        if result.kind == "grounding" and result.boxes:
            src = image_paths[0]
            if os.path.exists(src):
                out_dir = os.path.join(self.serving.media_root,
                                       self.serving.refer_expr_dir)
                answer_images = draw_grounding_boxes(src, result.boxes, out_dir)
                payload["result_images"] = answer_images

        self.store.save_answer(qa_id, payload, answer_images)
        log_to_terminal(self.hub, socket_id, {"result": payload})
        log_to_terminal(
            self.hub, socket_id,
            {"terminal": f"Task completed in "
                         f"{(time.perf_counter() - t0) * 1e3:.0f} ms"})
        return payload

    def step(self) -> Optional[str]:
        """Claim and run one job. Returns 'acked'/'requeued'/'dead'/None."""
        job = self.queue.claim()
        if job is None:
            return None
        try:
            self.process_job(job)
        except Exception:
            status = self.queue.nack(job.id)
            socket_id = job.body.get("socket_id", "")
            if status == "dead":
                log_to_terminal(
                    self.hub, socket_id,
                    {"terminal": "Job failed permanently.",
                     "error": traceback.format_exc(limit=3)})
            return "requeued" if status == "pending" else status
        self.queue.ack(job.id)
        return "acked"

    def run_forever(self, *, poll_interval_s: float = 0.05,
                    stop_event=None) -> None:
        """The consume loop (reference worker.py:672-673), poll-based."""
        while stop_event is None or not stop_event.is_set():
            if self.step() is None:
                time.sleep(poll_interval_s)
