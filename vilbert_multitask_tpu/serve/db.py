"""Result store: the ORM layer's capability on embedded sqlite.

Reference capability: the Django models (reference demo/models.py:4-46) on
PostgreSQL — ``Tasks`` (the task catalog the UI reads) and ``QuestionAnswer``
(the de-facto audit log: every job writes inputs at creation and answers on
completion, reference worker.py:548-552,579-645) — plus the admin's read path
(demo/admin.py:24-34). Credentials-in-repo (settings.py:85-94, SURVEY.md §2.4)
are gone: the store is a file next to the queue.
"""

from __future__ import annotations

import json
import os
import sqlite3
import time
from typing import Any, Dict, List, Optional

from vilbert_multitask_tpu.config import TASK_REGISTRY


class ResultStore:
    def __init__(self, path: str):
        self.path = path
        if os.path.dirname(path):
            os.makedirs(os.path.dirname(path), exist_ok=True)
        with self._conn() as c:
            # One write transaction for the whole boot migration: DDL
            # autocommits per-statement under the implicit mode, so a crash
            # or concurrent boot mid-loop would leave a half-migrated
            # schema (and race the ALTERs below).
            c.execute("BEGIN IMMEDIATE")
            c.execute(
                """CREATE TABLE IF NOT EXISTS tasks (
                    unique_id INTEGER PRIMARY KEY,
                    name TEXT NOT NULL,
                    placeholder TEXT,
                    description TEXT,
                    num_of_images INTEGER NOT NULL
                )"""
            )
            c.execute(
                """CREATE TABLE IF NOT EXISTS question_answers (
                    id INTEGER PRIMARY KEY AUTOINCREMENT,
                    task_id INTEGER NOT NULL,
                    input_text TEXT,
                    input_images TEXT,
                    answer_text TEXT,
                    answer_images TEXT,
                    socket_id TEXT,
                    queue_job_id INTEGER,
                    created_at REAL NOT NULL,
                    modified_at REAL NOT NULL
                )"""
            )
            c.execute(
                "CREATE UNIQUE INDEX IF NOT EXISTS qa_by_job ON "
                "question_answers (queue_job_id) WHERE queue_job_id IS NOT NULL"
            )
            # In-code migration (component row 14): the min/max image-count
            # columns drive the browser's task gating; ``edited`` marks rows
            # an admin changed by hand. Older stores get them added in place.
            for col, decl in (("num_of_images_min", "INTEGER"),
                              ("num_of_images_max", "INTEGER"),
                              ("edited", "INTEGER DEFAULT 0")):
                try:
                    c.execute(f"ALTER TABLE tasks ADD COLUMN {col} {decl}")
                except sqlite3.OperationalError as e:
                    # Only the idempotent-rerun case is expected; anything
                    # else (locked, corrupt, disk) must surface.
                    if "duplicate column" not in str(e).lower():
                        raise
            # Seed/refresh the task catalog from the typed registry (replaces
            # the reference's hand-entered admin rows, demo/models.py:4-20).
            # The registry is the source of truth on boot — EXCEPT for rows
            # an admin edited (reference parity: Django admin edits persist
            # across restarts, demo/admin.py:11-21).
            for spec in TASK_REGISTRY.values():
                c.execute(
                    "INSERT INTO tasks (unique_id, name, placeholder, "
                    "description, num_of_images, num_of_images_min, "
                    "num_of_images_max) VALUES (?, ?, ?, ?, ?, ?, ?) "
                    "ON CONFLICT(unique_id) DO UPDATE SET name=excluded.name, "
                    "placeholder=excluded.placeholder, "
                    "description=excluded.description, "
                    "num_of_images=excluded.num_of_images, "
                    "num_of_images_min=excluded.num_of_images_min, "
                    "num_of_images_max=excluded.num_of_images_max "
                    "WHERE COALESCE(tasks.edited, 0)=0",
                    (spec.task_id, spec.name, spec.placeholder,
                     spec.description, spec.max_images, spec.min_images,
                     spec.max_images),
                )

    def _conn(self) -> sqlite3.Connection:
        conn = sqlite3.connect(self.path, timeout=30.0)
        conn.execute("PRAGMA journal_mode=WAL")
        return conn

    # ------------------------------------------------------------------ tasks
    _TASK_COLS = ("unique_id", "name", "placeholder", "description",
                  "num_of_images", "num_of_images_min", "num_of_images_max")

    def get_task(self, task_id: int) -> Optional[Dict[str, Any]]:
        with self._conn() as c:
            row = c.execute(
                f"SELECT {', '.join(self._TASK_COLS)} FROM tasks "
                "WHERE unique_id=?",
                (task_id,),
            ).fetchone()
        return None if row is None else dict(zip(self._TASK_COLS, row))

    def list_tasks(self) -> List[Dict[str, Any]]:
        with self._conn() as c:
            rows = c.execute(
                f"SELECT {', '.join(self._TASK_COLS)} FROM tasks "
                "ORDER BY unique_id"
            ).fetchall()
        return [dict(zip(self._TASK_COLS, r)) for r in rows]

    # The admin's writable surface (reference demo/admin.py:11-21: Django
    # TaskAdmin exposes exactly the catalog fields for editing). unique_id
    # is the registry key and stays immutable.
    _TASK_EDITABLE = {"name", "placeholder", "description", "num_of_images",
                      "num_of_images_min", "num_of_images_max"}
    _TASK_INT_FIELDS = {"num_of_images", "num_of_images_min",
                        "num_of_images_max"}

    def update_task(self, task_id: int,
                    fields: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        """Admin edit of a catalog row; marks it ``edited`` so the boot-time
        registry reseed leaves it alone. Returns the updated row, or None if
        the task doesn't exist. Raises ValueError on unknown/ill-typed
        fields — admin typos should bounce, not half-apply."""
        unknown = set(fields) - self._TASK_EDITABLE
        if unknown or not fields:
            raise ValueError(
                f"editable fields are {sorted(self._TASK_EDITABLE)}; "
                f"got {sorted(fields) or 'nothing'}")
        clean: Dict[str, Any] = {}
        for k, v in fields.items():
            if k in self._TASK_INT_FIELDS:
                if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                    raise ValueError(f"{k} must be a non-negative int")
            elif not isinstance(v, str):
                raise ValueError(f"{k} must be a string")
            clean[k] = v
        current = self.get_task(task_id)
        if current is None:
            return None
        # Cross-field sanity on the merged row: an inverted min/max range
        # would make the task unselectable in the browser's gating — and
        # edited=1 means the boot reseed would never repair it.
        merged = {**current, **clean}
        lo = merged.get("num_of_images_min")
        hi = merged.get("num_of_images_max")
        if lo is not None and hi is not None and lo > hi:
            raise ValueError(
                f"num_of_images_min ({lo}) > num_of_images_max ({hi})")
        with self._conn() as c:
            cur = c.execute(
                "UPDATE tasks SET "
                + ", ".join(f"{k}=?" for k in clean)
                + ", edited=1 WHERE unique_id=?",
                (*clean.values(), task_id),
            )
            if cur.rowcount == 0:
                return None
        return self.get_task(task_id)

    # --------------------------------------------------------------- QA rows
    def create_question(self, task_id: int, input_text: str,
                        input_images: List[str], socket_id: str,
                        queue_job_id: Optional[int] = None) -> int:
        """Job intake row (reference worker.py:548-552).

        When ``queue_job_id`` is given, redelivered attempts of the same
        queued job reuse the original row instead of inserting duplicates.
        """
        now = time.time()
        with self._conn() as c:
            # The dedup probe below is a read-modify-write: without the
            # write lock, two redeliveries of the same job could both miss
            # the probe and race the INSERT (one dies on the qa_by_job
            # unique index instead of reusing the row).
            c.execute("BEGIN IMMEDIATE")
            if queue_job_id is not None:
                row = c.execute(
                    "SELECT id FROM question_answers WHERE queue_job_id=?",
                    (queue_job_id,),
                ).fetchone()
                if row is not None:
                    return int(row[0])
            cur = c.execute(
                "INSERT INTO question_answers (task_id, input_text, "
                "input_images, socket_id, queue_job_id, created_at, "
                "modified_at) VALUES (?, ?, ?, ?, ?, ?, ?)",
                (task_id, input_text, json.dumps(list(input_images)),
                 socket_id, queue_job_id, now, now),
            )
            return int(cur.lastrowid)

    def save_answer(self, qa_id: int, answer: Dict[str, Any],
                    answer_images: Optional[List[str]] = None) -> None:
        """Completion update (reference worker.py:579,606,623,644)."""
        with self._conn() as c:
            c.execute(
                "UPDATE question_answers SET answer_text=?, answer_images=?, "
                "modified_at=? WHERE id=?",
                (json.dumps(answer), json.dumps(answer_images or []),
                 time.time(), qa_id),
            )

    _QA_COLS = ("id", "task_id", "input_text", "input_images", "answer_text",
                "answer_images", "socket_id", "created_at", "modified_at")

    @classmethod
    def _qa_row(cls, row) -> Dict[str, Any]:
        d = dict(zip(cls._QA_COLS, row))
        for k in ("input_images", "answer_text", "answer_images"):
            if d[k]:
                d[k] = json.loads(d[k])
        return d

    def get_question(self, qa_id: int) -> Optional[Dict[str, Any]]:
        with self._conn() as c:
            row = c.execute(
                f"SELECT {', '.join(self._QA_COLS)} FROM question_answers "
                "WHERE id=?",
                (qa_id,),
            ).fetchone()
        return None if row is None else self._qa_row(row)

    def update_question(self, qa_id: int,
                        fields: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        """Admin correction of an audit row (reference demo/admin.py:24-34:
        QuestionAnswer is registered in the Django admin, so its text fields
        are editable there). Only the human-readable text fields are open;
        images/socket/job linkage stay immutable. Returns the updated row
        (scrub socket_id at the API layer), None if the row doesn't exist."""
        editable = {"input_text", "answer_text"}
        unknown = set(fields) - editable
        if unknown or not fields:
            raise ValueError(
                f"editable fields are {sorted(editable)}; "
                f"got {sorted(fields) or 'nothing'}")
        sets, vals = [], []
        if "input_text" in fields:
            if not isinstance(fields["input_text"], str):
                raise ValueError("input_text must be a string")
            sets.append("input_text=?")
            vals.append(fields["input_text"])
        if "answer_text" in fields:
            # Stored as JSON, same as save_answer — accepts the same shapes
            # the decode families emit (dict/list/str).
            sets.append("answer_text=?")
            vals.append(json.dumps(fields["answer_text"]))
        with self._conn() as c:
            cur = c.execute(
                f"UPDATE question_answers SET {', '.join(sets)}, "
                "modified_at=? WHERE id=?",
                (*vals, time.time(), qa_id),
            )
            if cur.rowcount == 0:
                return None
        return self.get_question(qa_id)

    def recent(self, limit: int = 50) -> List[Dict[str, Any]]:
        """Latest jobs, newest first (the admin list view's read,
        demo/admin.py:24-34)."""
        with self._conn() as c:
            rows = c.execute(
                f"SELECT {', '.join(self._QA_COLS)} FROM question_answers "
                "ORDER BY id DESC LIMIT ?",
                (limit,),
            ).fetchall()
        return [self._qa_row(r) for r in rows]
