"""Weight-only int8 quantization for the serving param store.

``EngineConfig.param_dtype="int8"`` halves weight bytes AGAIN over bf16
(PR 4): every floating matrix leaf is stored as a per-channel symmetric
``{"int8": values, "scale": f32}`` pair and dequantized *inside* the jitted
forward (engine/runtime.py), immediately before the matmul that consumes
it — HBM reads stay int8, the MXU sees bf16/f32. The trainer never sees
this module: f32 masters stay f32; quantization happens only at the
serving cast seam (parallel/sharding.py:cast_floating).

Scheme — per-channel (last-axis) symmetric:

- ``scale[c] = max(|x[..., c]|) / 127`` over all non-last axes (a zero
  column gets scale 1.0 so the divide is safe and round-trips to zeros);
- ``q = clip(round(x / scale), -127, 127).astype(int8)`` — same shape as
  the source leaf, so sharding rules keyed on the path still fit;
- dequant: ``q.astype(compute_dtype) * scale.astype(compute_dtype)``.

Only leaves with ``ndim >= 2`` are quantized (kernels, embedding tables).
Vectors — biases, LayerNorm scales — stay floating: they are a rounding
error of the byte budget and per-channel scales would degenerate to
per-element there.

A quantized pair is a plain dict, so the tree stays an ordinary pytree:
Orbax round-trips it, ``jax.device_put`` places it, and
``engine/flops.py:param_tree_bytes`` sums the int8 values + f32 scales
with no special casing — the roofline is dtype-aware for free.

Host/device duality: numpy leaves are quantized with numpy ops (the
checkpoint-restore and boot paths stay host-side — no device transfer
before placement), jax arrays/tracers with jnp ops.
"""

from __future__ import annotations

from typing import Any

import numpy as np

QVALUES = "int8"
QSCALE = "scale"

_QKEYS = frozenset((QVALUES, QSCALE))


def is_quantized_leaf(x: Any) -> bool:
    """True for one ``{"int8": values, "scale": scales}`` pair."""
    return isinstance(x, dict) and set(x.keys()) == _QKEYS


def tree_is_quantized(params: Any) -> bool:
    """True when the tree holds at least one quantized pair (the served
    storage mode is int8). Cheap: walks the python structure, not data."""
    import jax

    found = False

    # Probe with pairs as leaves: matching on PAIR STRUCTURE, not leaf
    # names — "scale" is also every LayerNorm leaf's name, so a name probe
    # would misreport any unquantized flax tree as quantized.
    def probe(leaf):
        nonlocal found
        found = found or is_quantized_leaf(leaf)
        return leaf

    jax.tree_util.tree_map(probe, params, is_leaf=is_quantized_leaf)
    return found


def quantize_leaf(x: Any) -> dict:
    """One floating leaf (ndim >= 2) -> ``{"int8": q, "scale": s}``.

    ``q`` keeps the leaf's shape; ``s`` is f32 of shape ``(last_dim,)``.
    Numpy in, numpy out (host path); jax in, jax out (tracer/device path).
    """
    if isinstance(x, np.ndarray):
        xp = np
        # Unreachable under tracing (a tracer is never np.ndarray) — this
        # branch is the host path only.
        xf = np.asarray(x, np.float32)  # vmtlint: disable=VMT101
    else:
        import jax.numpy as jnp

        xp = jnp
        xf = x.astype(jnp.float32)
    axes = tuple(range(xf.ndim - 1))
    amax = xp.max(xp.abs(xf), axis=axes)
    scale = xp.where(amax == 0.0, xp.ones_like(amax), amax / 127.0)
    scale = scale.astype(np.float32 if xp is np else xp.float32)
    q = xp.clip(xp.round(xf / scale), -127, 127).astype(np.int8)
    return {QVALUES: q, QSCALE: scale}


def dequantize_leaf(pair: dict, dtype) -> Any:
    """``{"int8", "scale"}`` -> dense array in ``dtype``. Runs inside the
    jitted forward (fused with the consuming matmul by XLA); calling it on
    host arrays outside jit re-inflates HBM traffic — vmtlint VMT118."""
    q, s = pair[QVALUES], pair[QSCALE]
    return q.astype(dtype) * s.astype(dtype)


def quantize_tree(params: Any) -> Any:
    """Quantize every floating ``ndim >= 2`` leaf; idempotent — already
    quantized pairs pass through untouched, so the checkpoint-restore ->
    ``load_params`` double cast is safe."""
    import jax

    def one(x):
        if is_quantized_leaf(x):
            return x
        dt = np.dtype(x.dtype)
        if dt.kind == "f" and getattr(x, "ndim", 0) >= 2:
            return quantize_leaf(x)
        return x

    return jax.tree_util.tree_map(one, params, is_leaf=is_quantized_leaf)


def dequantize_tree(params: Any, dtype) -> Any:
    """Expand every quantized pair back to a dense ``dtype`` array and cast
    the remaining floating leaves to match — the in-jit view the forward
    computes with. Non-quantized trees pass through (modulo the cast), so
    one code path serves both storage modes."""
    import jax
    import jax.numpy as jnp

    dt = jnp.dtype(dtype)

    def one(x):
        if is_quantized_leaf(x):
            return dequantize_leaf(x, dt)
        if jnp.issubdtype(x.dtype, jnp.floating) and x.dtype != dt:
            return x.astype(dt)
        return x

    return jax.tree_util.tree_map(one, params, is_leaf=is_quantized_leaf)
