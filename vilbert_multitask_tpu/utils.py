"""Small shared utilities with security-relevant, must-not-diverge logic."""

from __future__ import annotations

import os
from typing import Optional


def contained_path(root: str, candidate: str) -> Optional[str]:
    """Resolve ``candidate`` and return its realpath iff it stays under
    ``root`` — else None.

    The single containment rule for client-influenced filesystem access:
    the HTTP media handler (serve/http_api.py) and the live-extraction
    fallback store (detect/extractor.py) both route through here, so a
    future hardening (symlink policy, drive handling) lands in one place.
    """
    real_root = os.path.realpath(root)
    full = os.path.realpath(candidate)
    try:
        if os.path.commonpath([real_root, full]) != real_root:
            return None
    except ValueError:  # different drives / mixed abs-rel (windows)
        return None
    return full
