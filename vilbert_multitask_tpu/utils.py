"""Small shared utilities: single-home logic used across tiers — the
security-relevant path-containment rule and the indexed dataset reader."""

from __future__ import annotations

import json
import os
import threading
from typing import Optional


def contained_path(root: str, candidate: str) -> Optional[str]:
    """Resolve ``candidate`` and return its realpath iff it stays under
    ``root`` — else None.

    The single containment rule for client-influenced filesystem access:
    the HTTP media handler (serve/http_api.py) and the live-extraction
    fallback store (detect/extractor.py) both route through here, so a
    future hardening (symlink policy, drive handling) lands in one place.
    """
    real_root = os.path.realpath(root)
    full = os.path.realpath(candidate)
    try:
        if os.path.commonpath([real_root, full]) != real_root:
            return None
    except ValueError:  # different drives / mixed abs-rel (windows)
        return None
    return full


class IndexedJsonl:
    """Random-access JSONL without loading the dataset into memory.

    One startup scan records byte offsets of non-empty lines; reads seek
    and parse on demand. At 12-in-1 training scale (hundreds of thousands
    to millions of examples — e.g. Conceptual Captions) the resident cost
    is one int per line instead of every parsed record, which is what lets
    JsonlTaskData's stateless random draws (train/loop.py) run over real
    dataset sizes. The file must not change underneath (offsets are
    captured once); parsing is per-access, so hot loops that revisit few
    indices can wrap accesses in their own cache.
    """

    def __init__(self, path: str):
        self.path = path
        offsets = []
        with open(path, "rb") as f:
            pos = f.tell()
            for raw in f:
                if raw.strip():
                    offsets.append(pos)
                pos += len(raw)
        self._offsets = offsets
        self._f = open(path, "rb")
        # seek()+readline() is a two-step critical section on ONE shared
        # handle: two readers interleaving (a threaded loader, two samplers
        # over one dataset) would parse lines at the wrong offsets.
        self._lock = threading.Lock()

    def __len__(self) -> int:
        return len(self._offsets)

    def __getitem__(self, i: int):
        if not -len(self) <= i < len(self):
            raise IndexError(i)
        with self._lock:
            self._f.seek(self._offsets[i])
            raw = self._f.readline()
        return json.loads(raw)

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def close(self) -> None:
        self._f.close()

    def __enter__(self) -> "IndexedJsonl":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):  # belt-and-braces; close() is the real contract
        try:
            self._f.close()
        except Exception:
            pass
