"""Offline region-feature extractor: raw detector dumps → feature stores.

Reference capability: the serving-path Faster R-CNN feature extraction
(reference worker.py:59-223). Per BASELINE.json, serving reads precomputed
features; this CLI is the offline half that produces them, reproducing the
reference's post-processing exactly (SURVEY.md §7 hard part (b)):

- image preprocessing contract (worker.py:91-121): RGB→BGR channel order,
  per-channel mean subtraction, scale so the short side targets 800 px
  without the long side passing 1333 (helper :func:`preprocess_image`, for
  wiring an actual detector);
- per-class NMS@0.5 over the ~1601 class scores + top-100 selection by max
  surviving confidence (worker.py:123-176) — via the native C++ path when
  built, else the vectorized JAX path (ops/nms.py);
- output in the reference ``.npy`` dict schema (worker.py:209-216) or the
  packed ``.vlfr`` format.

Input: one ``.npz`` per image with arrays ``boxes (N,4)`` (pixel xyxy),
``cls_scores (N,C)`` (softmaxed, column 0 = background), ``features (N,D)``
(fc6), and scalars ``image_width``, ``image_height`` — the tensors any
detector (torch, JAX, or a saved dump) can emit.
"""

from __future__ import annotations

import argparse
import glob
import os
from typing import Tuple

import numpy as np

from vilbert_multitask_tpu.features.pipeline import RegionFeatures
from vilbert_multitask_tpu.features.store import save_reference_npy, save_vlfr

# Per-channel BGR means the reference subtracts (maskrcnn PIXEL_MEAN
# convention driven from worker.py:102-107).
BGR_PIXEL_MEANS = np.array([102.9801, 115.9465, 122.7717], np.float32)


def preprocess_image(
    image: np.ndarray,  # (H, W, 3) RGB uint8
    min_size: int = 800,
    max_size: int = 1333,
) -> Tuple[np.ndarray, float]:
    """RGB image → (BGR float32 mean-subtracted resized, scale).

    Matches the reference's transform semantics (worker.py:91-121): BGR
    channel flip, mean subtraction, short-side 800 scaling clamped so the
    long side stays ≤ 1333. Uses PIL bilinear resize.
    """
    from PIL import Image

    h, w = image.shape[:2]
    scale = min_size / min(h, w)
    if max(h, w) * scale > max_size:
        scale = max_size / max(h, w)
    new_w, new_h = int(round(w * scale)), int(round(h * scale))
    resized = np.asarray(
        Image.fromarray(image).resize((new_w, new_h), Image.BILINEAR),
        np.float32,
    )
    bgr = resized[:, :, ::-1] - BGR_PIXEL_MEANS
    return bgr, scale


def select_regions(boxes: np.ndarray, cls_scores: np.ndarray,
                   num_keep: int = 100, iou_threshold: float = 0.5):
    """Native C++ selection when built, JAX otherwise; identical semantics."""
    from vilbert_multitask_tpu import native

    if native.available():
        return native.select_top_regions(
            boxes, cls_scores, num_keep=num_keep, iou_threshold=iou_threshold)
    from vilbert_multitask_tpu.ops import nms as jnms

    return tuple(
        np.asarray(x) for x in jnms.select_top_regions(
            boxes, cls_scores, num_keep=num_keep, iou_threshold=iou_threshold)
    )


def extract_one(raw_path: str, out_dir: str, fmt: str = "npy",
                num_keep: int = 100, iou_threshold: float = 0.5) -> str:
    """One ``.npz`` detector dump → one feature file. Returns the out path."""
    raw = np.load(raw_path)
    boxes = np.asarray(raw["boxes"], np.float32)
    cls_scores = np.asarray(raw["cls_scores"], np.float32)
    features = np.asarray(raw["features"], np.float32)
    w, h = int(raw["image_width"]), int(raw["image_height"])

    keep, num_valid, _conf, objects, _max_conf = select_regions(
        boxes, cls_scores, num_keep=num_keep, iou_threshold=iou_threshold)
    n = int(min(num_valid, len(keep))) or 1  # at least one region
    keep = np.asarray(keep[:n])
    region = RegionFeatures(
        features=features[keep], boxes=boxes[keep],
        image_width=w, image_height=h, num_boxes=n)

    key = os.path.splitext(os.path.basename(raw_path))[0]
    os.makedirs(out_dir, exist_ok=True)
    out_path = os.path.join(out_dir, f"{key}.{fmt}")
    if fmt == "npy":
        # cls_prob = the FULL per-region class distribution rows (reference
        # schema; also the MRM pretraining target) — select_regions' last
        # return is the per-box max confidence, a different quantity.
        save_reference_npy(out_path, region, key,
                           objects=np.asarray(objects[:n]),
                           cls_prob=cls_scores[keep])
    elif fmt == "vlfr":
        save_vlfr(out_path, region)
    else:
        raise ValueError(f"unknown format {fmt!r} (npy|vlfr)")
    return out_path


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description="offline feature extraction")
    p.add_argument("--raw", required=True,
                   help="detector-dump .npz file, directory, or glob")
    p.add_argument("--out", required=True, help="output feature directory")
    p.add_argument("--format", default="npy", choices=("npy", "vlfr"))
    p.add_argument("--num-keep", type=int, default=100)
    p.add_argument("--iou-threshold", type=float, default=0.5)
    args = p.parse_args(argv)

    if os.path.isdir(args.raw):
        paths = sorted(glob.glob(os.path.join(args.raw, "*.npz")))
    elif any(ch in args.raw for ch in "*?["):
        paths = sorted(glob.glob(args.raw))
    else:
        paths = [args.raw]
    for path in paths:
        out = extract_one(path, args.out, args.format,
                          args.num_keep, args.iou_threshold)
        print(out)


if __name__ == "__main__":
    main()
