"""Host-side region-feature preprocessing: detector output → fixed-shape
batch buffers.

Reference capability: the image half of ``custom_prediction`` (reference
worker.py:421-455):

- mean-pool the region features into a global feature and prepend it
  (worker.py:432-434);
- 5-dim spatial encoding per box: [x1/w, y1/h, x2/w, y2/h, area_fraction]
  with the global box [0, 0, 1, 1, 1] prepended (worker.py:436-444);
- image mask 1 per real region (worker.py:445);
- co-attention mask is all zeros at serving time (worker.py:455).

TPU-first divergence: buffers are padded to a static ``max_regions`` (101 =
100 detector boxes + global, reference worker.py:71,433) so every request
compiles to the same XLA program; the reference instead shipped whatever
dynamic shape the detector produced.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import numpy as np


@dataclasses.dataclass
class RegionFeatures:
    """One image's detector output (the `.npy` schema fields that matter,
    reference worker.py:209-216).

    ``cls_prob`` (the detector's per-region class distribution, also in the
    reference schema) is optional — serving never reads it, but the
    masked-region pretraining objective uses it as the soft target
    (train/losses.py masked_region_loss)."""

    features: np.ndarray  # (num_boxes, feat_dim) fc6 features
    boxes: np.ndarray  # (num_boxes, 4) absolute xyxy pixel coords
    image_width: int
    image_height: int
    num_boxes: int | None = None  # defaults to features.shape[0]
    cls_prob: np.ndarray | None = None  # (num_boxes, n_classes) detector dist

    def __post_init__(self):
        if self.num_boxes is None:
            self.num_boxes = int(self.features.shape[0])


@dataclasses.dataclass
class EncodedImage:
    """Fixed-shape buffers for one image, ready to batch."""

    features: np.ndarray  # (max_regions, feat_dim) f32
    spatials: np.ndarray  # (max_regions, 5) f32
    image_mask: np.ndarray  # (max_regions,) i32


def build_spatials(boxes: np.ndarray, image_w: float, image_h: float) -> np.ndarray:
    """(N, 4) absolute xyxy → (N, 5) normalized [x1, y1, x2, y2, area_frac]."""
    out = np.zeros((boxes.shape[0], 5), np.float32)
    out[:, 0] = boxes[:, 0] / image_w
    out[:, 1] = boxes[:, 1] / image_h
    out[:, 2] = boxes[:, 2] / image_w
    out[:, 3] = boxes[:, 3] / image_h
    out[:, 4] = (
        (boxes[:, 3] - boxes[:, 1]) * (boxes[:, 2] - boxes[:, 0])
    ) / (image_w * image_h)
    return out


GLOBAL_BOX = np.array([0.0, 0.0, 1.0, 1.0, 1.0], np.float32)


def encode_image(region: RegionFeatures, max_regions: int = 101) -> EncodedImage:
    """Prepend global feature + pad to ``max_regions``."""
    n = int(region.num_boxes)
    feats = np.asarray(region.features[:n], np.float32)
    if n + 1 > max_regions:
        raise ValueError(f"{n} boxes + global exceeds max_regions={max_regions}")

    g_feat = feats.sum(axis=0, keepdims=True) / max(n, 1)
    spatials = build_spatials(np.asarray(region.boxes[:n], np.float32),
                              float(region.image_width), float(region.image_height))

    feat_dim = feats.shape[1]
    out_feats = np.zeros((max_regions, feat_dim), np.float32)
    out_feats[0] = g_feat
    out_feats[1 : n + 1] = feats
    out_spatials = np.zeros((max_regions, 5), np.float32)
    out_spatials[0] = GLOBAL_BOX
    out_spatials[1 : n + 1] = spatials
    mask = np.zeros((max_regions,), np.int32)
    mask[: n + 1] = 1
    return EncodedImage(out_feats, out_spatials, mask)


def clip_regions(regions: Sequence[RegionFeatures],
                 max_regions: int,
                 num_features: Optional[int] = None) -> list[RegionFeatures]:
    """Clip over-provisioned region sets to the budget (``max_regions`` - 1
    detector rows + the global row, tightened by ``num_features`` when the
    operator wants fewer boxes than the padded shape admits). Stores are
    confidence-ordered, so the clip keeps the top boxes. The ONE clip
    implementation — serving (engine.prepare) and training (train/loop)
    both use it, so a new per-region field only needs slicing here."""
    budget = max_regions - 1
    if num_features is not None:
        budget = min(budget, num_features)
    return [
        dataclasses.replace(
            r, features=r.features[:budget], boxes=r.boxes[:budget],
            num_boxes=min(r.num_boxes, budget),
            cls_prob=r.cls_prob[:budget] if r.cls_prob is not None else None)
        if r.num_boxes > budget else r
        for r in regions
    ]


def batch_images(
    images: Sequence[EncodedImage], pad_to: int | None = None
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Stack per-image buffers into (B, ...) arrays, optionally padding the
    batch dimension to a shape bucket (engine shape-bucket discipline)."""
    B = len(images)
    n = pad_to or B
    if n < B:
        raise ValueError(f"pad_to={pad_to} smaller than batch {B}")
    feat_dim = images[0].features.shape[-1]
    max_regions = images[0].features.shape[0]
    feats = np.zeros((n, max_regions, feat_dim), np.float32)
    spatials = np.zeros((n, max_regions, 5), np.float32)
    masks = np.zeros((n, max_regions), np.int32)
    for i, img in enumerate(images):
        feats[i] = img.features
        spatials[i] = img.spatials
        masks[i] = img.image_mask
    # Padded batch rows keep a single attended global region so softmaxes
    # stay well-defined; results for pad rows are discarded at decode.
    for i in range(B, n):
        masks[i, 0] = 1
        spatials[i, 0] = GLOBAL_BOX
    return feats, spatials, masks


def synthetic_regions(v_feature_size: int, *, n_boxes: int = 100,
                      rng=None, seed: int = 0,
                      image_w: int = 640, image_h: int = 480
                      ) -> RegionFeatures:
    """Plausibly-shaped random regions (x2>x1/y2>y1 boxes anchored inside
    the canvas — they may overhang the right/bottom edge, like loose
    detector output — N(0,1) features) for benches, smokes, and demos:
    the shared synthetic-input generator (bench round-robin, onboarding
    smoke). Not a source of normalized-spatial guarantees."""
    rng = rng or np.random.default_rng(seed)
    x1 = rng.random((n_boxes,)) * (image_w - 32)
    y1 = rng.random((n_boxes,)) * (image_h - 32)
    boxes = np.stack(
        [x1, y1, x1 + 16 + rng.random(n_boxes) * (image_w / 4),
         y1 + 16 + rng.random(n_boxes) * (image_h / 4)],
        axis=1).astype(np.float32)
    feats = rng.normal(size=(n_boxes, v_feature_size)).astype(np.float32)
    return RegionFeatures(feats, boxes, image_w, image_h)
