"""Precomputed region-feature store.

Per BASELINE.json, the GPU Faster R-CNN in the serving loop (reference
worker.py:59-223) is replaced by a precomputed-feature loader. Two formats:

1. The reference ``.npy`` schema — a pickled dict per image with keys
   ``image_id, features[N,2048], bbox[N,4], num_boxes, objects, cls_prob,
   image_width, image_height`` (written at reference worker.py:209-216) —
   so feature dumps produced by the reference tooling drop straight in.
2. A packed little-endian binary format (``.vlfr``) with a fixed header,
   designed for mmap-friendly zero-copy reads; the C++ fast loader in
   ``native/feature_store.cpp`` reads it without the pickle machinery.

The store is keyed the way the reference keys features: by image-file
basename without extension (worker.py:210-211).
"""

from __future__ import annotations

import os
import struct
from collections import OrderedDict
from typing import Dict, Iterable

import numpy as np

from vilbert_multitask_tpu.features.pipeline import RegionFeatures

_VLFR_MAGIC = b"VLFR\x01"


def load_reference_npy(path: str) -> RegionFeatures:
    """Read one image's features in the reference ``.npy`` dict schema."""
    raw = np.load(path, allow_pickle=True).item()
    cls_prob = np.asarray(raw.get("cls_prob", ()), np.float32)
    return RegionFeatures(
        features=np.asarray(raw["features"], np.float32),
        boxes=np.asarray(raw["bbox"], np.float32),
        image_width=int(raw["image_width"]),
        image_height=int(raw["image_height"]),
        num_boxes=int(raw.get("num_boxes", len(raw["features"]))),
        cls_prob=cls_prob if cls_prob.size else None,
    )


def save_reference_npy(path: str, region: RegionFeatures, image_id: str,
                       objects: np.ndarray | None = None,
                       cls_prob: np.ndarray | None = None) -> None:
    """Write the reference schema (what the offline extractor emits)."""
    info = {
        "image_id": image_id,
        "features": np.asarray(region.features, np.float32),
        "bbox": np.asarray(region.boxes, np.float32),
        "num_boxes": int(region.num_boxes),
        "image_width": int(region.image_width),
        "image_height": int(region.image_height),
        "objects": objects if objects is not None else np.zeros((0,), np.int64),
        "cls_prob": (cls_prob if cls_prob is not None
                     else region.cls_prob if region.cls_prob is not None
                     else np.zeros((0, 0), np.float32)),
    }
    np.save(path, info)


def save_vlfr(path: str, region: RegionFeatures) -> None:
    """Packed binary: header(magic, n, d, w, h) + f32 features + f32 boxes.

    The format carries the SERVING fields only — ``cls_prob`` (the MRM
    pretraining target) is dropped; a pretraining run against a .vlfr
    store falls back to uniform targets, so warn when it's discarded here.
    """
    if region.cls_prob is not None:
        import logging

        logging.getLogger(__name__).warning(
            ".vlfr stores no cls_prob: %s loses the detector class "
            "distribution — MRM pretraining against this store will use "
            "uniform targets (keep the .npy for pretraining data)", path)
    feats = np.ascontiguousarray(region.features, dtype="<f4")
    boxes = np.ascontiguousarray(region.boxes, dtype="<f4")
    n, d = feats.shape
    with open(path, "wb") as f:
        f.write(_VLFR_MAGIC)
        f.write(struct.pack("<IIII", n, d, int(region.image_width),
                            int(region.image_height)))
        f.write(feats.tobytes())
        f.write(boxes.tobytes())


def load_vlfr(path: str) -> RegionFeatures:
    with open(path, "rb") as f:
        magic = f.read(5)
        if magic != _VLFR_MAGIC:
            raise ValueError(f"{path}: not a VLFR file")
        n, d, w, h = struct.unpack("<IIII", f.read(16))
        feats = np.frombuffer(f.read(n * d * 4), dtype="<f4").reshape(n, d)
        boxes = np.frombuffer(f.read(n * 4 * 4), dtype="<f4").reshape(n, 4)
    return RegionFeatures(features=feats.copy(), boxes=boxes.copy(),
                          image_width=w, image_height=h, num_boxes=n)


def image_key(image_path: str) -> str:
    """Image path → store key (basename sans extension, worker.py:210-211)."""
    return os.path.basename(image_path).split(".")[0]


def file_identity(path: str) -> str:
    """Content-stable cache identity for a file: path + mtime + size."""
    st = os.stat(path)
    return f"{path}:{st.st_mtime_ns}:{st.st_size}"


class FeatureStore:
    """Directory-backed feature store with an LRU cache.

    Fixes a reference inefficiency while keeping its contract: the reference
    re-reads label pickles and feature data per request (SURVEY.md §2.4);
    here repeated images hit the in-memory LRU.
    """

    def __init__(self, root: str, max_cached: int = 256):
        self.root = root
        self.max_cached = max_cached
        self._cache: "OrderedDict[str, RegionFeatures]" = OrderedDict()
        # Probe (and if needed build) the native reader at construction —
        # boot-time cost, so the first request never pays the g++ build —
        # but only when this store actually holds .vlfr files.
        self._native_ok = False
        if self._has_vlfr():
            from vilbert_multitask_tpu import native

            self._native_ok = native.available()

    def _has_vlfr(self) -> bool:
        try:
            with os.scandir(self.root) as it:
                return any(e.name.endswith(".vlfr") for e in it)
        except OSError:
            return False

    def path_for(self, key: str) -> str:
        for ext, loader in ((".npy", load_reference_npy), (".vlfr", load_vlfr)):
            p = os.path.join(self.root, key + ext)
            if os.path.exists(p):
                return p
        raise FileNotFoundError(
            f"no feature file for key '{key}' under {self.root} (.npy/.vlfr)"
        )

    def identity(self, image_path: str) -> str:
        """Content-stable identity for this image's features: resolved file
        path + mtime + size. Cache layers (the host LRU here, the engine's
        device input cache) key on this so a replaced/edited feature file
        is a cache MISS, never silently served stale."""
        return file_identity(self.path_for(image_key(image_path)))

    def fetch(self, image_path: str) -> tuple[RegionFeatures, str]:
        """(features, content identity) — the identity is captured BEFORE
        the read, so a file replaced mid-request can at worst bind an OLD
        key to NEW content (which the next request's fresh stat misses and
        re-reads), never a new key to stale content."""
        path = self.path_for(image_key(image_path))
        key = file_identity(path)
        if key in self._cache:
            self._cache.move_to_end(key)
            return self._cache[key], key
        if path.endswith(".npy"):
            region = load_reference_npy(path)
        elif self._native_ok:
            from vilbert_multitask_tpu import native

            region = native.read_vlfr(path)
        else:
            region = load_vlfr(path)
        self._cache[key] = region
        if len(self._cache) > self.max_cached:
            self._cache.popitem(last=False)
        return region, key

    def get(self, image_path: str) -> RegionFeatures:
        return self.fetch(image_path)[0]

    def get_batch(self, image_paths: Iterable[str]) -> list[RegionFeatures]:
        return [self.get(p) for p in image_paths]
