"""Two-stream encoder with interleaved co-attention.

The schedule is derived statically from ``t_biattention_id`` / ``v_biattention_id``
(config name ``bert_base_6layer_6conect``): with t ids (6..11) and v ids (0..5),

    text 0..5 → co-attn 0 → text 6 + vis 0 → co-attn 1 → ... → co-attn 5
    → vis 5 → text 11

i.e. the first six text layers run before the visual stream starts, then each
bridge interleaves one layer per stream, and each stream finishes its tail
after the last bridge. The loop is plain Python over a static schedule — under
``jit`` it traces once into a flat XLA graph (no dynamic control flow).

Reference capability: BertEncoder in the external ``vilbert`` package
(driven from worker.py:286-289); redesigned for XLA.
"""

from __future__ import annotations

from typing import TYPE_CHECKING, List, Optional, Tuple

import jax.numpy as jnp
from flax import linen as nn

from vilbert_multitask_tpu.config import ViLBertConfig
from vilbert_multitask_tpu.models.layers import ConnectionLayer, TransformerLayer

if TYPE_CHECKING:
    from vilbert_multitask_tpu.parallel.ring import RingContext


class TwoStreamEncoder(nn.Module):
    """``ring_v`` routes VISUAL-stream self-attention through sequence-
    parallel ring attention (parallel/ring.py) when the region count clears
    the context's threshold — regions are the long axis (video frames,
    tiled detections); the text stream is capped at 38 tokens by the
    pipeline and always stays dense, as does the cross-stream bridge."""

    config: ViLBertConfig
    ring_v: Optional["RingContext"] = None
    dtype: jnp.dtype = jnp.float32

    def setup(self):
        cfg = self.config
        # Per-layer rematerialization: deterministic / need_probs are static
        # (they steer Python control flow inside the layers).
        t_layer_cls = TransformerLayer
        c_layer_cls = ConnectionLayer
        if cfg.remat:
            t_layer_cls = nn.remat(TransformerLayer, static_argnums=(3,))
            c_layer_cls = nn.remat(ConnectionLayer, static_argnums=(5, 6))
        self.t_layers = [
            t_layer_cls(
                hidden_size=cfg.hidden_size,
                num_heads=cfg.num_attention_heads,
                intermediate_size=cfg.intermediate_size,
                activation=cfg.hidden_act,
                hidden_dropout=cfg.hidden_dropout_prob,
                attention_dropout=cfg.attention_probs_dropout_prob,
                layer_norm_eps=cfg.layer_norm_eps,
                use_pallas=cfg.use_pallas_self_attention,
                dtype=self.dtype,
                name=f"t_layer_{i}",
            )
            for i in range(cfg.num_hidden_layers)
        ]
        self.v_layers = [
            t_layer_cls(
                hidden_size=cfg.v_hidden_size,
                num_heads=cfg.v_num_attention_heads,
                intermediate_size=cfg.v_intermediate_size,
                activation=cfg.v_hidden_act,
                hidden_dropout=cfg.v_hidden_dropout_prob,
                attention_dropout=cfg.v_attention_probs_dropout_prob,
                layer_norm_eps=cfg.layer_norm_eps,
                use_pallas=cfg.use_pallas_self_attention,
                ring=self.ring_v,
                dtype=self.dtype,
                name=f"v_layer_{i}",
            )
            for i in range(cfg.v_num_hidden_layers)
        ]
        self.c_layers = [
            c_layer_cls(
                hidden_size=cfg.hidden_size,
                v_hidden_size=cfg.v_hidden_size,
                bi_hidden_size=cfg.bi_hidden_size,
                bi_num_heads=cfg.bi_num_attention_heads,
                intermediate_size=cfg.intermediate_size,
                v_intermediate_size=cfg.v_intermediate_size,
                activation=cfg.hidden_act,
                v_activation=cfg.v_hidden_act,
                hidden_dropout=cfg.hidden_dropout_prob,
                attention_dropout=cfg.attention_probs_dropout_prob,
                layer_norm_eps=cfg.layer_norm_eps,
                use_pallas=cfg.use_pallas_coattention,
                dtype=self.dtype,
                name=f"c_layer_{i}",
            )
            for i in range(cfg.num_connection_layers)
        ]

    def __call__(
        self,
        t_hidden,
        v_hidden,
        t_mask_bias,
        v_mask_bias,
        *,
        deterministic: bool = True,
        collect_attention: bool = False,
    ):
        cfg = self.config
        attn_maps: List[Tuple] = []

        t_ptr = 0
        v_ptr = 0
        for c_idx, (v_stop, t_stop) in enumerate(
            zip(cfg.v_biattention_id, cfg.t_biattention_id)
        ):
            while t_ptr < t_stop:
                t_hidden, _ = self.t_layers[t_ptr](
                    t_hidden, t_mask_bias, deterministic
                )
                t_ptr += 1
            while v_ptr < v_stop:
                v_hidden, _ = self.v_layers[v_ptr](
                    v_hidden, v_mask_bias, deterministic
                )
                v_ptr += 1
            v_hidden, t_hidden, co_probs = self.c_layers[c_idx](
                v_hidden, v_mask_bias, t_hidden, t_mask_bias,
                deterministic, collect_attention,
            )
            if collect_attention:
                attn_maps.append(co_probs)

        while v_ptr < cfg.v_num_hidden_layers:
            v_hidden, _ = self.v_layers[v_ptr](
                v_hidden, v_mask_bias, deterministic
            )
            v_ptr += 1
        while t_ptr < cfg.num_hidden_layers:
            t_hidden, _ = self.t_layers[t_ptr](
                t_hidden, t_mask_bias, deterministic
            )
            t_ptr += 1

        return t_hidden, v_hidden, attn_maps
