"""The flagship model: two-stream ViLBERT trunk + 9 task heads.

Reference capability: ``VILBertForVLTasks`` from the external ``vilbert``
package — constructed at worker.py:530-536, called at worker.py:286-289 with

    model(question, features, spatials, segment_ids, input_mask, image_mask,
          co_attention_mask, task_tokens, output_all_attention_masks=True)

returning the 10-tuple decoded at worker.py:295-386. This module reproduces
that call contract (as a typed :class:`ViLBertOutput`) on a TPU-first stack.
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

import jax.numpy as jnp
from flax import linen as nn
from flax import struct

from vilbert_multitask_tpu.config import ViLBertConfig
from vilbert_multitask_tpu.models.embeddings import ImageEmbeddings, TextEmbeddings
from vilbert_multitask_tpu.models.encoder import TwoStreamEncoder
from vilbert_multitask_tpu.models.heads import (
    ImagePredictionHead,
    Pooler,
    SimpleClassifier,
    TextPredictionHead,
    fused_layer_norm,
)
from vilbert_multitask_tpu.models.layers import ACT
from vilbert_multitask_tpu.ops.attention import mask_to_bias


@struct.dataclass
class ViLBertOutput:
    """Typed view of the reference 10-tuple (worker.py:287-289).

    A registered pytree (flax.struct) so it can cross ``jit``/``pjit``
    boundaries and be sharded leaf-wise.
    """

    vil_prediction: jnp.ndarray  # (B, num_labels)        VQA
    vil_prediction_gqa: jnp.ndarray  # (B, gqa_num_labels) GQA
    vil_logit: jnp.ndarray  # (B, 1)                       retrieval alignment
    vil_binary_prediction: Optional[jnp.ndarray]  # (B//2, 2)  NLVR2 pairs
    vil_tri_prediction: jnp.ndarray  # (B, 3)              SNLI-VE
    vision_prediction: Optional[jnp.ndarray]  # (B, Nv, v_target) masked-region
    vision_logit: jnp.ndarray  # (B, Nv, 1)                grounding
    linguisic_prediction: Optional[jnp.ndarray]  # (B, Nt', vocab) masked-LM
    linguisic_logit: jnp.ndarray  # (B, Nt', 1)            token grounding
    attn_data_list: List[Any]  # per-bridge (text→image, image→text) probs

    def to_tuple(self) -> Tuple:
        """Reference positional order."""
        return (
            self.vil_prediction,
            self.vil_prediction_gqa,
            self.vil_logit,
            self.vil_binary_prediction,
            self.vil_tri_prediction,
            self.vision_prediction,
            self.vision_logit,
            self.linguisic_prediction,
            self.linguisic_logit,
            self.attn_data_list,
        )


class ViLBertModel(nn.Module):
    """Trunk: embeddings + two-stream encoder + poolers."""

    config: ViLBertConfig
    ring_v: Optional[Any] = None  # parallel.ring.RingContext — see encoder
    dtype: jnp.dtype = jnp.float32

    def setup(self):
        cfg = self.config
        self.embeddings = TextEmbeddings(cfg, dtype=self.dtype)
        self.v_embeddings = ImageEmbeddings(cfg, dtype=self.dtype)
        self.encoder = TwoStreamEncoder(cfg, ring_v=self.ring_v,
                                        dtype=self.dtype)
        self.t_pooler = Pooler(cfg.bi_hidden_size, dtype=self.dtype)
        self.v_pooler = Pooler(cfg.bi_hidden_size, dtype=self.dtype)

    def __call__(
        self,
        input_ids,  # (B, Nt) int32
        features,  # (B, Nv, v_feature_size)
        spatials,  # (B, Nv, 5)
        segment_ids,  # (B, Nt) int32
        input_mask,  # (B, Nt) {0,1}
        image_mask,  # (B, Nv) {0,1}
        task_ids=None,  # (B, 1) int32 when task_specific_tokens
        *,
        deterministic: bool = True,
        collect_attention: bool = False,
    ):
        cfg = self.config
        t_hidden = self.embeddings(
            input_ids, segment_ids, task_ids, deterministic=deterministic
        )
        if cfg.task_specific_tokens:
            input_mask = TextEmbeddings.extend_mask_for_task_token(input_mask)
        v_hidden = self.v_embeddings(features, spatials, deterministic=deterministic)

        t_bias = mask_to_bias(input_mask, self.dtype)
        v_bias = mask_to_bias(image_mask, self.dtype)

        t_seq, v_seq, attn_maps = self.encoder(
            t_hidden, v_hidden, t_bias, v_bias,
            deterministic=deterministic, collect_attention=collect_attention,
        )
        pooled_t = self.t_pooler(t_seq)
        pooled_v = self.v_pooler(v_seq)
        return t_seq, v_seq, pooled_t, pooled_v, attn_maps, input_mask


class ViLBertForVLTasks(nn.Module):
    """Trunk + all 9 heads; output order matches the reference 10-tuple.

    ``ring_v`` (parallel.ring.RingContext) opts the visual stream into
    sequence-parallel ring attention on the context's mesh — the
    long-context serving/training path. Dense and ring instances have
    identical param trees (checkpoints are interchangeable).
    """

    config: ViLBertConfig
    ring_v: Optional[Any] = None
    dtype: jnp.dtype = jnp.float32

    def setup(self):
        cfg = self.config
        self.bert = ViLBertModel(cfg, ring_v=self.ring_v, dtype=self.dtype)
        bi = cfg.bi_hidden_size
        self.vil_prediction = SimpleClassifier(
            bi * 2, cfg.num_labels, cfg.layer_norm_eps, dtype=self.dtype
        )
        self.vil_prediction_gqa = SimpleClassifier(
            bi * 2, cfg.gqa_num_labels, cfg.layer_norm_eps, dtype=self.dtype
        )
        self.vil_binary_prediction = SimpleClassifier(
            bi * 2, 2, cfg.layer_norm_eps, dtype=self.dtype
        )
        self.vil_logit = nn.Dense(1, dtype=self.dtype)
        self.vil_tri_prediction = nn.Dense(3, dtype=self.dtype)
        self.vision_logit = nn.Dense(1, dtype=self.dtype)
        self.linguisic_logit = nn.Dense(1, dtype=self.dtype)
        self.cls_text = TextPredictionHead(cfg, dtype=self.dtype)
        self.cls_image = ImagePredictionHead(cfg, dtype=self.dtype)
        self.head_dropout = nn.Dropout(0.1)

    def trunk(
        self,
        input_ids,
        features,
        spatials,
        segment_ids,
        input_mask,
        image_mask,
        co_attention_mask=None,  # accepted for contract parity; zeros in serving
        task_ids=None,
        *,
        deterministic: bool = True,
        output_all_attention_masks: bool = False,
    ):
        """Trunk-only apply target (``model.apply(..., method="trunk")``)
        for the engine's fused-head serving path: same positional contract
        as :meth:`__call__`, but stops at the pooled vectors — the nine
        heads run as ONE batched slab program outside the module (see
        :func:`fused_head_output`), so mixed-task chunks stop paying nine
        sequential small matmuls."""
        return self.bert(
            input_ids, features, spatials, segment_ids, input_mask,
            image_mask, task_ids,
            deterministic=deterministic,
            collect_attention=output_all_attention_masks,
        )

    def __call__(
        self,
        input_ids,
        features,
        spatials,
        segment_ids,
        input_mask,
        image_mask,
        co_attention_mask=None,  # accepted for contract parity; zeros in serving
        task_ids=None,
        *,
        deterministic: bool = True,
        output_all_attention_masks: bool = False,
        compute_pretraining_heads: bool = True,
    ) -> ViLBertOutput:
        """``compute_pretraining_heads=False`` skips the masked-LM and
        masked-region decoders — the widest matmuls in the head stack
        (Nt'×vocab and Nv×v_target) — which no serving decode reads
        (engine/decode.py); the reference computes them unconditionally
        every request (worker.py:287-289). Training keeps the default."""
        cfg = self.config
        t_seq, v_seq, pooled_t, pooled_v, attn_maps, _ = self.bert(
            input_ids, features, spatials, segment_ids, input_mask, image_mask,
            task_ids,
            deterministic=deterministic,
            collect_attention=output_all_attention_masks,
        )

        if cfg.fusion_method == "mul":
            pooled = pooled_t * pooled_v
        elif cfg.fusion_method == "sum":
            pooled = pooled_t + pooled_v
        else:
            raise ValueError(f"unknown fusion_method {cfg.fusion_method}")
        pooled = self.head_dropout(pooled, deterministic=deterministic)

        vil_prediction = self.vil_prediction(pooled)
        vil_prediction_gqa = self.vil_prediction_gqa(pooled)
        vil_logit = self.vil_logit(pooled)
        vil_tri_prediction = self.vil_tri_prediction(pooled)

        # NLVR2: adjacent rows are the image pair for one example
        # (repeat-batching at engine/dispatch.py, mirroring worker.py:266-276).
        vil_binary_prediction = None
        if pooled.shape[0] % 2 == 0:
            paired = pooled.reshape(pooled.shape[0] // 2, -1)
            vil_binary_prediction = self.vil_binary_prediction(paired)
        elif self.is_initializing():
            # Materialize the head's params even when init ran with an odd
            # batch, so param existence never depends on the init shapes.
            self.vil_binary_prediction(
                jnp.zeros((1, 2 * pooled.shape[-1]), self.dtype)
            )

        # Grounding heads: mask penalty keeps padded regions out of the softmax
        # (same -10000 fold-in the reference model applies).
        vision_logit = self.vision_logit(self.head_dropout(
            v_seq, deterministic=deterministic))
        vision_logit = vision_logit + mask_to_bias(image_mask, self.dtype)[:, 0, 0, :, None]
        linguisic_logit = self.linguisic_logit(self.head_dropout(
            t_seq, deterministic=deterministic))

        linguisic_prediction = vision_prediction = None
        if compute_pretraining_heads or self.is_initializing():
            linguisic_prediction = self.cls_text(
                t_seq, self.bert.embeddings.word_table)
            vision_prediction = self.cls_image(v_seq)

        return ViLBertOutput(
            vil_prediction=vil_prediction,
            vil_prediction_gqa=vil_prediction_gqa,
            vil_logit=vil_logit,
            vil_binary_prediction=vil_binary_prediction,
            vil_tri_prediction=vil_tri_prediction,
            vision_prediction=vision_prediction,
            vision_logit=vision_logit,
            linguisic_prediction=linguisic_prediction,
            linguisic_logit=linguisic_logit,
            attn_data_list=attn_maps,
        )


def fused_head_output(
    cfg: ViLBertConfig, slabs: dict, trunk_out, image_mask, dtype
) -> Tuple[ViLBertOutput, jnp.ndarray]:
    """All nine serving heads from one trunk pass, as batched slab matmuls.

    ``slabs`` is :func:`..models.heads.build_head_slabs` over the served
    tree (already dequantized when params are int8); ``trunk_out`` is the
    :meth:`ViLBertForVLTasks.trunk` 6-tuple. Reproduces the per-head
    ``__call__`` numerics (flax casts every kernel/bias to the compute
    dtype; LayerNorm statistics in f32 — :func:`fused_layer_norm`), so the
    returned :class:`ViLBertOutput` matches the module path to rounding:
    the stacked label logits slice back to each head's real width, the
    concat-fused pooled heads have independent output columns, and head
    dropout is a serving no-op (deterministic).

    Also returns the raw stacked ``(B, 2, max_label_width)`` label logits —
    the engine's decode bundle gathers per-row by task id from them (ONE
    softmax/top-k instead of two full-width passes); padded columns sit at
    ``PAD_LOGIT_BIAS`` and vanish in the softmax.
    """
    t_seq, v_seq, pooled_t, pooled_v, attn_maps, _ = trunk_out
    if cfg.fusion_method == "mul":
        pooled = pooled_t * pooled_v
    elif cfg.fusion_method == "sum":
        pooled = pooled_t + pooled_v
    else:
        raise ValueError(f"unknown fusion_method {cfg.fusion_method}")
    k = lambda name: slabs[name].astype(dtype)  # noqa: E731

    # Wide label pair (VQA + GQA): one batched classifier over a head axis.
    h = jnp.einsum("bi,kio->bko", pooled, k("label_d1_kernel"))
    h = ACT["gelu"](h + k("label_d1_bias")[None])
    h = fused_layer_norm(h, slabs["label_ln_scale"], slabs["label_ln_bias"],
                         cfg.layer_norm_eps)
    label_logits = (jnp.einsum("bko,kow->bkw", h, k("label_d2_kernel"))
                    + k("label_d2_bias")[None])
    vil_prediction = label_logits[:, 0, : cfg.num_labels]
    vil_prediction_gqa = label_logits[:, 1, : cfg.gqa_num_labels]

    # Tiny pooled heads, concat-fused: columns 0 = vil_logit, 1:4 = tri.
    small = pooled @ k("pooled_kernel") + k("pooled_bias")
    vil_logit = small[:, :1]
    vil_tri_prediction = small[:, 1:4]

    # NLVR2 paired head: even batches only (models/vilbert.py pairing).
    vil_binary_prediction = None
    if pooled.shape[0] % 2 == 0:
        paired = pooled.reshape(pooled.shape[0] // 2, -1)
        hb = ACT["gelu"](paired @ k("binary_d1_kernel")
                         + k("binary_d1_bias"))
        hb = fused_layer_norm(hb, slabs["binary_ln_scale"],
                              slabs["binary_ln_bias"], cfg.layer_norm_eps)
        vil_binary_prediction = (hb @ k("binary_d2_kernel")
                                 + k("binary_d2_bias"))

    # Per-token grounding heads, mask penalty folded in as in __call__.
    vision_logit = v_seq @ k("vision_kernel") + k("vision_bias")
    vision_logit = vision_logit + mask_to_bias(
        image_mask, dtype)[:, 0, 0, :, None]
    linguisic_logit = t_seq @ k("ling_kernel") + k("ling_bias")

    out = ViLBertOutput(
        vil_prediction=vil_prediction,
        vil_prediction_gqa=vil_prediction_gqa,
        vil_logit=vil_logit,
        vil_binary_prediction=vil_binary_prediction,
        vil_tri_prediction=vil_tri_prediction,
        vision_prediction=None,
        vision_logit=vision_logit,
        linguisic_prediction=None,
        linguisic_logit=linguisic_logit,
        attn_data_list=attn_maps,
    )
    return out, label_logits
