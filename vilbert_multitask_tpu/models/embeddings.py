"""Input embeddings for both streams.

Reference capability: BertEmbeddings / BertImageEmbeddings inside the external
``vilbert`` package. Behavioral contract reproduced:

- text = word + position + token-type embeddings, then (with
  ``task_specific_tokens=True``, reference worker.py:485,516-517) the task
  token embedding is inserted **after [CLS]**, extending the sequence by one;
  LayerNorm + dropout applied after insertion.
- image = linear(2048 fc6 feature) + linear(5-dim normalized box geometry),
  summed, LayerNorm + dropout. The 5-dim spatial layout is built host-side
  (features/pipeline.py, mirroring worker.py:436-444).
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import linen as nn

from vilbert_multitask_tpu.config import ViLBertConfig


class TextEmbeddings(nn.Module):
    config: ViLBertConfig
    dtype: jnp.dtype = jnp.float32

    def setup(self):
        cfg = self.config
        self.word_embeddings = nn.Embed(
            cfg.vocab_size, cfg.hidden_size, dtype=self.dtype, name="word_embeddings"
        )
        self.position_embeddings = nn.Embed(
            cfg.max_position_embeddings, cfg.hidden_size, dtype=self.dtype,
            name="position_embeddings",
        )
        self.token_type_embeddings = nn.Embed(
            cfg.type_vocab_size, cfg.hidden_size, dtype=self.dtype,
            name="token_type_embeddings",
        )
        if cfg.task_specific_tokens:
            self.task_embeddings = nn.Embed(
                cfg.num_task_tokens, cfg.hidden_size, dtype=self.dtype,
                name="task_embeddings",
            )
        self.norm = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=self.dtype)
        self.dropout = nn.Dropout(cfg.hidden_dropout_prob)

    def __call__(self, input_ids, token_type_ids, task_ids=None, *, deterministic=True):
        cfg = self.config
        N = input_ids.shape[1]
        positions = jnp.arange(N)[None, :]
        x = (
            self.word_embeddings(input_ids)
            + self.position_embeddings(positions)
            + self.token_type_embeddings(token_type_ids)
        )
        if cfg.task_specific_tokens:
            if task_ids is None:
                raise ValueError("task_specific_tokens=True requires task_ids")
            task = self.task_embeddings(task_ids)  # (B, 1, H)
            # Insert after [CLS]: [cls, task, rest...] → sequence length N+1.
            x = jnp.concatenate([x[:, :1], task, x[:, 1:]], axis=1)
        x = self.norm(x)
        return self.dropout(x, deterministic=deterministic)

    @property
    def word_table(self) -> jnp.ndarray:
        """The (vocab, hidden) embedding matrix, for the tied LM decoder."""
        return self.word_embeddings.embedding

    @staticmethod
    def extend_mask_for_task_token(mask: jnp.ndarray) -> jnp.ndarray:
        """Extend a (B, N) attention mask to (B, N+1) for the inserted task
        token (always attended)."""
        ones = jnp.ones_like(mask[:, :1])
        return jnp.concatenate([mask[:, :1], ones, mask[:, 1:]], axis=1)


class ImageEmbeddings(nn.Module):
    config: ViLBertConfig
    dtype: jnp.dtype = jnp.float32

    def setup(self):
        cfg = self.config
        self.image_embeddings = nn.Dense(
            cfg.v_hidden_size, dtype=self.dtype, name="image_embeddings"
        )
        self.image_location_embeddings = nn.Dense(
            cfg.v_hidden_size, dtype=self.dtype, name="image_location_embeddings"
        )
        self.norm = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=self.dtype)
        self.dropout = nn.Dropout(cfg.v_hidden_dropout_prob)

    def __call__(self, features, spatials, *, deterministic=True):
        """features: (B, Nv, v_feature_size); spatials: (B, Nv, 5)."""
        feat = self.image_embeddings(features.astype(self.dtype))
        loc = self.image_location_embeddings(spatials.astype(self.dtype))
        x = self.norm(feat + loc)
        return self.dropout(x, deterministic=deterministic)
