"""Transformer building blocks for both streams.

Post-LayerNorm BERT topology (what the 12-in-1 checkpoint family was trained
with), fused-QKV attention, GELU FFN. Reference capability: the BertLayer /
BertImageLayer / BertConnectionLayer stack inside the external ``vilbert``
package driven from worker.py:286-289 — re-designed as Flax modules.
"""

from __future__ import annotations

import functools
from typing import TYPE_CHECKING, Optional, Tuple

import jax.numpy as jnp
from flax import linen as nn

from vilbert_multitask_tpu.ops.attention import (
    CrossAttention,
    FusedSelfAttention,
)

if TYPE_CHECKING:
    from vilbert_multitask_tpu.parallel.ring import RingContext

# Exact (erf) GELU: the BERT/ViLBERT family is trained with the exact form,
# and flax's default is the tanh approximation — close enough to train, close
# enough to silently flip near-tie answer rankings at serving time. Keep erf.
ACT = {
    "gelu": functools.partial(nn.gelu, approximate=False),
    "relu": nn.relu,
    "swish": nn.swish,
}


class AttentionOutput(nn.Module):
    """Projection + dropout + residual + LayerNorm after an attention block."""

    hidden_size: int
    dropout_rate: float = 0.1
    layer_norm_eps: float = 1e-12
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, context, residual, *, deterministic: bool = True):
        x = nn.Dense(self.hidden_size, dtype=self.dtype, name="dense")(context)
        x = nn.Dropout(self.dropout_rate)(x, deterministic=deterministic)
        x = nn.LayerNorm(epsilon=self.layer_norm_eps, dtype=self.dtype, name="norm")(
            x + residual
        )
        return x


class FeedForward(nn.Module):
    """BERT FFN: expand → activation → contract → dropout → residual → LN.

    The intermediate matmul is the MXU workhorse; kept as one large dense so
    XLA tiles it onto the systolic array and fuses the activation.
    """

    hidden_size: int
    intermediate_size: int
    activation: str = "gelu"
    dropout_rate: float = 0.1
    layer_norm_eps: float = 1e-12
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, *, deterministic: bool = True):
        h = nn.Dense(self.intermediate_size, dtype=self.dtype, name="intermediate")(x)
        h = ACT[self.activation](h)
        h = nn.Dense(self.hidden_size, dtype=self.dtype, name="output")(h)
        h = nn.Dropout(self.dropout_rate)(h, deterministic=deterministic)
        return nn.LayerNorm(
            epsilon=self.layer_norm_eps, dtype=self.dtype, name="norm"
        )(h + x)


class TransformerLayer(nn.Module):
    """One single-stream encoder layer (text or visual).

    ``ring`` opts the self-attention into the sequence-parallel path (see
    FusedSelfAttention); param structure is identical either way, so dense
    and ring instances share checkpoints.
    """

    hidden_size: int
    num_heads: int
    intermediate_size: int
    activation: str = "gelu"
    hidden_dropout: float = 0.1
    attention_dropout: float = 0.1
    layer_norm_eps: float = 1e-12
    use_pallas: bool = False
    ring: Optional["RingContext"] = None
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, mask_bias, deterministic: bool = True):
        ctx, probs = FusedSelfAttention(
            hidden_size=self.hidden_size,
            num_heads=self.num_heads,
            dropout_rate=self.attention_dropout,
            use_pallas=self.use_pallas,
            ring=self.ring,
            dtype=self.dtype,
            name="attention",
        )(x, mask_bias, deterministic=deterministic)
        x = AttentionOutput(
            hidden_size=self.hidden_size,
            dropout_rate=self.hidden_dropout,
            layer_norm_eps=self.layer_norm_eps,
            dtype=self.dtype,
            name="attention_output",
        )(ctx, x, deterministic=deterministic)
        x = FeedForward(
            hidden_size=self.hidden_size,
            intermediate_size=self.intermediate_size,
            activation=self.activation,
            dropout_rate=self.hidden_dropout,
            layer_norm_eps=self.layer_norm_eps,
            dtype=self.dtype,
            name="ffn",
        )(x, deterministic=deterministic)
        return x, probs


class ConnectionLayer(nn.Module):
    """Co-attention bridge between the streams (the "connect" in
    ``bert_base_6layer_6conect``).

    Bi-directional cross attention in the shared ``bi_hidden`` space:
    text queries attend image keys/values (context for the text stream) and
    image queries attend text keys/values (context for the image stream),
    each followed by its own output projection + residual + LN + FFN.

    This is the module the Pallas kernel (:mod:`..ops.coattention`) replaces on
    TPU; the XLA path here is the numerics reference for the kernel test.
    """

    hidden_size: int  # text stream width
    v_hidden_size: int  # visual stream width
    bi_hidden_size: int
    bi_num_heads: int
    intermediate_size: int  # text FFN width in the connection layer
    v_intermediate_size: int
    activation: str = "gelu"
    v_activation: str = "gelu"
    hidden_dropout: float = 0.1
    attention_dropout: float = 0.1
    layer_norm_eps: float = 1e-12
    use_pallas: bool = False
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(
        self,
        v_hidden,  # (B, Nv, v_hidden)
        v_mask_bias,  # (B, 1, 1, Nv)
        t_hidden,  # (B, Nt, hidden)
        t_mask_bias,  # (B, 1, 1, Nt)
        deterministic: bool = True,
        need_probs: bool = True,
    ) -> Tuple[jnp.ndarray, jnp.ndarray, Tuple[jnp.ndarray, jnp.ndarray]]:
        # Text queries over image keys/values → feeds the TEXT stream.
        t_ctx, probs_t2v = CrossAttention(
            bi_hidden_size=self.bi_hidden_size,
            num_heads=self.bi_num_heads,
            dropout_rate=self.attention_dropout,
            use_pallas=self.use_pallas,
            dtype=self.dtype,
            name="text_attends_image",
        )(t_hidden, v_hidden, v_mask_bias, deterministic=deterministic,
          need_probs=need_probs)
        # Image queries over text keys/values → feeds the IMAGE stream.
        v_ctx, probs_v2t = CrossAttention(
            bi_hidden_size=self.bi_hidden_size,
            num_heads=self.bi_num_heads,
            dropout_rate=self.attention_dropout,
            use_pallas=self.use_pallas,
            dtype=self.dtype,
            name="image_attends_text",
        )(v_hidden, t_hidden, t_mask_bias, deterministic=deterministic,
          need_probs=need_probs)

        v_hidden = AttentionOutput(
            hidden_size=self.v_hidden_size,
            dropout_rate=self.hidden_dropout,
            layer_norm_eps=self.layer_norm_eps,
            dtype=self.dtype,
            name="v_output",
        )(v_ctx, v_hidden, deterministic=deterministic)
        t_hidden = AttentionOutput(
            hidden_size=self.hidden_size,
            dropout_rate=self.hidden_dropout,
            layer_norm_eps=self.layer_norm_eps,
            dtype=self.dtype,
            name="t_output",
        )(t_ctx, t_hidden, deterministic=deterministic)

        v_hidden = FeedForward(
            hidden_size=self.v_hidden_size,
            intermediate_size=self.v_intermediate_size,
            activation=self.v_activation,
            dropout_rate=self.hidden_dropout,
            layer_norm_eps=self.layer_norm_eps,
            dtype=self.dtype,
            name="v_ffn",
        )(v_hidden, deterministic=deterministic)
        t_hidden = FeedForward(
            hidden_size=self.hidden_size,
            intermediate_size=self.intermediate_size,
            activation=self.activation,
            dropout_rate=self.hidden_dropout,
            layer_norm_eps=self.layer_norm_eps,
            dtype=self.dtype,
            name="t_ffn",
        )(t_hidden, deterministic=deterministic)

        return v_hidden, t_hidden, (probs_t2v, probs_v2t)
