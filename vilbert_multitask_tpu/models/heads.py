"""Poolers and the nine task heads.

Output contract = the 10-tuple unpacked at reference worker.py:287-289:

    vil_prediction, vil_prediction_gqa, vil_logit, vil_binary_prediction,
    vil_tri_prediction, vision_prediction, vision_logit,
    linguisic_prediction, linguisic_logit, attn_data_list

Head topologies follow the 12-in-1 model family:
- poolers take the first token of each stream through a Dense + ReLU into the
  shared ``bi_hidden`` space (text CLS / visual global-feature token);
- ``SimpleClassifier`` = Dense → GELU → LayerNorm → Dense;
- vision/linguistic "prediction" heads are the masked-modeling heads
  (transform + decoder; text decoder tied to the word-embedding table);
- ``vision_logit`` / ``linguisic_logit`` are per-token linear grounding heads,
  with the image-mask penalty folded in (tokens outside the mask get -10000).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import linen as nn

from vilbert_multitask_tpu.config import ViLBertConfig
from vilbert_multitask_tpu.models.layers import ACT

# Logit floor written into the padded dense2 bias columns of the stacked
# label slab (build_head_slabs): padded columns come out at exactly this
# value, which underflows to probability 0 in the f32 softmax — so top-k
# over the padded width matches top-k over each head's real width.
PAD_LOGIT_BIAS = -1e9


class Pooler(nn.Module):
    """First-token pooler into the bi_hidden space (ReLU, per ViLBERT)."""

    out_dim: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, hidden):
        x = nn.Dense(self.out_dim, dtype=self.dtype, name="dense")(hidden[:, 0])
        return nn.relu(x)


class SimpleClassifier(nn.Module):
    """Dense → GELU → LayerNorm → Dense (12-in-1 classifier topology)."""

    hidden_dim: int
    out_dim: int
    layer_norm_eps: float = 1e-12
    activation: str = "gelu"
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        h = nn.Dense(self.hidden_dim, dtype=self.dtype, name="dense1")(x)
        h = ACT[self.activation](h)
        h = nn.LayerNorm(epsilon=self.layer_norm_eps, dtype=self.dtype, name="norm")(h)
        return nn.Dense(self.out_dim, dtype=self.dtype, name="dense2")(h)


# Param-tree module names of the heads the fused decode program consumes
# (ViLBertForVLTasks.setup) — the slab builder's input contract.
SERVING_HEAD_MODULES = (
    "vil_prediction", "vil_prediction_gqa", "vil_binary_prediction",
    "vil_logit", "vil_tri_prediction", "vision_logit", "linguisic_logit",
)


def build_head_slabs(head_params, cfg: ViLBertConfig) -> dict:
    """Stack the nine serving heads' weights into batched slabs — the
    weights side of the fused decode-head program (models/vilbert.py:
    fused_head_output).

    - the two wide label classifiers (VQA / GQA) stack on a leading head
      axis; their dense2 kernels zero-pad to the wider label count and the
      padded bias columns carry :data:`PAD_LOGIT_BIAS` so padded logits
      drop out of the softmax;
    - the two tiny pooled heads (vil_logit, vil_tri_prediction) concat
      into one (bi, 4) kernel — independent output columns, so slicing
      the fused product reproduces each head exactly;
    - the paired NLVR2 classifier and the per-token grounding heads keep
      their own leaves (different input shapes; nothing to batch).

    Pure stacking math over a head-params subtree (``params[name]`` for
    each name in :data:`SERVING_HEAD_MODULES`) — jit it over the served
    tree to build the slabs on device.
    """
    vqa = head_params["vil_prediction"]
    gqa = head_params["vil_prediction_gqa"]
    binary = head_params["vil_binary_prediction"]
    wmax = max(cfg.num_labels, cfg.gqa_num_labels)

    def padded(head):
        k, b = head["dense2"]["kernel"], head["dense2"]["bias"]
        pad = wmax - b.shape[-1]
        return (jnp.pad(k, ((0, 0), (0, pad))),
                jnp.pad(b, (0, pad), constant_values=PAD_LOGIT_BIAS))

    k_vqa, b_vqa = padded(vqa)
    k_gqa, b_gqa = padded(gqa)
    return {
        "label_d1_kernel": jnp.stack(
            [vqa["dense1"]["kernel"], gqa["dense1"]["kernel"]]),
        "label_d1_bias": jnp.stack(
            [vqa["dense1"]["bias"], gqa["dense1"]["bias"]]),
        "label_ln_scale": jnp.stack(
            [vqa["norm"]["scale"], gqa["norm"]["scale"]]),
        "label_ln_bias": jnp.stack(
            [vqa["norm"]["bias"], gqa["norm"]["bias"]]),
        "label_d2_kernel": jnp.stack([k_vqa, k_gqa]),
        "label_d2_bias": jnp.stack([b_vqa, b_gqa]),
        "pooled_kernel": jnp.concatenate(
            [head_params["vil_logit"]["kernel"],
             head_params["vil_tri_prediction"]["kernel"]], axis=-1),
        "pooled_bias": jnp.concatenate(
            [head_params["vil_logit"]["bias"],
             head_params["vil_tri_prediction"]["bias"]], axis=-1),
        "binary_d1_kernel": binary["dense1"]["kernel"],
        "binary_d1_bias": binary["dense1"]["bias"],
        "binary_ln_scale": binary["norm"]["scale"],
        "binary_ln_bias": binary["norm"]["bias"],
        "binary_d2_kernel": binary["dense2"]["kernel"],
        "binary_d2_bias": binary["dense2"]["bias"],
        "vision_kernel": head_params["vision_logit"]["kernel"],
        "vision_bias": head_params["vision_logit"]["bias"],
        "ling_kernel": head_params["linguisic_logit"]["kernel"],
        "ling_bias": head_params["linguisic_logit"]["bias"],
    }


def fused_layer_norm(h, scale, bias, eps: float):
    """LayerNorm with flax ``nn.LayerNorm`` numerics: statistics in f32
    (``var = max(0, E[x²] − E[x]²)``), scale folded into the rsqrt, result
    cast back to the input dtype — so the fused classifier matches the
    per-head module path to f32 rounding."""
    dt = h.dtype
    x = h.astype(jnp.float32)
    mean = x.mean(axis=-1, keepdims=True)
    var = jnp.maximum(0.0, (x * x).mean(axis=-1, keepdims=True) - mean * mean)
    mul = jax.lax.rsqrt(var + eps) * scale.astype(jnp.float32)
    return ((x - mean) * mul + bias.astype(jnp.float32)).astype(dt)


class TextPredictionHead(nn.Module):
    """Masked-LM head: transform + tied decoder over the vocab."""

    config: ViLBertConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, hidden, word_embedding_table):
        cfg = self.config
        h = nn.Dense(cfg.hidden_size, dtype=self.dtype, name="transform_dense")(hidden)
        h = ACT[cfg.hidden_act](h)
        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=self.dtype,
                         name="transform_norm")(h)
        logits = jnp.einsum(
            "bnh,vh->bnv", h, word_embedding_table.astype(self.dtype),
            preferred_element_type=self.dtype,
        )
        bias = self.param("decoder_bias", nn.initializers.zeros, (cfg.vocab_size,))
        return logits + bias.astype(self.dtype)


class ImagePredictionHead(nn.Module):
    """Masked-region head: transform + decoder onto v_target_size classes."""

    config: ViLBertConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, hidden):
        cfg = self.config
        h = nn.Dense(cfg.v_hidden_size, dtype=self.dtype, name="transform_dense")(hidden)
        h = ACT[cfg.v_hidden_act](h)
        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=self.dtype,
                         name="transform_norm")(h)
        return nn.Dense(cfg.v_target_size, dtype=self.dtype, name="decoder")(h)
