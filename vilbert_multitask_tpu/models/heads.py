"""Poolers and the nine task heads.

Output contract = the 10-tuple unpacked at reference worker.py:287-289:

    vil_prediction, vil_prediction_gqa, vil_logit, vil_binary_prediction,
    vil_tri_prediction, vision_prediction, vision_logit,
    linguisic_prediction, linguisic_logit, attn_data_list

Head topologies follow the 12-in-1 model family:
- poolers take the first token of each stream through a Dense + ReLU into the
  shared ``bi_hidden`` space (text CLS / visual global-feature token);
- ``SimpleClassifier`` = Dense → GELU → LayerNorm → Dense;
- vision/linguistic "prediction" heads are the masked-modeling heads
  (transform + decoder; text decoder tied to the word-embedding table);
- ``vision_logit`` / ``linguisic_logit`` are per-token linear grounding heads,
  with the image-mask penalty folded in (tokens outside the mask get -10000).
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import linen as nn

from vilbert_multitask_tpu.config import ViLBertConfig
from vilbert_multitask_tpu.models.layers import ACT


class Pooler(nn.Module):
    """First-token pooler into the bi_hidden space (ReLU, per ViLBERT)."""

    out_dim: int
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, hidden):
        x = nn.Dense(self.out_dim, dtype=self.dtype, name="dense")(hidden[:, 0])
        return nn.relu(x)


class SimpleClassifier(nn.Module):
    """Dense → GELU → LayerNorm → Dense (12-in-1 classifier topology)."""

    hidden_dim: int
    out_dim: int
    layer_norm_eps: float = 1e-12
    activation: str = "gelu"
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        h = nn.Dense(self.hidden_dim, dtype=self.dtype, name="dense1")(x)
        h = ACT[self.activation](h)
        h = nn.LayerNorm(epsilon=self.layer_norm_eps, dtype=self.dtype, name="norm")(h)
        return nn.Dense(self.out_dim, dtype=self.dtype, name="dense2")(h)


class TextPredictionHead(nn.Module):
    """Masked-LM head: transform + tied decoder over the vocab."""

    config: ViLBertConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, hidden, word_embedding_table):
        cfg = self.config
        h = nn.Dense(cfg.hidden_size, dtype=self.dtype, name="transform_dense")(hidden)
        h = ACT[cfg.hidden_act](h)
        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=self.dtype,
                         name="transform_norm")(h)
        logits = jnp.einsum(
            "bnh,vh->bnv", h, word_embedding_table.astype(self.dtype),
            preferred_element_type=self.dtype,
        )
        bias = self.param("decoder_bias", nn.initializers.zeros, (cfg.vocab_size,))
        return logits + bias.astype(self.dtype)


class ImagePredictionHead(nn.Module):
    """Masked-region head: transform + decoder onto v_target_size classes."""

    config: ViLBertConfig
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, hidden):
        cfg = self.config
        h = nn.Dense(cfg.v_hidden_size, dtype=self.dtype, name="transform_dense")(hidden)
        h = ACT[cfg.v_hidden_act](h)
        h = nn.LayerNorm(epsilon=cfg.layer_norm_eps, dtype=self.dtype,
                         name="transform_norm")(h)
        return nn.Dense(cfg.v_target_size, dtype=self.dtype, name="decoder")(h)
