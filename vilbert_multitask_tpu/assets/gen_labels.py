"""Deterministic generator for the committed answer-vocabulary assets.

Emits VQA (3129 labels, reference worker.py:523) and GQA (1533) answer lists
in the reference's exact on-disk layout —
``{root}/{name}/cache/trainval_label2ans.pkl``, a pickled list[str]
(reference worker.py:299-300,311-315) — so the serving default exercises the
same loader code path the real assets will use. The real label pickles are
not vendorable from this image (no egress, not present in /root/reference);
the first entries are the well-known most-frequent VQAv2/GQA answers, the
tail is explicit ``answer_###`` placeholders. Swap the files for the real
pickles to get score parity; no code changes.

Regenerate with ``python -m vilbert_multitask_tpu.assets.gen_labels``.
"""

from __future__ import annotations

import os
import pickle

# Most-frequent VQAv2 answers (publicly documented ordering varies by cache;
# this list is a realistic head, not a parity artifact).
VQA_HEAD = [
    "yes", "no", "2", "1", "white", "3", "red", "blue", "4", "green",
    "black", "yellow", "brown", "5", "tennis", "baseball", "6", "orange",
    "0", "bathroom", "wood", "right", "left", "frisbee", "pink", "gray",
    "pizza", "7", "kitchen", "8", "cat", "skiing", "skateboarding", "dog",
    "snow", "black and white", "surfing", "water", "red and white", "9",
    "nothing", "kite", "blue and white", "wii", "grass", "umbrella",
    "stop", "man", "woman", "phone", "food", "motorcycle", "bus", "train",
    "horse", "sheep", "elephant", "zebra", "giraffe", "banana", "apple",
    "sandwich", "broccoli", "carrot", "hot dog", "donut", "cake", "chair",
    "couch", "bed", "laptop", "tv", "clock", "beach", "park", "street",
    "day", "night", "summer", "winter", "sunny", "cloudy", "raining",
    "daytime", "afternoon", "morning", "male", "female", "on table",
    "in water", "standing", "sitting", "walking", "eating", "playing",
]

GQA_HEAD = [
    "no", "yes", "left", "right", "man", "woman", "white", "black", "blue",
    "red", "green", "brown", "gray", "yellow", "orange", "pink", "purple",
    "color", "bottom", "top", "small", "large", "wood", "metal", "plastic",
    "glass", "table", "chair", "window", "door", "wall", "floor", "grass",
    "sky", "tree", "car", "bus", "train", "dog", "cat", "horse", "bird",
    "boy", "girl", "shirt", "pants", "jacket", "hat", "standing",
    "sitting", "walking", "eating", "playing", "open", "closed", "on",
    "off", "indoors", "outdoors", "day", "night",
]


def _full(head: list[str], size: int, name: str) -> list[str]:
    labels = list(head)
    labels += [f"{name}_answer_{i}" for i in range(len(labels), size)]
    assert len(labels) == size
    return labels


def main() -> list[str]:
    root = os.path.join(os.path.dirname(__file__), "labels")
    out = []
    for name, head, size in (("vqa", VQA_HEAD, 3129), ("gqa", GQA_HEAD, 1533)):
        d = os.path.join(root, name, "cache")
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, "trainval_label2ans.pkl")
        with open(path, "wb") as f:
            pickle.dump(_full(head, size, name), f, protocol=2)
        out.append(path)
    return out


if __name__ == "__main__":
    for p in main():
        print(p)
