"""Deterministic generator for the committed serving vocabulary.

This image has no network egress and no bert-base-uncased asset anywhere on
disk, so the real 30,522-token vocabulary cannot be vendored (VERDICT round 1
item 4, adapted). Instead this script emits a REAL WordPiece vocabulary file
in the standard one-token-per-line format whose STRUCTURE mirrors
bert-base-uncased exactly:

- ``[PAD]`` = 0, ``[unused0]``..``[unused98]`` = 1..99, ``[UNK]`` = 100,
  ``[CLS]`` = 101, ``[SEP]`` = 102, ``[MASK]`` = 103 — the special ids the
  12-in-1 checkpoint family bakes in (reference worker.py:402-403 encodes
  with these), so swapping in the genuine vocab file later changes no
  special-token id and no code;
- printable-ASCII single characters and their ``##`` continuations, so any
  ASCII word tokenizes to subwords rather than ``[UNK]`` (matching the real
  vocab's behavior for rare words);
- a curated vision-and-language word list (COCO object categories, VQA answer
  words, question/function words) plus common English suffix pieces, giving
  the greedy longest-match algorithm realistic multi-piece splits.

Regenerate with ``python -m vilbert_multitask_tpu.assets.gen_vocab``; output
is byte-stable.
"""

from __future__ import annotations

import os

SUFFIXES = [
    "s", "es", "ed", "ing", "er", "est", "ly", "y", "ies", "ion", "tion",
    "al", "ic", "ous", "ful", "less", "ness", "ment", "able", "ish", "en",
    "an", "man", "men", "board", "ball", "light", "room", "time", "side",
]

# COCO-80 object categories (public list), split into single words.
COCO = """person bicycle car motorcycle airplane bus train truck boat
traffic light fire hydrant stop sign parking meter bench bird cat dog horse
sheep cow elephant bear zebra giraffe backpack umbrella handbag tie suitcase
frisbee skis snowboard sports ball kite baseball bat glove skateboard
surfboard tennis racket bottle wine glass cup fork knife spoon bowl banana
apple sandwich orange broccoli carrot hot pizza donut cake chair couch potted
plant bed dining table toilet tv laptop mouse remote keyboard cell phone
microwave oven toaster sink refrigerator book clock vase scissors teddy hair
drier toothbrush""".split()

WORDS = """
the a an is are was were am be been being do does did doing have has had
having will would can could shall should may might must not no yes none
what which who whom whose where when why how many much some any all both
few more most other another such only own same so than too very just
i you he she it we they me him her us them my your his its our their this
that these those there here and or but if because as until while of at by
for with about against between into through during before after above below
to from up down in out on off over under again further then once
man woman boy girl child children adult people player rider driver worker
face head eye ear nose mouth hand arm leg foot feet hair beard body finger
shirt pants jacket coat dress hat cap helmet shoe sock scarf uniform jeans
shorts skirt suit sunglasses watch bag purse
red green blue yellow white black brown gray grey pink purple tan beige
golden silver dark light bright colorful
zero one two three four five six seven eight nine ten eleven twelve
thirteen fourteen fifteen twenty thirty forty fifty hundred first second
third last single double several pair group bunch crowd
big small large little tall short long wide narrow thick thin huge tiny
old young new modern round square flat curved empty full open closed clean
dirty wet dry hot cold warm cool sunny cloudy rainy snowy bright shiny
happy sad angry surprised tired hungry cute funny scary dangerous safe
wood wooden metal plastic glass paper stone brick concrete leather fabric
water snow rain ice sand grass tree trees bush flower flowers leaf leaves
branch sky cloud clouds sun moon star mountain hill field forest beach
ocean sea lake river road street sidewalk path bridge building house home
wall floor ceiling roof window door fence gate yard garden park playground
kitchen bathroom bedroom office store shop market restaurant school city
town farm zoo station airport harbor court
eat eating drink drinking hold holding wear wearing ride riding play
playing stand standing sit sitting walk walking run running jump jumping
fly flying swim swimming sleep sleeping look looking watch watching read
reading write writing talk talking smile smiling laugh laughing wait
waiting work working cook cooking cut cutting throw throwing catch
catching kick kicking hit hitting carry carrying pull pulling push pushing
point pointing reach reaching lean leaning lie lying feed feeding brush
brushing wash washing drive driving park parking turn turning cross
crossing climb climbing surf surfing ski skiing skate skating race racing
serve serving toss tossing swing swinging
left right top bottom middle center front back near far next behind beside
under above inside outside around corner edge end side
color kind type number amount time day night morning afternoon evening
weather season summer winter spring fall scene picture image photo
background foreground shadow reflection
food meal breakfast lunch dinner snack fruit vegetable meat bread cheese
egg rice pasta soup salad sauce butter sugar salt pepper coffee tea milk
juice soda beer drink dessert chocolate cookie cream
plate dish tray pan pot lid napkin towel basket box container jar can
bag plane jet helicopter ship sail engine wheel tire door seat
animal pet bird fish duck goose chicken pig goat rabbit deer monkey lion
tiger fox wolf squirrel turtle frog insect bee butterfly spider
tail wing paw horn fur feather
ball bat racket net goal team game sport match player field court track
kite board wave rope pole flag sign signal lamp lantern candle
computer screen monitor television phone camera radio speaker clock
machine device button switch wire cable battery
table desk shelf cabinet drawer counter bench stool sofa cushion pillow
blanket curtain mirror picture frame painting poster rug carpet stair
toy doll kite balloon game card
q start answer stop question guess true false entailment neutral
contradiction
""".split()


def build_vocab() -> list[str]:
    tokens: list[str] = ["[PAD]"]
    tokens += [f"[unused{i}]" for i in range(99)]
    tokens += ["[UNK]", "[CLS]", "[SEP]", "[MASK]"]
    seen = set(tokens)

    def add(tok: str) -> None:
        if tok and tok not in seen:
            seen.add(tok)
            tokens.append(tok)

    for c in range(33, 127):
        add(chr(c))
    for c in range(33, 127):
        add("##" + chr(c))
    for suf in SUFFIXES:
        add("##" + suf)
    for w in sorted(set(w.lower() for w in [*COCO, *WORDS])):
        add(w)
    return tokens


def main() -> str:
    out_path = os.path.join(os.path.dirname(__file__), "wordpiece_vocab.txt")
    with open(out_path, "w", encoding="utf-8") as f:
        f.write("\n".join(build_vocab()) + "\n")
    return out_path


if __name__ == "__main__":
    print(main())
