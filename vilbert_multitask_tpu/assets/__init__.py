"""Committed serving assets: WordPiece vocabulary + answer-label maps.

The reference loads bert-base-uncased and VQA/GQA label pickles from paths
outside its repo (worker.py:537-539, 299-315); this package vendors
swap-compatible defaults (see gen_vocab.py / gen_labels.py for provenance)
so the serving default path is the real asset-loading code, never a toy
in-memory fallback.
"""

from __future__ import annotations

import os

_HERE = os.path.dirname(__file__)


def asset_path(*parts: str) -> str:
    return os.path.join(_HERE, *parts)


def default_vocab_path() -> str:
    """The committed WordPiece vocab (bert-base-uncased structural layout:
    [PAD]=0, [UNK]=100, [CLS]=101, [SEP]=102, [MASK]=103)."""
    return asset_path("wordpiece_vocab.txt")


def default_labels_root() -> str:
    """Root holding ``{name}/cache/trainval_label2ans.pkl`` label maps in
    the reference's on-disk layout (worker.py:299,311)."""
    return asset_path("labels")
