"""vilbert_multitask_tpu — a TPU-native (JAX/XLA/Flax/Pallas/pjit) framework with the
capabilities of the Cloud-CV/vilbert-multi-task demo stack.

The reference system (see /root/reference, surveyed in SURVEY.md) is a Django web
demo plus a RabbitMQ-driven single-GPU PyTorch inference worker serving 8
vision-and-language task types from one 270M-parameter "12-in-1" ViLBERT
checkpoint. This package re-designs every layer TPU-first:

- ``models/``     two-stream ViLBERT trunk + 9 task heads as Flax modules
                  (reference capability: the external ``vilbert`` package,
                  imported at reference worker.py:44-46).
- ``ops/``        attention primitives and the Pallas co-attention kernel
                  (reference capability: CUDA kernels inside torch).
- ``parallel/``   device mesh, NamedSharding partition rules, collectives
                  (reference has none — worker.py:481 pins distributed=False;
                  here parallelism is first-class).
- ``text/``       pure-host WordPiece tokenizer (reference: pytorch_transformers
                  BertTokenizer, worker.py:42,537-539).
- ``features/``   precomputed region-feature pipeline + vectorized NMS
                  (reference: maskrcnn_benchmark C++/CUDA, worker.py:50-54).
- ``engine/``     jit-compiled shape-bucketed inference runner + per-task decode
                  (reference: worker.py:248-458).
- ``checkpoint/`` Orbax checkpointing + torch-state-dict converter
                  (reference: from_pretrained at worker.py:530-532).
- ``serve/``      durable job queue, HTTP API, websocket push, result store
                  (reference: demo/ Django app + pika, SURVEY.md L3-L6).
- ``native/``     C++ runtime pieces (NMS, feature store IO) built with g++,
                  bound via ctypes (reference: maskrcnn_benchmark native ops).
- ``obs/``        span tracing, counters/gauges/histograms, Prometheus and
                  Chrome-trace exporters (reference: one wall-clock print per
                  job, worker.py:657-658).
"""

__version__ = "0.1.0"

from vilbert_multitask_tpu.config import (  # noqa: F401
    ViLBertConfig,
    TaskSpec,
    TASK_REGISTRY,
    EngineConfig,
    ServingConfig,
    FrameworkConfig,
)
