"""Inference engine: bucketed jit runtime, label store, per-task decoders."""

from vilbert_multitask_tpu.engine.aotcache import AotCache, compile_fingerprint
from vilbert_multitask_tpu.engine.decode import ImageMeta, TaskResult
from vilbert_multitask_tpu.engine.labels import LabelMapStore
from vilbert_multitask_tpu.engine.runtime import InferenceEngine, PreparedRequest

__all__ = [
    "AotCache",
    "compile_fingerprint",
    "ImageMeta",
    "TaskResult",
    "LabelMapStore",
    "InferenceEngine",
    "PreparedRequest",
]
