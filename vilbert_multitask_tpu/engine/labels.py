"""Answer-vocabulary (label map) store.

Reference capability: the VQA/GQA ``trainval_label2ans.pkl`` pickles loaded
inside the decode path (reference worker.py:299-300,311-315). Two knowing
fixes over the reference:

- maps are loaded **once** and cached, not re-read from disk per request
  (SURVEY.md §2.4 lists the per-request reload as a quirk to fix);
- a JSON source format is supported alongside the pickle, and a deterministic
  synthetic fallback exists so the full serving path runs end-to-end on
  machines that don't have the original answer-vocabulary assets.
"""

from __future__ import annotations

import json
import os
import pickle
from typing import Dict, List, Sequence


class LabelMapStore:
    """name → list[str] answer vocabulary, loaded once at boot.

    Lookup order for map ``name`` under ``root``:
    ``{name}_label2ans.json`` → ``{name}_label2ans.pkl`` →
    ``{name}/cache/trainval_label2ans.pkl`` (the reference's on-disk layout,
    worker.py:299,311) → synthetic placeholders if ``allow_synthetic``.
    """

    def __init__(self, root: str = "assets/labels", *,
                 sizes: Dict[str, int] | None = None,
                 allow_synthetic: bool = True):
        self.root = root
        self.allow_synthetic = allow_synthetic
        # Default head widths: VQA 3129 (worker.py:523), GQA 1533 (12-in-1).
        self.sizes = dict(sizes or {"vqa": 3129, "gqa": 1533})
        self._cache: Dict[str, List[str]] = {}

    def _candidate_paths(self, name: str) -> Sequence[str]:
        return (
            os.path.join(self.root, f"{name}_label2ans.json"),
            os.path.join(self.root, f"{name}_label2ans.pkl"),
            os.path.join(self.root, name, "cache", "trainval_label2ans.pkl"),
        )

    def get(self, name: str) -> List[str]:
        if name in self._cache:
            return self._cache[name]
        labels: List[str] | None = None
        for path in self._candidate_paths(name):
            if not os.path.exists(path):
                continue
            if path.endswith(".json"):
                with open(path) as f:
                    labels = list(json.load(f))
            else:
                with open(path, "rb") as f:
                    labels = list(pickle.load(f))
            break
        if labels is None:
            if not self.allow_synthetic:
                raise FileNotFoundError(
                    f"no label map '{name}' under {self.root} "
                    f"(tried {', '.join(self._candidate_paths(name))})"
                )
            size = self.sizes.get(name, 1000)
            labels = [f"{name}_answer_{i}" for i in range(size)]
        self._cache[name] = labels
        return labels

    def save_json(self, name: str, labels: Sequence[str]) -> str:
        """Persist a label map in the JSON format (e.g. after converting the
        reference pickles once, offline)."""
        os.makedirs(self.root, exist_ok=True)
        path = os.path.join(self.root, f"{name}_label2ans.json")
        with open(path, "w") as f:
            json.dump(list(labels), f)
        self._cache[name] = list(labels)
        return path
