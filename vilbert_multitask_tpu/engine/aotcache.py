"""AOT executable cache: serialized XLA programs keyed by the compile surface.

PR 12 proved the engine's compile-key universe is closed and enumerated it
as ``COMPILE_SURFACE.json`` (family × bucket × param_dtype × fused ×
topology × attn). That turns boot-time compilation from runtime shape
discovery into a mechanical iteration — so the executables themselves can
be built once and persisted next to the checkpoint, the same AOT
discipline JAX serving stacks use::

    jax.jit(fwd).lower(*abstract_args).compile()        # trace once
    serialize_executable.serialize(compiled)            # persist
    serialize_executable.deserialize_and_load(payload)  # every boot after

Cache layout (``root`` = ``EngineConfig.aot_cache_dir``)::

    <root>/<fingerprint_hash>/fingerprint.json
    <root>/<fingerprint_hash>/rows__b8__float32__fused__dp-1.tp1.sp1__plain.aotx

Entry names are the manifest record keys (``analysis/surface.py``
``_record_key`` — the runtime↔manifest contract) with ``/`` mapped to
``__``. The fingerprint directory is what makes stale entries MISS instead
of poisoning: it hashes everything that changes the compiled program but
is not in the record key — jax/jaxlib versions, backend, device kind, the
actual mesh shape, ``model_gen`` (the kernel-fallback generation), and the
compile-relevant config sections. A new jaxlib, a degraded engine, or a
resized model lands in a different directory and recompiles cleanly;
nothing ever deserializes an executable built for a different world.

Each ``.aotx`` file is one pickle of ``{payload, in_tree, out_tree,
fingerprint, key}`` — the exact triple ``deserialize_and_load`` needs
(PyTreeDefs of dict/tuple/None trees pickle fine). Loads verify the
embedded fingerprint as belt-and-braces over the directory hash; any
read/unpickle/deserialize failure is a clean miss (recompile-and-overwrite
heals it), never a crash.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import pickle
import threading
import time
from typing import Any, Dict, List, Optional

import jax

from vilbert_multitask_tpu import obs

ENTRY_SUFFIX = ".aotx"
ENTRY_FORMAT = 1
FINGERPRINT_BASENAME = "fingerprint.json"

# Engine knobs that never shape a compiled program: filesystem locations
# and boot-orchestration switches. Everything else in EngineConfig (shape
# buckets, dtypes, fused mode, kernel flags, slab sizing) stays in the
# fingerprint — a drifted value must miss.
_NON_COMPILE_ENGINE_KNOBS = frozenset({
    "vocab_path", "labels_root", "compilation_cache_dir", "aot_cache_dir",
    "persistent_cache_min_compile_secs", "parallel_warmup",
})

_HITS = obs.REGISTRY.counter(
    "vmt_aot_cache_hits",
    "AOT-cache entries deserialized instead of compiled.",
    labelnames=("program",))
_MISSES = obs.REGISTRY.counter(
    "vmt_aot_cache_misses",
    "AOT-cache lookups that fell back to trace+compile.",
    labelnames=("program",))
_DESERIALIZE_MS = obs.REGISTRY.histogram(
    "vmt_aot_cache_deserialize_ms",
    "Executable deserialize+load time per cache hit (ms).")
_COMPILE_MS = obs.REGISTRY.histogram(
    "vmt_aot_cache_compile_ms",
    "lower+compile time per cache miss (ms).")


def record_compile_ms(ms: float) -> None:
    """Book one miss-path lower+compile duration (the compile itself runs
    engine-side, next to the jit machinery, so the runtime calls this)."""
    _COMPILE_MS.observe(ms)


def _jaxlib_version() -> str:
    try:
        import jaxlib

        return getattr(jaxlib, "__version__", jax.__version__)
    except Exception:  # noqa: BLE001 — version probing must never fail boot
        return jax.__version__


def topology_id(mesh_cfg) -> str:
    """The manifest's topology dimension id for a MeshConfig — must match
    ``analysis/surface.py::_topology_dimension`` (``dp-1.tp1.sp1`` for the
    defaults)."""
    return f"dp{mesh_cfg.dp}.tp{mesh_cfg.tp}.sp{mesh_cfg.sp}"


def record_key(family: str, bucket: int, param_dtype: str, fused: bool,
               topology: str, attn: bool) -> str:
    """One manifest record key — the same format as
    ``analysis/surface.py::_record_key`` (the runtime↔manifest contract;
    the cross-check test pins the two together)."""
    return (f"{family}/b{bucket}/{param_dtype}/"
            f"{'fused' if fused else 'perhead'}/{topology}/"
            f"{'attn' if attn else 'plain'}")


def compile_fingerprint(cfg, *, mesh=None, heads: bool = True
                        ) -> Dict[str, Any]:
    """Everything that changes a compiled program but is not in the record
    key. ``mesh`` is the LIVE mesh (or None): the record key's topology
    comes from MeshConfig knobs, but ``dp=-1`` resolves against whatever
    devices exist — the actual device grid must fingerprint. ``heads``
    records whether the engine serves fused head slabs (a head-less tree
    lowers a different input pytree under the same record key)."""
    engine = {k: v for k, v in dataclasses.asdict(cfg.engine).items()
              if k not in _NON_COMPILE_ENGINE_KNOBS}
    dev = jax.devices()[0]
    return {
        "jax": jax.__version__,
        "jaxlib": _jaxlib_version(),
        "backend": jax.default_backend(),
        "device_kind": getattr(dev, "device_kind", "unknown"),
        "mesh": ("none" if mesh is None else
                 "x".join(f"{k}{v}" for k, v in mesh.shape.items())),
        "heads": "slabs" if heads else "none",
        "model": dataclasses.asdict(cfg.model),
        "engine": engine,
        "mesh_cfg": dataclasses.asdict(cfg.mesh),
    }


def fingerprint_hash(fingerprint: Dict[str, Any], model_gen: int = 0) -> str:
    """Stable short hash of (fingerprint, model_gen) — the cache
    subdirectory name. ``model_gen`` folds in here so post-degrade
    programs (XLA attention after a Mosaic rejection) can never be served
    to a gen-0 boot that should probe the Pallas path."""
    blob = json.dumps({**fingerprint, "model_gen": model_gen},
                      sort_keys=True, default=repr)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def entry_filename(key: str) -> str:
    return key.replace("/", "__") + ENTRY_SUFFIX


class AotCache:
    """On-disk executable cache (one instance may be shared by a whole
    replica pool — loads are memoized, so replica 1..n-1 boot from memory).

    Thread-safe: disk reads/writes happen outside the lock; the memo and
    prefetch buffers are guarded. All failures are soft — a cache that
    cannot read or write degrades to plain trace+compile, never takes the
    engine down.
    """

    def __init__(self, root: str, fingerprint: Dict[str, Any]):
        self.root = os.path.abspath(root)
        self.fingerprint = fingerprint
        self._lock = threading.Lock()
        # (model_gen, key) → loaded executable: the pool fast path.
        self._loaded: Dict[Any, Any] = {}
        # path → raw file bytes, filled by prefetch() while the checkpoint
        # restore runs on another thread (disjoint resources: disk here,
        # network/device there).
        self._prefetched: Dict[str, bytes] = {}

    # ------------------------------------------------------------- layout
    def dir_for(self, model_gen: int = 0) -> str:
        return os.path.join(self.root,
                            fingerprint_hash(self.fingerprint, model_gen))

    def entry_path(self, key: str, model_gen: int = 0) -> str:
        return os.path.join(self.dir_for(model_gen), entry_filename(key))

    # ----------------------------------------------------------- prefetch
    def prefetch(self, keys: Optional[List[str]] = None,
                 model_gen: int = 0) -> int:
        """Read entry bytes into memory (pure disk I/O — no jax work), so
        boot can overlap this with the checkpoint restore. ``keys=None``
        prefetches every entry in the current fingerprint directory.
        Returns the number of entries buffered."""
        d = self.dir_for(model_gen)
        if keys is not None:
            paths = [self.entry_path(k, model_gen) for k in keys]
        else:
            try:
                paths = [os.path.join(d, n) for n in sorted(os.listdir(d))
                         if n.endswith(ENTRY_SUFFIX)]
            except OSError:
                return 0
        n = 0
        for p in paths:
            try:
                with open(p, "rb") as f:
                    blob = f.read()
            except OSError:
                continue
            with self._lock:
                self._prefetched[p] = blob
            n += 1
        return n

    # ---------------------------------------------------------- load/store
    def load(self, key: str, *, model_gen: int = 0, program: str = ""):
        """Deserialize-and-load one entry; None on any miss (absent, wrong
        fingerprint, unreadable, undeserializable — all clean)."""
        memo_key = (model_gen, key)
        with self._lock:
            if memo_key in self._loaded:
                _HITS.inc(program=program or key.split("/", 1)[0])
                return self._loaded[memo_key]
        path = self.entry_path(key, model_gen)
        t0 = time.perf_counter()
        loaded = self._load_from_disk(path, model_gen)
        program = program or key.split("/", 1)[0]
        if loaded is None:
            _MISSES.inc(program=program)
            return None
        _HITS.inc(program=program)
        _DESERIALIZE_MS.observe((time.perf_counter() - t0) * 1e3)
        with self._lock:
            self._loaded[memo_key] = loaded
        return loaded

    def _load_from_disk(self, path: str, model_gen: int):
        with self._lock:
            blob = self._prefetched.pop(path, None)
        if blob is None:
            try:
                with open(path, "rb") as f:
                    blob = f.read()
            except OSError:
                return None
        try:
            entry = pickle.loads(blob)
            if entry.get("format") != ENTRY_FORMAT:
                raise ValueError(f"entry format {entry.get('format')!r}")
            want = {**self.fingerprint, "model_gen": model_gen}
            if entry.get("fingerprint") != want:
                raise ValueError("fingerprint mismatch")
            from jax.experimental import serialize_executable as se

            return se.deserialize_and_load(
                entry["payload"], entry["in_tree"], entry["out_tree"])
        except Exception as e:  # noqa: BLE001 — stale/corrupt entries are
            # misses by design; the recompile overwrites them.
            obs.record_event("aot_cache_load_failed", path=path,
                             error=repr(e))
            return None

    def store(self, key: str, compiled, *, model_gen: int = 0) -> bool:
        """Serialize one compiled executable; atomic write (tmp+rename) so
        a crashed boot never leaves a torn entry. Best-effort: serialization
        or IO failures are recorded and swallowed — the engine already holds
        the compiled program it needs."""
        try:
            from jax.experimental import serialize_executable as se

            payload, in_tree, out_tree = se.serialize(compiled)
            entry = {
                "format": ENTRY_FORMAT,
                "key": key,
                "fingerprint": {**self.fingerprint, "model_gen": model_gen},
                "payload": payload,
                "in_tree": in_tree,
                "out_tree": out_tree,
            }
            blob = pickle.dumps(entry)
            d = self.dir_for(model_gen)
            os.makedirs(d, exist_ok=True)
            self._write_fingerprint(d, model_gen)
            path = self.entry_path(key, model_gen)
            tmp = f"{path}.tmp.{os.getpid()}.{threading.get_ident()}"
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, path)
            return True
        except Exception as e:  # noqa: BLE001 — cache writes must never
            # fail a boot that already compiled its program.
            obs.record_event("aot_cache_store_failed", key=key,
                             error=repr(e))
            return False

    def _write_fingerprint(self, d: str, model_gen: int) -> None:
        """Human-readable fingerprint next to the entries (debugging aid —
        `why did my cache miss` is answered by diffing two of these)."""
        path = os.path.join(d, FINGERPRINT_BASENAME)
        if os.path.exists(path):
            return
        try:
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump({**self.fingerprint, "model_gen": model_gen},
                          f, indent=2, sort_keys=True, default=repr)
            os.replace(tmp, path)
        except OSError:
            pass

    # ------------------------------------------------------- introspection
    def entry_count(self, model_gen: int = 0) -> int:
        try:
            return sum(1 for n in os.listdir(self.dir_for(model_gen))
                       if n.endswith(ENTRY_SUFFIX))
        except OSError:
            return 0
