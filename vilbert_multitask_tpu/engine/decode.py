"""Per-task decoding: model output 10-tuple → answer payloads.

Reference capability: the per-task branches of ``prediction``
(reference worker.py:295-386) plus the result-marshalling in the callback
(worker.py:564-645), redesigned as pure host-side functions over numpy views
of :class:`~vilbert_multitask_tpu.models.vilbert.ViLBertOutput`.

Decode families (config.TaskSpec.decode):
- ``labels``    tasks 1/2 (VQA), 15 (GQA): softmax → top-k answers via the
                label map (worker.py:295-323).
- ``binary``    task 12 (NLVR2): 2-way softmax over the paired head
                (worker.py:325-338).
- ``trinary``   task 13 (SNLI-VE): 3-way softmax (worker.py:341-354).
- ``ranking``   task 7 (retrieval): rank candidate images by vil_logit
                (worker.py:358-367).
- ``grounding`` tasks 4/11/16: top-k regions from vision_logit, mapped back
                to pixel boxes (worker.py:371-386).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Sequence

import numpy as np

from vilbert_multitask_tpu.config import (
    NLVR2_LABELS,
    SNLI_VE_LABELS,
    TaskSpec,
)
from vilbert_multitask_tpu.engine.labels import LabelMapStore


def softmax(x: np.ndarray, axis: int = -1) -> np.ndarray:
    x = x - x.max(axis=axis, keepdims=True)
    e = np.exp(x)
    return e / e.sum(axis=axis, keepdims=True)


@dataclasses.dataclass
class ImageMeta:
    """Per-image context the decoders need (path + original pixel size)."""

    path: str
    width: int
    height: int


@dataclasses.dataclass
class TaskResult:
    """One decoded answer, serializable for the DB row / websocket frame.

    ``kind`` mirrors TaskSpec.decode; exactly one payload field is populated.
    """

    task_id: int
    kind: str
    answers: List[Dict[str, Any]] | None = None  # labels/binary/trinary
    boxes: List[Dict[str, Any]] | None = None  # grounding
    ranking: List[Dict[str, Any]] | None = None  # retrieval

    def to_json(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"task_id": self.task_id, "kind": self.kind}
        for k in ("answers", "boxes", "ranking"):
            v = getattr(self, k)
            if v is not None:
                out[k] = v
        return out


def decode_labels(
    spec: TaskSpec, logits_row: np.ndarray, labels: LabelMapStore
) -> TaskResult:
    """VQA/GQA: softmax over the answer vocabulary, top-k answers."""
    probs = softmax(np.asarray(logits_row, np.float32))
    order = np.argsort(-probs)[: spec.top_k]
    return decode_labels_topk(spec, order, probs[order], labels)


def decode_labels_topk(
    spec: TaskSpec, top_idx: np.ndarray, top_probs: np.ndarray,
    labels: LabelMapStore,
) -> TaskResult:
    """VQA/GQA from an already-reduced top-k — the serving path, where the
    softmax + top-k ran on device inside the jitted forward
    (engine/runtime.py:_decode_bundle) so only k (index, prob) pairs cross
    the device→host link instead of the 3129/1533-wide head row."""
    vocab = labels.get(spec.label_map)
    answers = [
        {"answer": vocab[i] if i < len(vocab) else f"<{i}>",
         "confidence": float(p)}
        for i, p in zip(np.asarray(top_idx)[: spec.top_k],
                        np.asarray(top_probs)[: spec.top_k])
    ]
    return TaskResult(spec.task_id, "labels", answers=answers)


def decode_binary(spec: TaskSpec, logits_pair: np.ndarray) -> TaskResult:
    """NLVR2: 2-way softmax; labels (False, True) per worker.py:327."""
    probs = softmax(np.asarray(logits_pair, np.float32).reshape(-1)[:2])
    order = np.argsort(-probs)
    answers = [
        {"answer": NLVR2_LABELS[i], "confidence": float(probs[i])} for i in order
    ]
    return TaskResult(spec.task_id, "binary", answers=answers)


def decode_trinary(spec: TaskSpec, logits_row: np.ndarray) -> TaskResult:
    """SNLI-VE: contradiction/neutral/entailment (worker.py:342)."""
    probs = softmax(np.asarray(logits_row, np.float32).reshape(-1)[:3])
    order = np.argsort(-probs)
    answers = [
        {"answer": SNLI_VE_LABELS[i], "confidence": float(probs[i])} for i in order
    ]
    return TaskResult(spec.task_id, "trinary", answers=answers)


def decode_ranking(
    spec: TaskSpec, vil_logit: np.ndarray, images: Sequence[ImageMeta]
) -> TaskResult:
    """Retrieval: each batch row scored the caption against one candidate
    image (repeat-batching, worker.py:278-284); rank candidates by score."""
    n = len(images)
    scores = np.asarray(vil_logit, np.float32).reshape(-1)[:n]
    probs = softmax(scores)
    order = np.argsort(-scores)
    ranking = [
        {"rank": r + 1, "image": images[i].path, "score": float(scores[i]),
         "confidence": float(probs[i])}
        for r, i in enumerate(order)
    ]
    return TaskResult(spec.task_id, "ranking", ranking=ranking)


def decode_grounding(
    spec: TaskSpec,
    vision_logit_row: np.ndarray,  # (Nv, 1) — already mask-penalized
    spatials_row: np.ndarray,  # (Nv, 5) normalized
    image: ImageMeta,
    *,
    include_global_box: bool = True,
) -> TaskResult:
    """Visual7W/RefCOCO/GuessWhat: top-k regions → pixel boxes.

    The reference sorts the raw (mask-penalized) logits over all 101 regions
    including the prepended whole-image feature (worker.py:371-386) — so the
    global box can legitimately win. ``include_global_box=False`` restricts to
    detector boxes.
    """
    logits = np.asarray(vision_logit_row, np.float32).reshape(-1)
    probs = softmax(logits)
    start = 0 if include_global_box else 1
    order = start + np.argsort(-logits[start:])
    boxes: List[Dict[str, Any]] = []
    for i in order[: spec.top_k]:
        x1, y1, x2, y2 = (np.asarray(spatials_row[i, :4], np.float32)
                          * np.array([image.width, image.height,
                                      image.width, image.height], np.float32))
        boxes.append(
            {
                "region_index": int(i),
                "is_global": bool(i == 0),
                "box_xyxy": [float(x1), float(y1), float(x2), float(y2)],
                "box_normalized": [float(v) for v in spatials_row[i, :4]],
                "score": float(logits[i]),
                "confidence": float(probs[i]),
                "image": image.path,
            }
        )
    return TaskResult(spec.task_id, "grounding", boxes=boxes)
