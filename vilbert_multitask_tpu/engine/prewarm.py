"""Offline AOT cache population: ``python -m …engine.prewarm``.

Walks the COMPILE_SURFACE.json manifest (analysis/surface.py — the repo's
static enumeration of every program the engine can compile) and ensures an
AOT cache entry exists for each record matching this process's engine
variant: lower+compile+serialize on miss, verify-deserialize on hit. Run it
in CI after a config or model change and every replica host that mounts the
cache directory boots warm — restarts deserialize in seconds instead of
re-tracing for minutes (engine/aotcache.py).

One process covers ONE variant (param_dtype × fused × topology): records
for other variants are reported as skipped, not errors — re-run with
``--dtype``/``--per-head`` or on the target topology to cover them.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
import time


def _parse_buckets(text: str | None):
    if not text:
        return None
    return {int(tok) for tok in text.replace(",", " ").split()}


def main(argv=None) -> int:
    from vilbert_multitask_tpu.config import (
        FrameworkConfig,
        add_backend_args,
        apply_backend_args,
    )

    p = argparse.ArgumentParser(
        description="populate the AOT executable cache from the compile-"
                    "surface manifest (offline; replicas then boot warm)")
    p.add_argument("--manifest", default="COMPILE_SURFACE.json",
                   help="compile-surface manifest (analysis surface)")
    p.add_argument("--cache-dir", default=None,
                   help="AOT cache root (default: EngineConfig.aot_cache_dir"
                        " or serve_state/aot_cache)")
    p.add_argument("--family", choices=("batched", "rows"), default=None,
                   help="restrict to one program family")
    p.add_argument("--buckets", default=None,
                   help="comma-separated bucket filter (default: all)")
    p.add_argument("--dtype", default=None,
                   choices=("float32", "bfloat16", "int8"),
                   help="prewarm this param-storage variant instead of the "
                        "config default")
    p.add_argument("--per-head", action="store_true",
                   help="prewarm the per-head (non-fused) head variant")
    add_backend_args(p)
    args = p.parse_args(argv)

    cfg = apply_backend_args(FrameworkConfig(), args)
    ecfg = cfg.engine
    overrides = {}
    if args.dtype:
        overrides["param_dtype"] = args.dtype
    if args.per_head:
        overrides["fused_task_heads"] = False
    cache_dir = (args.cache_dir or ecfg.aot_cache_dir
                 or os.path.join("serve_state", "aot_cache"))
    overrides["aot_cache_dir"] = cache_dir
    cfg = dataclasses.replace(
        cfg, engine=dataclasses.replace(ecfg, **overrides))

    with open(args.manifest) as f:
        manifest = json.load(f)
    records = manifest["records"]

    # jax only after apply_backend_args (--cpu pins the platform).
    import jax

    from vilbert_multitask_tpu.engine import aotcache
    from vilbert_multitask_tpu.engine.runtime import InferenceEngine

    mesh = None
    if jax.device_count() > 1:
        from vilbert_multitask_tpu.parallel import build_mesh

        mesh = build_mesh(cfg.mesh)
    topology = aotcache.topology_id(cfg.mesh)
    want_buckets = _parse_buckets(args.buckets)
    valid_buckets = set(cfg.engine.all_row_buckets())

    def matches(rec) -> str | None:
        """None if this process can compile the record, else skip reason."""
        if rec["param_dtype"] != cfg.engine.param_dtype:
            return "dtype"
        if rec["fused"] != cfg.engine.fused_task_heads:
            return "heads"
        if rec["topology"] != topology:
            return "topology"
        if rec["bucket"] not in valid_buckets:
            return "bucket"
        if args.family and rec["family"] != args.family:
            return "filtered"
        if want_buckets is not None and rec["bucket"] not in want_buckets:
            return "filtered"
        return None

    todo = [(rec, matches(rec)) for rec in records]
    n_todo = sum(1 for _, why in todo if why is None)
    print(f"prewarm: {n_todo}/{len(records)} manifest records match this "
          f"variant ({cfg.engine.param_dtype}/"
          f"{'fused' if cfg.engine.fused_task_heads else 'perhead'}/"
          f"{topology}) -> {cache_dir}")
    if not n_todo:
        return 0

    t0 = time.perf_counter()
    engine = InferenceEngine(cfg, mesh=mesh, replica_id="prewarm")
    init_s = time.perf_counter() - t0

    width = max(len(rec["key"]) for rec in records)
    counts = {"hit": 0, "compiled": 0}
    skipped: dict = {}
    for rec, why in todo:
        if why is not None:
            skipped[why] = skipped.get(why, 0) + 1
            continue
        t1 = time.perf_counter()
        status = engine.aot_compile_record(
            rec["family"], rec["bucket"], rec["collect_attention"])
        ms = (time.perf_counter() - t1) * 1e3
        counts[status] = counts.get(status, 0) + 1
        print(f"  {rec['key']:<{width}}  {status:<8}  {ms:8.1f} ms")
    skip_text = " ".join(f"{k}={v}" for k, v in sorted(skipped.items()))
    print(f"prewarm: hits={counts['hit']} compiled={counts['compiled']} "
          f"skipped=[{skip_text or 'none'}] "
          f"entries={engine._aot.entry_count(engine._model_gen)} "
          f"init={init_s:.1f}s total={time.perf_counter() - t0:.1f}s")
    return 0


if __name__ == "__main__":
    sys.exit(main())
