"""Analytic FLOP count of one serving forward — the MFU numerator.

Counts matmul FLOPs (2·m·n·k per dense / attention einsum) of the serving
graph: embeddings, both single-stream encoders, the co-attention bridges,
poolers, and the classifier heads — with ``compute_pretraining_heads=False``
(the serving path, engine/runtime.py) so the masked-LM/region decoders are
excluded. Elementwise/LayerNorm/softmax FLOPs are ignored; on this model
they are <2% of the matmul count, so the figure is a tight lower bound —
the conservative direction for MFU claims.

``tests/test_bench_flops.py`` pins this estimate against XLA's own
``cost_analysis()['flops']`` on the compiled serving forward.
"""

from __future__ import annotations

from vilbert_multitask_tpu.config import EngineConfig, ViLBertConfig


def _dense(n: int, d_in: int, d_out: int) -> int:
    return 2 * n * d_in * d_out


def _self_attn_layer(n: int, hidden: int, inter: int) -> int:
    """Fused-QKV self-attention + output projection + FFN (ops/attention.py,
    models/layers.py:TransformerLayer)."""
    return (
        _dense(n, hidden, 3 * hidden)  # fused qkv
        + 2 * 2 * n * n * hidden  # scores + probs·V
        + _dense(n, hidden, hidden)  # attention output projection
        + _dense(n, hidden, inter) + _dense(n, inter, hidden)  # FFN
    )


def _bridge(nt: int, nv: int, cfg: ViLBertConfig) -> int:
    """One ConnectionLayer: bi-directional cross-attention + per-stream
    output projections and FFNs (models/layers.py:ConnectionLayer)."""
    h, hv, bi = cfg.hidden_size, cfg.v_hidden_size, cfg.bi_hidden_size
    t_dir = (
        _dense(nt, h, bi)  # text queries
        + 2 * _dense(nv, hv, bi)  # image keys + values
        + 2 * 2 * nt * nv * bi  # scores + probs·V
        + _dense(nt, bi, h)  # t_output projection
    )
    v_dir = (
        _dense(nv, hv, bi)
        + 2 * _dense(nt, h, bi)
        + 2 * 2 * nv * nt * bi
        + _dense(nv, bi, hv)
    )
    ffns = (
        _dense(nt, h, cfg.intermediate_size)
        + _dense(nt, cfg.intermediate_size, h)
        + _dense(nv, hv, cfg.v_intermediate_size)
        + _dense(nv, cfg.v_intermediate_size, hv)
    )
    return t_dir + v_dir + ffns


def serving_forward_flops(
    mcfg: ViLBertConfig, ecfg: EngineConfig, batch: int
) -> int:
    """Matmul FLOPs of one compiled serving forward at batch size ``batch``
    (text always padded to ``max_text_len``, regions to ``max_regions``)."""
    nt, nv = ecfg.max_text_len, ecfg.max_regions
    per_row = 0
    # Image embeddings: feature + location projections (models/embeddings.py).
    per_row += _dense(nv, mcfg.v_feature_size, mcfg.v_hidden_size)
    per_row += _dense(nv, 5, mcfg.v_hidden_size)
    # Encoders.
    per_row += mcfg.num_hidden_layers * _self_attn_layer(
        nt, mcfg.hidden_size, mcfg.intermediate_size)
    per_row += mcfg.v_num_hidden_layers * _self_attn_layer(
        nv, mcfg.v_hidden_size, mcfg.v_intermediate_size)
    per_row += mcfg.num_connection_layers * _bridge(nt, nv, mcfg)
    # Poolers into bi_hidden (models/heads.py:Pooler).
    bi = mcfg.bi_hidden_size
    per_row += _dense(1, mcfg.hidden_size, bi) + _dense(1, mcfg.v_hidden_size, bi)
    # Classifier heads over the fused pooled vector (models/vilbert.py).
    per_row += _dense(1, bi, 2 * bi) + _dense(1, 2 * bi, mcfg.num_labels)
    per_row += _dense(1, bi, 2 * bi) + _dense(1, 2 * bi, mcfg.gqa_num_labels)
    per_row += _dense(1, bi, 1) + _dense(1, bi, 3)  # vil_logit, tri
    # Paired NLVR2 head runs on batch/2 rows of width 2·bi.
    per_row += (_dense(1, 2 * bi, 4 * bi) + _dense(1, 4 * bi, 2)) // 2
    # Per-token grounding logits (vision_logit / linguisic_logit).
    per_row += _dense(nv, mcfg.v_hidden_size, 1) + _dense(nt, mcfg.hidden_size, 1)
    return batch * per_row


# Peak dense bf16 FLOP/s per chip, keyed on jax device_kind substrings.
# Sources: published TPU spec sheets (per-chip, not per-core).
PEAK_BF16_FLOPS = (
    ("v5 lite", 197e12),  # v5e
    ("v5e", 197e12),
    ("v5p", 459e12),
    ("v6 lite", 918e12),  # Trillium
    ("v6e", 918e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 46e12),
)


def peak_flops_for(device_kind: str) -> float | None:
    dk = device_kind.lower()
    for key, peak in PEAK_BF16_FLOPS:
        if key in dk:
            return peak
    return None


# Peak HBM bandwidth per chip (bytes/s), same substring keying. Sources:
# published TPU spec sheets.
PEAK_HBM_BYTES_PER_S = (
    ("v5 lite", 819e9),  # v5e
    ("v5e", 819e9),
    ("v5p", 2765e9),
    ("v6 lite", 1640e9),  # Trillium
    ("v6e", 1640e9),
    ("v4", 1228e9),
    ("v3", 900e9),
    ("v2", 700e9),
)

# Off-TPU (CPU smoke runs, unknown device kinds) the roofline is still
# worth stating against a reference chip so TINY bench artifacts carry the
# same fields as hardware ones — the reason string names the substitution.
_REFERENCE_CHIP = ("v5e", 197e12, 819e9)


def peak_hbm_bw_for(device_kind: str) -> float | None:
    dk = device_kind.lower()
    for key, bw in PEAK_HBM_BYTES_PER_S:
        if key in dk:
            return bw
    return None


def param_tree_bytes(params) -> int:
    """Total bytes of a device param tree — the weight-read term of the
    serving roofline (every forward reads every parameter once).

    Dtype-aware by construction: it sums what the tree actually stores, so
    an int8 tree (quant.py ``{"int8", "scale"}`` pairs — 1-byte values plus
    their f32 scale vectors) reports its real HBM footprint, bf16 reports
    half of f32, with no per-mode special casing."""
    import jax

    return int(sum(
        leaf.size * jax.numpy.dtype(leaf.dtype).itemsize
        for leaf in jax.tree_util.tree_leaves(params)))


def weight_bytes_per_row(param_bytes: int, batch: int) -> float:
    """HBM weight bytes amortized per batch row at ``batch`` — the number
    bigger batches and smaller storage dtypes both shrink; emitted in the
    bench roofline block next to ``param_bytes``."""
    return param_bytes / max(1, batch)


def knee_rows(mcfg: ViLBertConfig, ecfg: EngineConfig, device_kind: str,
              param_bytes: int) -> int:
    """The batch size where the roofline verdict flips from
    weight-read-bound to compute-bound: the smallest ``batch`` with
    ``t_compute >= t_mem``. FLOPs are linear in batch
    (:func:`serving_forward_flops`) while the weight-read term is flat, so
    the knee is analytic: ``ceil(param_bytes · peak / (bw · flops_per_row))``.
    Unknown device kinds (CPU smoke runs) compute against the v5e
    reference, same substitution as :func:`serving_roofline`."""
    import math

    peak = peak_flops_for(device_kind)
    bw = peak_hbm_bw_for(device_kind)
    if peak is None or bw is None:
        _, peak, bw = _REFERENCE_CHIP
    flops_per_row = serving_forward_flops(mcfg, ecfg, 1)
    return max(1, math.ceil(param_bytes * peak / (bw * flops_per_row)))


def serving_roofline(mcfg: ViLBertConfig, ecfg: EngineConfig, batch: int,
                     device_kind: str, param_bytes: int) -> dict:
    """Roofline cap on serving MFU at ``batch`` rows: a forward must read
    all ``param_bytes`` from HBM once (t_mem) and execute the analytic
    FLOPs (t_compute); achievable_mfu = t_compute / max(t_compute, t_mem).

    When that ratio is well below 1 the forward is weight-read-bound and
    more MXU (or a measured MFU "gap") is not the story — fewer weight
    bytes (``EngineConfig.param_dtype="bfloat16"``) or bigger batches are.
    Returns ``{"achievable_mfu", "reason"}``; unknown device kinds compute
    against the v5e reference so the fields are always present.
    """
    peak = peak_flops_for(device_kind)
    bw = peak_hbm_bw_for(device_kind)
    note = ""
    if peak is None or bw is None:
        ref, peak, bw = _REFERENCE_CHIP
        note = (f" [no spec table entry for {device_kind!r}; "
                f"roofline stated against {ref}]")
    flops = serving_forward_flops(mcfg, ecfg, batch)
    t_compute = flops / peak
    t_mem = param_bytes / bw
    mfu = t_compute / max(t_compute, t_mem)
    if t_mem > t_compute:
        reason = (
            f"weight-read-bound at batch {batch}: {param_bytes / 1e6:.0f} MB "
            f"params / {bw / 1e9:.0f} GB/s = {t_mem * 1e3:.2f} ms HBM vs "
            f"{t_compute * 1e3:.2f} ms compute — MFU caps at {mfu:.3f}")
    else:
        reason = (
            f"compute-bound at batch {batch}: {t_compute * 1e3:.2f} ms "
            f"compute vs {t_mem * 1e3:.2f} ms weight reads — MFU can "
            f"approach 1.0")
    return {"achievable_mfu": round(mfu, 4), "reason": reason + note}
