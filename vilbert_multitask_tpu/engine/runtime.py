"""TPU serving runtime: bucketed, jit-compiled ViLBERT inference.

Reference capability: the worker's model-driving core — ``load_vilbert_model``
(reference worker.py:463-539), ``custom_prediction`` (worker.py:388-458) and
``prediction`` (worker.py:248-386) — redesigned around XLA's compilation
model:

- **static shape buckets**: text is always ``max_text_len`` (37), regions
  ``max_regions`` (101), and the image/batch axis is padded to one of
  ``EngineConfig.image_buckets`` — every request hits a program compiled
  once, instead of the reference's shape-per-request dynamic batching
  (worker.py:266-284);
- **repeat-batching stays**: NLVR2 pairs and retrieval candidates score in a
  single forward with the question replicated per image row, mirroring
  worker.py:266-284;
- **bf16 compute** on the MXU; softmaxes run in f32. Params are stored in
  ``EngineConfig.param_dtype`` (f32 default; ``"bfloat16"`` is the serving
  mode that halves every weight read and the boot upload — training keeps
  f32 master copies, the cast happens at init/restore time only);
- **mesh-ready**: pass a ``Mesh`` and params are placed via the partition
  rules in :mod:`..parallel.sharding`; without one, single-device jit;
- **host↔device bytes are the latency** on a tunneled/network-attached
  chip, so the single-device program reads image rows out of a
  device-resident **row slab** (one (S, Nv, ...) tensor per input kind)
  via a per-call index vector: rows for content-stable store images pin
  in their slab slot after first use (LRU input cache), bucket padding
  reuses the permanent pad slot 0, and features ship in bf16 when the
  engine computes in bf16 — repeat queries upload ~KB of text instead of
  ~MB of features. The compiled forward signature is O(1) in bucket rows
  (params + 3 slab leaves + one packed text/index tree), so per-dispatch
  argument marshalling no longer scales with batch size;
- label maps load once at boot (fixes the per-request pickle reload,
  SURVEY.md §2.4).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import OrderedDict
from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from vilbert_multitask_tpu.config import (
    FrameworkConfig,
    TASK_REGISTRY,
    TaskSpec,
)
from vilbert_multitask_tpu.engine import aotcache
from vilbert_multitask_tpu.engine import decode as dec
from vilbert_multitask_tpu.engine.labels import LabelMapStore
from vilbert_multitask_tpu.features.pipeline import (
    GLOBAL_BOX,
    RegionFeatures,
    batch_images,
    clip_regions,
    encode_image,
)
from vilbert_multitask_tpu.features.store import FeatureStore
from vilbert_multitask_tpu.models.heads import (
    SERVING_HEAD_MODULES,
    build_head_slabs,
)
from vilbert_multitask_tpu.models.vilbert import (
    ViLBertForVLTasks,
    ViLBertOutput,
    fused_head_output,
)
from vilbert_multitask_tpu.parallel import sharding as shd
from vilbert_multitask_tpu import quant
from vilbert_multitask_tpu.resilience import (
    CircuitBreaker,
    DeadlineExceeded,
    ReplicaKilled,
)
from vilbert_multitask_tpu.resilience.faults import fault_point
from vilbert_multitask_tpu import assets, obs

# XLA compiles are the dominant "why did THIS request take 4 s" answer;
# the counter makes them visible next to the queue gauges in /metrics.
_COMPILES = obs.REGISTRY.counter(
    "vmt_engine_compiles_total",
    "jit program compilations by program family.",
    labelnames=("program",))
from vilbert_multitask_tpu.text.pipeline import EncodedText, encode_question
from vilbert_multitask_tpu.text.wordpiece import FullTokenizer


_cache_enabled_for: Optional[str] = None


def _enable_compilation_cache(path: str,
                              min_compile_secs: float = 2.0) -> None:
    """Turn on JAX's persistent compilation cache (process-global, so set
    once; JAX has one cache per process). A second engine requesting a
    DIFFERENT path keeps the first's — but loudly: the conflict is recorded
    so a misconfigured pool doesn't silently share (or split) cache state.
    ``min_compile_secs`` is the persistence floor
    (jax_persistent_cache_min_compile_time_secs): compilations faster than
    it are never written — 0.0 persists everything, which is what the AOT
    cache wants (the small per-bucket programs dominate warmup COUNT)."""
    global _cache_enabled_for
    import os

    path = os.path.abspath(path)
    if _cache_enabled_for is not None:
        if _cache_enabled_for != path:
            import logging

            logging.getLogger(__name__).warning(
                "compilation cache already enabled for %s; ignoring "
                "request for %s (JAX has one persistent cache per process)",
                _cache_enabled_for, path)
            obs.record_event("compile_cache_path_conflict",
                             active=_cache_enabled_for, requested=path)
        return
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      float(min_compile_secs))
    _cache_enabled_for = path


class _AotProgram:
    """One compiled program behind a manifest record key, resolved lazily.

    The forward builders run under ``_compile_lock`` and must stay cheap —
    that lock is what lets parallel warmup overlap bucket compiles — so
    when the AOT cache is on they install THIS wrapper instead of doing
    any cache or compile work inline. Resolution happens at the first
    call, under a per-program lock (concurrent buckets still resolve in
    parallel): deserialize the cached executable on a hit, or
    ``fwd.lower(*abstract_args).compile()`` on a miss and backfill the
    cache with the serialized result.

    A deserialized executable is proven by its first successful call. If
    that first call fails (an executable serialized against a world the
    fingerprint failed to distinguish), the wrapper permanently falls
    back to the plain jitted forward and counts the recompile. After the
    first proven call errors propagate unwrapped — transient device
    failures must reach the breaker/degrade machinery, not be masked as
    cache fallbacks.
    """

    def __init__(self, engine: "InferenceEngine", family: str, bucket: int,
                 attn: bool, fwd, rec_key: str, model_gen: int):
        self._engine = engine
        self._family = family
        self._bucket = bucket
        self._attn = attn
        self._fwd = fwd
        self.record_key = rec_key
        self._model_gen = model_gen
        self._lock = threading.Lock()
        self._fn = None
        self._proven = False
        self.from_cache = False
        self.fell_back = False

    @property
    def resolved(self) -> bool:
        return self._fn is not None

    def ensure(self, load_only: bool = False) -> Optional[str]:
        """Resolve the callable: ``"hit"`` (deserialized from the cache),
        ``"compiled"`` (traced+compiled, cache backfilled), or None when
        ``load_only`` and the cache missed (nothing compiled — the caller
        decides whether to pay the compile)."""
        with self._lock:
            if self._fn is not None:
                return "hit" if self.from_cache else "compiled"
            eng = self._engine
            t0 = time.perf_counter()
            loaded = eng._aot.load(self.record_key,
                                   model_gen=self._model_gen,
                                   program=self._family)
            if loaded is not None:
                eng.book_boot_time("cache_load_s",
                                   time.perf_counter() - t0)
                self._fn = loaded
                self.from_cache = True
                return "hit"
            if load_only:
                return None
            t0 = time.perf_counter()
            args = eng._abstract_forward_args(self._family, self._bucket)
            compiled = self._fwd.lower(*args).compile()
            dt = time.perf_counter() - t0
            _COMPILES.inc(program=self._family)
            aotcache.record_compile_ms(dt * 1e3)
            eng.book_boot_time("compile_s", dt)
            eng._aot.store(self.record_key, compiled,
                           model_gen=self._model_gen)
            self._fn = compiled
            return "compiled"

    def __call__(self, *args):
        self.ensure()
        fn = self._fn
        if self._proven:
            return fn(*args)
        try:
            out = fn(*args)
        except Exception as e:  # noqa: BLE001 — only the unproven
            # deserialized-executable case is handled; everything else
            # (including compile errors from ensure's lower) propagates to
            # the dispatch funnel's degrade/breaker machinery.
            if not self.from_cache:
                raise
            obs.record_event("aot_cache_exec_fallback",
                             key=self.record_key, error=repr(e))
            with self._lock:
                self._fn = self._fwd
                self.from_cache = False
                self.fell_back = True
            _COMPILES.inc(program=self._family)
            out = self._fwd(*args)
        self._proven = True
        return out


@dataclasses.dataclass
class PreparedRequest:
    """Host-side buffers for one request, already bucketed.

    ``features`` is stored in the engine's *transfer dtype*: bf16 when the
    engine computes in bf16 (the model's first dense layer casts inputs to
    the compute dtype anyway — see models/embeddings.py ImageEmbeddings — so
    pre-casting on the host is bit-identical and halves the dominant
    host→device payload), f32 otherwise (test/golden-fixture engines).
    """

    spec: TaskSpec
    n_images: int
    bucket: int
    text: EncodedText  # (bucket, Nt)
    features: np.ndarray  # (bucket, Nv, D) transfer dtype
    spatials: np.ndarray  # (bucket, Nv, 5) f32 (decode reads these host-side)
    image_mask: np.ndarray  # (bucket, Nv)
    task_ids: np.ndarray  # (bucket, 1)
    images: List[dec.ImageMeta]
    # Stable per-image identities for the device input cache (one string
    # per REAL image row, length n_images), or None for novel uploads /
    # synthetic defaults. Row-level so any bucket size shares entries.
    cache_keys: Optional[List[str]] = None


class InferenceEngine:
    """One engine per process: owns params, tokenizer, stores, compile cache."""

    def __init__(
        self,
        cfg: Optional[FrameworkConfig] = None,
        *,
        params=None,
        tokenizer: Optional[FullTokenizer] = None,
        feature_store: Optional[FeatureStore] = None,
        label_store: Optional[LabelMapStore] = None,
        mesh=None,
        seed: int = 0,
        replica_id: Optional[str] = None,
        aot_cache: Optional[aotcache.AotCache] = None,
    ):
        self.cfg = cfg or FrameworkConfig()
        # Replica identity (serve/pool.py): None for standalone engines.
        # Threads through the breaker name, live_stats keys, and forward
        # spans so N same-process replicas stay distinguishable in every
        # telemetry surface.
        self.replica_id = replica_id
        # Flipped by ReplicaPool.kill() (chaos) or by the pool when a
        # health probe declares this replica dead: every subsequent
        # dispatch fails fast with ReplicaKilled so in-flight batches fail
        # over instead of completing against a corpse.
        self.killed = False
        ecfg = self.cfg.engine
        self.compute_dtype = jnp.dtype(ecfg.compute_dtype)
        # Storage dtype of the served param tree (EngineConfig.param_dtype).
        # bf16 halves every weight read at serving shapes — where the MXU is
        # weight-read-bound, that is the roofline (see engine/flops.py) —
        # and halves the one-time boot upload. "int8" halves it again:
        # floating matrix leaves become per-channel {"int8", "scale"} pairs
        # (quant.py) and the jitted forward dequantizes them in-program
        # right before the matmuls, so HBM reads stay int8. Training never
        # sees this: the trainer builds/restores its own f32 master tree.
        self.param_dtype = jnp.dtype(ecfg.param_dtype)
        self.param_quantized = self.param_dtype == jnp.dtype(jnp.int8)
        if not (self.param_quantized
                or jnp.issubdtype(self.param_dtype, jnp.floating)):
            raise ValueError(
                f"engine.param_dtype must be a floating dtype or 'int8', "
                f"got {ecfg.param_dtype!r}")
        # Engine kernel knobs win over the model config, unconditionally —
        # kernel selection must not depend on which config carried a flag.
        model_cfg = dataclasses.replace(
            self.cfg.model,
            use_pallas_coattention=ecfg.use_pallas_coattention,
            use_pallas_self_attention=ecfg.use_pallas_self_attention,
        )
        # Sequence-parallel routing: a mesh with a real "sp" axis
        # (MeshConfig.sp > 1) opts the visual stream into ring attention
        # for buckets at/above ring_min_regions — the long-context path.
        # Demo-scale buckets (≤101 regions) stay dense; the decision is
        # static per compiled bucket (RingContext.engages).
        from vilbert_multitask_tpu.parallel.ring import RingContext

        self._ring_v = RingContext.from_mesh(
            mesh, min_seq=ecfg.ring_min_regions)
        self.model = ViLBertForVLTasks(model_cfg, ring_v=self._ring_v,
                                       dtype=self.compute_dtype)
        # Default assets: the committed vocab/label files — real file-loading
        # paths (reference worker.py:537-539, 299-315), not in-memory toys.
        self.tokenizer = tokenizer or FullTokenizer.from_vocab_file(
            ecfg.vocab_path or assets.default_vocab_path())
        self._check_vocab_coherence()
        self.feature_store = feature_store
        self.labels = label_store or LabelMapStore(
            root=ecfg.labels_root or assets.default_labels_root(),
            sizes={"vqa": self.cfg.model.num_labels,
                   "gqa": self.cfg.model.gqa_num_labels}
        )
        self.mesh = mesh
        if ecfg.compilation_cache_dir:
            min_secs = ecfg.persistent_cache_min_compile_secs
            if min_secs is None:
                # Auto: with the AOT cache on, persist EVERY compile —
                # warmup count is dominated by small per-bucket programs
                # the 2.0 s JAX default would skip.
                min_secs = 0.0 if ecfg.aot_cache_dir else 2.0
            _enable_compilation_cache(ecfg.compilation_cache_dir, min_secs)
        # Boot-phase timing split (restore_s is stamped by the serving
        # layer that owns the checkpoint read; cache_load_s/compile_s
        # accumulate as programs resolve; upload_s below).
        self.boot_times: Dict[str, float] = {}
        self._boot_lock = threading.Lock()
        # Task-id → label-head gather table for the fused decode program
        # (index 1 = the GQA head, 0 = the VQA head): a static python tuple
        # the jitted _fused_bundle embeds as a tiny constant.
        n_tasks = max(TASK_REGISTRY) + 1
        self._gqa_gather = tuple(
            1 if (t in TASK_REGISTRY
                  and TASK_REGISTRY[t].head == "vil_prediction_gqa") else 0
            for t in range(n_tasks))
        # The fused head-slab stacking program, built before the first
        # params publish below (the setter runs it when fused heads are on).
        self._head_slab_builder = self._make_head_slab_builder()
        if params is None:
            # One-time boot transfer: PRNGKey materializes its seed scalar
            # host→device. Explicitly allowed so engine construction stays
            # legal under the tests' jax.transfer_guard("disallow")
            # sanitizer (tests/conftest.py) — this is the only implicit
            # upload on the boot path, and it is intentional.
            with jax.transfer_guard("allow"):
                boot_key = jax.random.PRNGKey(seed)
            params = self.init_params(boot_key)
        t_up = time.perf_counter()
        params = self._place_params(params)
        jax.block_until_ready(params)
        self.params = params
        self.book_boot_time("upload_s", time.perf_counter() - t_up)
        # AOT executable cache (engine/aotcache.py): a shared instance from
        # the serving layer (one per pool, prefetched during restore) wins;
        # otherwise built here from the config knob. Constructed AFTER the
        # params publish so the fingerprint records whether this engine
        # actually serves fused head slabs.
        if aot_cache is not None:
            self._aot: Optional[aotcache.AotCache] = aot_cache
        elif ecfg.aot_cache_dir:
            self._aot = aotcache.AotCache(
                ecfg.aot_cache_dir,
                aotcache.compile_fingerprint(
                    self.cfg, mesh=mesh, heads=self.head_slabs is not None))
        else:
            self._aot = None
        # keyed ('batched'|'rows', bucket, collect_attention, model_gen) —
        # see _forward / _forward_rows
        self._compiled: Dict[Tuple[str, int, bool, int], callable] = {}
        self.stage_times: Dict[str, float] = {}
        # Set by the first forward if Mosaic rejected the Pallas kernels on
        # this backend and the engine degraded to the XLA attention path.
        # _model_gen increments on degrade; the compile cache is keyed by it
        # so a closure built against the pre-degrade model can never be
        # served to a post-degrade call (parallel-warmup race).
        self.kernel_fallback = False
        self._model_gen = 0
        self._fallback_lock = threading.Lock()
        # Guards the _compiled dict itself (parallel warmup threads race
        # check-then-insert against _degrade_to_xla's clear()). Ordering:
        # _fallback_lock may be held when taking this one, never the
        # reverse — the builders take only _compile_lock.
        self._compile_lock = threading.Lock()
        # Breaker over the forward funnel (_call_forward): sustained device
        # failures (dead tunnel, OOM loop) fail jobs fast toward the queue's
        # dead-letter path instead of stalling the worker on each one. The
        # threshold is deliberately laxer than the transport breaker's —
        # one-off runtime errors (worst case: one bad request per window)
        # must not poison a shared engine.
        breaker_name = ("engine.forward" if replica_id is None
                        else f"engine.forward.{replica_id}")
        self._breaker = CircuitBreaker(
            name=breaker_name, failure_threshold=8, window_s=60.0,
            reset_timeout_s=15.0)
        # Device input cache: encoded region tensors for content-stable
        # (store-backed) images, pinned in HBM after first use — the input
        # analogue of the one-time param device_put above. Rows live in the
        # row slab (see _row_slab); the cache maps key → slab slot, LRU
        # over EngineConfig.device_input_cache_entries.
        self._input_cache: "OrderedDict[str, int]" = OrderedDict()
        self._input_cache_lock = threading.Lock()
        self._input_cache_hits = 0
        self._input_cache_misses = 0
        # Row slab state (built lazily under _input_cache_lock): the slab
        # tensors, the free cache-slot pool, the scratch rotor, and the
        # jitted single-row insert program.
        self._slab: Optional[dict] = None
        self._slab_free: List[int] = []
        self._slab_scratch0 = 0
        self._slab_scratch_n = 0
        self._scratch_next = 0
        self._slab_insert_fn = None

    # ----------------------------------------------------- served tree state
    # The served weights publish as ONE attribute write of a (params,
    # head_slabs) pair, so a dispatch can never observe a new tree with the
    # previous tree's fused head slabs (or vice versa) mid-swap.

    @property
    def params(self):
        """The served param tree (published atomically with its fused
        head slabs — see :meth:`load_params`)."""
        return self._served[0]

    @params.setter
    def params(self, tree):
        # Head-less trees (e.g. boot probes with params={}) publish without
        # slabs; decode falls back to the per-head path until a full tree
        # lands.
        build = (self.cfg.engine.fused_task_heads
                 and all(n in tree for n in SERVING_HEAD_MODULES))
        slabs = self._build_head_slabs(tree) if build else None
        self._served = (tree, slabs)

    @property
    def head_slabs(self):
        """Device-resident fused decode-head slabs (models/heads.py:
        build_head_slabs over the served tree; int8 kernel slabs when the
        storage mode is quantized). None when fused_task_heads is off."""
        return self._served[1]

    def _place_params(self, params):
        """Cast/quantize + device-pin a param tree — the ONE placement
        path __init__ and load_params share.

        Device-pinning mirrors the reference's one-time ``model.cuda(0)``
        (worker.py:534-536): without it every jitted forward re-uploads
        ~1 GB of f32 weights host→TPU (23.7 s/query over the remote-TPU
        link in round 2). Host trees (checkpoint restores, test fixtures)
        cast — or int8-quantize — host-side first, so the upload ships the
        small representation; already-committed device trees (init_params)
        quantize under jit instead, because an eager quantize's scalar
        constants would be implicit transfers (the conftest sanitizer).
        """
        if self.mesh is not None:
            return shd.shard_params(params, self.mesh,
                                    dtype=self.param_dtype)
        host = any(isinstance(x, np.ndarray)
                   for x in jax.tree_util.tree_leaves(params))
        if self.param_quantized and not host:
            return jax.jit(quant.quantize_tree)(params)
        return jax.device_put(shd.cast_floating(params, self.param_dtype))

    def _make_head_slab_builder(self):
        """Jitted head-slab stacker, built once in ``__init__`` (same
        shapes across swaps — load_params stays zero-recompile for the
        forward programs and pays only this tiny stacking program). In
        int8 mode the wide kernel slabs are re-quantized after stacking so
        slab HBM reads stay int8 too; LN scales and biases stay floating —
        they are a rounding error of the byte budget and
        precision-critical.
        """
        mcfg = self.cfg.model
        quantized = self.param_quantized

        def build(tree):
            heads = {n: tree[n] for n in SERVING_HEAD_MODULES}
            if quantized:
                heads = quant.dequantize_tree(heads, jnp.float32)
            slabs = build_head_slabs(heads, mcfg)
            if quantized:
                slabs = {k: (quant.quantize_leaf(v)
                             if k.endswith("kernel") else v)
                         for k, v in slabs.items()}
            return slabs

        return jax.jit(build)

    def _build_head_slabs(self, params):
        """Stack the nine task heads into the fused slab tree, on device
        (:meth:`_make_head_slab_builder`'s compiled program)."""
        slabs = self._head_slab_builder(params)
        jax.block_until_ready(slabs)
        return slabs

    # ------------------------------------------------------------------ init
    def _check_vocab_coherence(self) -> None:
        """Boot-time guard: the loaded vocab must fit the embedding table.

        A vocab larger than ``vocab_size`` would emit token ids that index
        out of the embedding table — on TPU that's a silent gather clamp,
        not an error, so every over-range token would quietly read row
        vocab_size-1. Fail loudly here instead. The inverse gap (table much
        wider than the vocab, e.g. the 30,522-row serving table over the
        committed 1,037-token synthetic vocab) is legal but worth a log
        line: those rows are dead weight until the real vocab is swapped in
        (config.py EngineConfig.vocab_path).
        """
        n_vocab = len(self.tokenizer.vocab)
        n_rows = self.cfg.model.vocab_size
        if n_vocab > n_rows:
            raise ValueError(
                f"vocab file has {n_vocab} tokens but ViLBertConfig."
                f"vocab_size is {n_rows}: token ids would index out of the "
                f"embedding table. Fix vocab_path or vocab_size.")
        if n_rows > 2 * n_vocab:
            import logging

            logging.getLogger(__name__).warning(
                "embedding table has %d rows but the vocab only %d tokens "
                "(%.0f%% dead weight) — expected with the committed "
                "synthetic vocab; swap EngineConfig.vocab_path to the real "
                "bert-base-uncased vocab for score parity",
                n_rows, n_vocab, 100 * (1 - n_vocab / n_rows))

    def _dummy_host(self, batch: int) -> dict:
        """Host-side all-zeros batch in exactly the dtypes prepare() ships."""
        ecfg, mcfg = self.cfg.engine, self.cfg.model
        return dict(
            input_ids=np.zeros((batch, ecfg.max_text_len), np.int32),
            # Same dtype prepare() ships (transfer_dtype): a different input
            # dtype is a different XLA program — warmup must compile the one
            # live requests hit.
            features=np.zeros((batch, ecfg.max_regions, mcfg.v_feature_size),
                              self.transfer_dtype),
            spatials=np.zeros((batch, ecfg.max_regions, 5), np.float32),
            segment_ids=np.zeros((batch, ecfg.max_text_len), np.int32),
            input_mask=np.ones((batch, ecfg.max_text_len), np.int32),
            image_mask=np.ones((batch, ecfg.max_regions), np.int32),
            task_ids=np.zeros((batch, 1), np.int32),
        )

    def _dummy_batch(self, batch: int):
        # One explicit fused upload instead of seven implicit jnp.zeros
        # scalar-fill transfers — keeps warmup legal under
        # jax.transfer_guard("disallow") (the conftest sanitizer fixture).
        return jax.device_put(self._dummy_host(batch))

    def init_params(self, rng):
        """Random init, entirely on device (even batch so the paired NLVR2
        head materializes).

        The whole init runs under one jit so the tree is born on the chip —
        no device→host→device round trip (round 2's 259 s engine boot was
        exactly that round trip over the remote-TPU link). Params land in
        ``EngineConfig.param_dtype`` (f32 default; bf16 serving mode);
        compute casts to the compute dtype inside the model either way.
        """
        d = self._dummy_batch(2)
        # Init through an XLA-attention twin: the Pallas and XLA paths create
        # the IDENTICAL param tree (they share the projection submodules and
        # differ only in the attention computation), so initializing with the
        # kernels off keeps engine construction independent of whether Mosaic
        # accepts the kernel on this backend — warmup() is the single probe
        # point with the fallback.
        init_model = ViLBertForVLTasks(
            dataclasses.replace(
                self.model.config,
                use_pallas_coattention=False,
                use_pallas_self_attention=False),
            dtype=self.compute_dtype)

        # int8 trees quantize at the placement seam (_place_params) — the
        # init jit itself keeps f32 leaves.
        pdt = (self.param_dtype
               if jnp.issubdtype(self.param_dtype, jnp.floating)
               else jnp.dtype(jnp.float32))

        def _init(rng):
            variables = init_model.init(
                rng, d["input_ids"], d["features"], d["spatials"],
                d["segment_ids"], d["input_mask"], d["image_mask"], None,
                d["task_ids"], deterministic=True,
            )
            return jax.tree_util.tree_map(
                lambda x: x.astype(pdt)
                if jnp.issubdtype(x.dtype, jnp.floating) else x,
                variables["params"],
            )

        return jax.jit(_init)(rng)

    def load_params(self, params) -> None:
        """Hot-swap the served param tree (rolling checkpoint deploy).

        The compiled programs take params as a call argument, not a
        closure (``fwd(params, ...)``), so a same-shape tree swaps in with
        ZERO recompiles: placement/cast mirrors ``__init__``
        (:meth:`_place_params` — shard under a mesh, cast/quantize +
        device-pin otherwise, so an int8 engine RE-QUANTIZES a swapped f32
        checkpoint instead of silently serving it fat) and the publish is
        one attribute write of the (params, head_slabs) pair — an
        in-flight forward finishes against the pair it started with, the
        next dispatch reads the new one.
        """
        params = self._place_params(params)
        # Block BEFORE publishing: a half-uploaded tree must never be
        # observable, and the swap caller's timing should measure the
        # upload, not leak it into the next request's forward.
        jax.block_until_ready(params)
        self.params = params

    # -------------------------------------------------------------- compile
    # Max label-decode fanout (TaskSpec.top_k ≤ 3 for the labels family).
    _TOPK = 3

    @classmethod
    def _decode_bundle(cls, out: ViLBertOutput):
        """Device-side decode prep: softmax/top-k INSIDE the jitted forward.

        Serving runs against a tunneled chip where every device→host fetch
        pays a network RTT; pulling the wide answer heads (3129/1533 logits
        per row) after the forward made decode cost as much as the forward
        itself (BENCH r3 probe: 65 ms decode vs 65 ms forward). Everything
        each decode family needs is reduced on device to a few KB and
        fetched as ONE pytree. The reference never had this problem —
        its head tensors come back over PCIe (worker.py:287-289).
        """
        f32 = lambda x: x.astype(jnp.float32)  # noqa: E731
        vqa_v, vqa_i = jax.lax.top_k(
            jax.nn.softmax(f32(out.vil_prediction), axis=-1), cls._TOPK)
        gqa_v, gqa_i = jax.lax.top_k(
            jax.nn.softmax(f32(out.vil_prediction_gqa), axis=-1), cls._TOPK)
        return {
            "labels_top": {"vil_prediction": (vqa_v, vqa_i),
                           "vil_prediction_gqa": (gqa_v, gqa_i)},
            "vil_logit": f32(out.vil_logit),
            "vil_tri_prediction": f32(out.vil_tri_prediction),
            "vision_logit": f32(out.vision_logit),
            # The paired NLVR2 head only exists for even batches
            # (models/vilbert.py) — odd buckets never decode "binary".
            **({"vil_binary_prediction": f32(out.vil_binary_prediction)}
               if out.vil_binary_prediction is not None else {}),
        }

    @classmethod
    def _fused_bundle(cls, out: ViLBertOutput, label_logits, task_ids,
                      gqa_gather):
        """Decode bundle for the fused-head program: ONE f32 softmax/top-k
        over the label head GATHERED per row by task id (the in-program
        gather — stacked label logits never leave the device), written
        under BOTH label keys so :meth:`decode` stays family-agnostic.
        Padded label columns sit at heads.PAD_LOGIT_BIAS and underflow to
        probability zero, so top-k matches the per-head softmax."""
        f32 = lambda x: x.astype(jnp.float32)  # noqa: E731
        table = jnp.asarray(gqa_gather, jnp.int32)
        sel = table[jnp.clip(task_ids[:, 0], 0, table.shape[0] - 1)]
        row = jnp.take_along_axis(
            f32(label_logits), sel[:, None, None], axis=1)[:, 0]
        pair = jax.lax.top_k(jax.nn.softmax(row, axis=-1), cls._TOPK)
        return {
            "labels_top": {"vil_prediction": pair,
                           "vil_prediction_gqa": pair},
            "vil_logit": f32(out.vil_logit),
            "vil_tri_prediction": f32(out.vil_tri_prediction),
            "vision_logit": f32(out.vision_logit),
            **({"vil_binary_prediction": f32(out.vil_binary_prediction)}
               if out.vil_binary_prediction is not None else {}),
        }

    def _apply_heads(self, model, params, heads, batch, attn):
        """Shared trace body of the two forward builders: in-program int8
        dequant → trunk or full module apply → per-head or fused-slab
        heads → device-side decode bundle. Runs under jit only."""
        cdt = self.compute_dtype
        if self.param_quantized:
            # The fused values.astype(compute) * scales sits right before
            # each consuming matmul after XLA fusion — weight HBM reads
            # stay int8; only the trainer ever holds fat masters.
            params = quant.dequantize_tree(params, cdt)
        if heads is not None:
            trunk_out = model.apply(
                {"params": params},
                batch["input_ids"], batch["features"], batch["spatials"],
                batch["segment_ids"], batch["input_mask"],
                batch["image_mask"], None, batch["task_ids"],
                deterministic=True, output_all_attention_masks=attn,
                method="trunk",
            )
            slabs = (quant.dequantize_tree(heads, jnp.float32)
                     if self.param_quantized else heads)
            out, label_logits = fused_head_output(
                model.config, slabs, trunk_out, batch["image_mask"], cdt)
            bundle = self._fused_bundle(out, label_logits,
                                        batch["task_ids"], self._gqa_gather)
            return out, bundle
        out = model.apply(
            {"params": params},
            batch["input_ids"], batch["features"], batch["spatials"],
            batch["segment_ids"], batch["input_mask"],
            batch["image_mask"], None, batch["task_ids"],
            deterministic=True, output_all_attention_masks=attn,
            # serving decodes never read the masked-LM/region heads
            compute_pretraining_heads=False,
        )
        return out, InferenceEngine._decode_bundle(out)

    def _forward(self, bucket: int, collect_attention: bool):
        """Batched-input program (the mesh path: inputs are device_put with
        batch shardings as one (bucket, ...) tree per call). Signature is
        ``fwd(params, heads, batch)`` — ``heads`` is the persistent fused
        head-slab tree (None when fused_task_heads is off)."""
        key = ("batched", bucket, collect_attention, self._model_gen)
        with self._compile_lock:
            if key in self._compiled:
                return self._compiled[key]
            model = self.model
            engine = self

            @partial(jax.jit, static_argnames=("attn",))
            def fwd(params, heads, batch, attn=collect_attention):
                return engine._apply_heads(model, params, heads, batch, attn)

            fn = self._aot_resolve("batched", bucket, collect_attention, fwd)
            self._compiled[key] = fn
            return fn

    def _forward_rows(self, bucket: int, collect_attention: bool):
        """Row-slab program (the single-device serving path): image rows
        live in the device-resident slab (:meth:`_row_slab`) and the
        per-call ``pack`` carries the text tensors plus one (bucket,)
        int32 slot-index vector; the (bucket, ...) batch is GATHERED from
        the slab inside the compiled program. Rows that are already slab-
        resident (the input cache, the permanent pad slot 0) upload
        nothing. The flattened argument list is params + 3 slab leaves +
        5 pack leaves — constant in bucket size, so per-dispatch argument
        marshalling no longer scales with batch rows (the round-5
        ``manyarg_exec_ms`` suspect). The pack is freshly uploaded every
        call and never referenced again, so it is donated to XLA on
        backends that implement input donation (the slab, persistent
        cross-call state, must never be)."""
        key = ("rows", bucket, collect_attention, self._model_gen)
        with self._compile_lock:
            if key in self._compiled:
                return self._compiled[key]
            model = self.model
            engine = self
            donate = (("pack",)
                      if jax.default_backend() in ("tpu", "gpu") else ())

            @partial(jax.jit, static_argnames=("attn",),
                     donate_argnames=donate)
            def fwd(params, heads, slab, pack, attn=collect_attention):
                rows = pack["rows"]
                batch = dict(
                    input_ids=pack["input_ids"],
                    features=slab["features"][rows],
                    spatials=slab["spatials"][rows],
                    segment_ids=pack["segment_ids"],
                    input_mask=pack["input_mask"],
                    image_mask=slab["image_mask"][rows],
                    task_ids=pack["task_ids"],
                )
                return engine._apply_heads(model, params, heads, batch, attn)

            fn = self._aot_resolve("rows", bucket, collect_attention, fwd)
            self._compiled[key] = fn
            return fn

    def _aot_resolve(self, family: str, bucket: int, attn: bool, fwd):
        """What the builders install under their compile key. Without the
        AOT cache: the plain jitted forward, counted as a compile here
        (first call traces+compiles — the pre-cache behavior, unchanged).
        With it: an :class:`_AotProgram` wrapper; the compile counter
        moves to the wrapper's resolution, so ``vmt_engine_compiles_total``
        keeps meaning REAL compiles. Runs under ``_compile_lock`` — no IO,
        no compile, just key formatting."""
        if self._aot is None:
            _COMPILES.inc(program=family)
            return fwd
        ecfg = self.cfg.engine
        rec = aotcache.record_key(
            family, bucket, ecfg.param_dtype, ecfg.fused_task_heads,
            aotcache.topology_id(self.cfg.mesh), attn)
        return _AotProgram(self, family, bucket, attn, fwd, rec,
                           self._model_gen)

    def _abstract_forward_args(self, family: str, bucket: int):
        """ShapeDtypeStruct argument trees for ``fwd.lower()`` — exactly
        the live call's shapes/dtypes (and, under a mesh, shardings), so
        the AOT-compiled executable binds to what dispatch actually ships.
        The static ``attn`` argument keeps its closure default, so only
        the array arguments appear here."""
        params, heads = self._served
        if self.mesh is not None:
            def sds(x):
                return jax.ShapeDtypeStruct(x.shape, x.dtype,
                                            sharding=x.sharding)
        else:
            def sds(x):
                return jax.ShapeDtypeStruct(x.shape, x.dtype)
        params_a = jax.tree_util.tree_map(sds, params)
        heads_a = (None if heads is None
                   else jax.tree_util.tree_map(sds, heads))
        if family == "batched":
            host = self._dummy_host(bucket)
            if self.mesh is not None:
                shards = shd.batch_shardings(host, self.mesh)
                batch_a = jax.tree_util.tree_map(
                    lambda x, s: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                                      sharding=s),
                    host, shards)
            else:
                batch_a = {n: jax.ShapeDtypeStruct(v.shape, v.dtype)
                           for n, v in host.items()}
            return (params_a, heads_a, batch_a)
        # rows: the slab shapes mirror _row_slab, the pack mirrors
        # _run_rows' explicit device_put.
        ecfg, mcfg = self.cfg.engine, self.cfg.model
        n_rows = (1 + ecfg.device_input_cache_entries
                  + ecfg.max_batch_rows())
        nv = ecfg.max_regions
        slab_a = dict(
            features=jax.ShapeDtypeStruct(
                (n_rows, nv, mcfg.v_feature_size), self.transfer_dtype),
            spatials=jax.ShapeDtypeStruct((n_rows, nv, 5), np.float32),
            image_mask=jax.ShapeDtypeStruct((n_rows, nv), np.int32))
        text_shape = (bucket, ecfg.max_text_len)
        pack_a = dict(
            input_ids=jax.ShapeDtypeStruct(text_shape, np.int32),
            segment_ids=jax.ShapeDtypeStruct(text_shape, np.int32),
            input_mask=jax.ShapeDtypeStruct(text_shape, np.int32),
            task_ids=jax.ShapeDtypeStruct((bucket, 1), np.int32),
            rows=jax.ShapeDtypeStruct((bucket,), np.int32))
        return (params_a, heads_a, slab_a, pack_a)

    def book_boot_time(self, phase: str, seconds: float) -> None:
        """Accumulate one boot-phase duration (restore_s / cache_load_s /
        compile_s / upload_s). The serving layer stamps restore_s; the
        engine books the rest. Surfaces in live_stats() → /healthz."""
        with self._boot_lock:
            self.boot_times[phase] = (
                self.boot_times.get(phase, 0.0) + seconds)

    def boot_from_cache(self, buckets: Optional[Sequence[int]] = None
                        ) -> bool:
        """Warm-boot path: install every warmup program from the AOT cache
        WITHOUT compiling anything. True iff every bucket's program
        deserialized — the pool then skips warmup() entirely (executables
        are proven by their first live call; a stale one falls back to the
        jitted forward, see :class:`_AotProgram`). On any miss nothing was
        compiled here — the caller falls back to warmup(), which compiles
        the misses and backfills the cache."""
        if self._aot is None:
            return False
        buckets = list(buckets if buckets is not None
                       else self.cfg.engine.all_row_buckets())
        builder = self._forward if self.mesh is not None \
            else self._forward_rows
        ok = True
        for b in buckets:
            fn = builder(b, False)
            if isinstance(fn, _AotProgram):
                ok = (fn.ensure(load_only=True) is not None) and ok
        return ok

    def aot_compile_record(self, family: str, bucket: int, attn: bool
                           ) -> str:
        """Prewarm one manifest record: ``"hit"`` if already cached, else
        lower+compile+serialize → ``"compiled"`` (the engine.prewarm CLI's
        per-record primitive)."""
        if self._aot is None:
            raise RuntimeError("aot_compile_record needs the AOT cache "
                               "(set EngineConfig.aot_cache_dir)")
        builder = self._forward if family == "batched" \
            else self._forward_rows
        fn = builder(bucket, attn)
        if not isinstance(fn, _AotProgram):
            return "compiled"
        return fn.ensure() or "compiled"

    @property
    def pallas_enabled(self) -> bool:
        """Effective kernel selection (config flags minus any fallback)."""
        return (self.model.config.use_pallas_coattention
                or self.model.config.use_pallas_self_attention)

    # Substrings that identify a Pallas/Mosaic compile rejection. Transient
    # runtime failures (RESOURCE_EXHAUSTED, UNAVAILABLE, RPC disconnects on a
    # tunneled chip) deliberately do NOT match: degrading the engine for the
    # rest of its lifetime over a one-off hiccup would silently cost the
    # kernel's speedup — those propagate to the serving layer's per-job
    # failure isolation and the next request retries the kernel path.
    _KERNEL_ERR_MARKERS = ("mosaic", "pallas", "tpu_custom_call",
                           "lowering", "unimplemented", "not implemented",
                           "unsupported")

    @classmethod
    def _is_kernel_rejection(cls, err: BaseException) -> bool:
        text = f"{type(err).__name__}: {err}".lower()
        return any(m in text for m in cls._KERNEL_ERR_MARKERS)

    def _degrade_to_xla(self, err: BaseException) -> None:
        """Rebuild the engine on the XLA attention path after a kernel
        compile failure; re-raises when the failure can't be the kernel's."""
        if (not self.pallas_enabled or self.kernel_fallback
                or not self._is_kernel_rejection(err)):
            raise err
        import logging

        logging.getLogger(__name__).warning(
            "Pallas kernel path failed to compile (%s); "
            "falling back to XLA attention", err)
        self.kernel_fallback = True
        self.model = ViLBertForVLTasks(
            dataclasses.replace(
                self.model.config,
                use_pallas_coattention=False,
                use_pallas_self_attention=False),
            ring_v=self._ring_v,
            dtype=self.compute_dtype)
        self._model_gen += 1
        with self._compile_lock:  # racing builder inserts are keyed out
            self._compiled.clear()  # memory hygiene

    def _call_forward(self, bucket: int, collect_attention: bool, *args,
                      rows: bool = False):
        """All device forwards funnel through here — resilience gate first.

        ``fault_point("engine.dispatch")`` lets a chaos plan flap/slow the
        device path; the breaker turns SUSTAINED dispatch failures (dead
        tunnel, OOM loop) into fast fails so jobs drain toward dead-letter
        instead of each stalling the worker. A dispatch that degrades to
        XLA and then succeeds counts as a success — degrade is recovery,
        not failure.
        """
        fault_point("engine.dispatch")
        if self.killed:
            raise ReplicaKilled(
                f"engine replica {self.replica_id or '?'} is dead")
        self._breaker.preflight()
        try:
            result = self._dispatch_forward(bucket, collect_attention,
                                            *args, rows=rows)
        except Exception:
            self._breaker.record_failure()
            raise
        self._breaker.record_success()
        return result

    def _dispatch_forward(self, bucket: int, collect_attention: bool, *args,
                          rows: bool = False):
        """The Pallas probe under the resilience gate.

        The kernels are default-on; if Mosaic rejects them on this backend
        (new TPU generation, toolchain skew), the engine degrades itself to
        the XLA attention path and retries ONCE instead of taking the
        deployment down — so every consumer gets the fallback (ServeApp,
        evals, bench, and un-warmed engines whose first compile happens on a
        live request). A second failure propagates: it isn't the kernel.
        """
        builder = self._forward_rows if rows else self._forward
        gen_before = self._model_gen
        # One atomic read of the (params, head_slabs) pair: a concurrent
        # load_params can never hand this dispatch a new tree with the old
        # tree's fused head slabs.
        params, heads = self._served
        try:
            return builder(bucket, collect_attention)(params, heads, *args)
        except Exception as e:  # noqa: BLE001 — compile-time rejection
            with self._fallback_lock:
                # Parallel warmup: several buckets can hit the rejection at
                # once; the first thread degrades, the rest just retry on
                # the already-rebuilt XLA model.
                if not self.kernel_fallback:
                    self._degrade_to_xla(e)  # re-raises unless kernel's fault
            if self._model_gen == gen_before:
                # No degrade happened during this call — the engine was
                # already on the XLA path, so this is a genuine runtime
                # error; re-running the forward would double device work
                # exactly when the device is struggling.
                raise
            return builder(bucket, collect_attention)(params, heads, *args)

    def warmup(self, buckets: Optional[Sequence[int]] = None,
               parallel: Optional[bool] = None) -> None:
        """Pre-compile every shape bucket so first requests pay no compile.

        With ``parallel`` (default from EngineConfig), buckets compile
        concurrently: XLA compilation is C++ and releases the GIL, so the
        full bucket set warms in roughly the longest single compile instead
        of the sum — the difference between a ~70s and a ~20s boot on a
        v5e. Kernel-rejection fallback stays correct under concurrency
        (the first failing thread degrades under a lock; others retry on
        the rebuilt XLA model).
        """
        # Default set covers everything serving dispatches: the image
        # buckets (run()) AND the throughput buckets (run_many under
        # backlog) — otherwise the first big batch stalls on a mid-serving
        # compile, breaking this method's contract.
        buckets = list(buckets if buckets is not None
                       else self.cfg.engine.all_row_buckets())
        if parallel is None:
            parallel = self.cfg.engine.parallel_warmup

        def _warm_one(b: int) -> None:
            if self.mesh is not None:
                # Match run()'s input shardings exactly — a different input
                # sharding is a different XLA program (fresh compile).
                batch = shd.place_batch(self._dummy_batch(b), self.mesh)
                _, bundle = self._call_forward(b, False, batch)
            else:
                # Warm the slab program run()/run_many() actually use —
                # dummy rows route through the scratch slots, which also
                # warms the slab insert program.
                host = self._dummy_host(b)
                text = {k: host[k] for k in
                        ("input_ids", "segment_ids", "input_mask", "task_ids")}
                rows = [(dict(features=host["features"][i],
                              spatials=host["spatials"][i],
                              image_mask=host["image_mask"][i]), None)
                        for i in range(b)]
                _, bundle = self._run_rows(b, False, text, rows)
            jax.block_until_ready(bundle["vil_logit"])

        if parallel and len(buckets) > 1:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(max_workers=len(buckets)) as pool:
                # list() propagates the first worker exception to the caller.
                list(pool.map(_warm_one, buckets))
        else:
            for b in buckets:
                _warm_one(b)

    # -------------------------------------------------------------- prepare
    def prepare_from_store(self, task_id: int, question: str,
                           image_paths: Sequence[str]) -> PreparedRequest:
        """prepare() with regions AND device-cache identities from the
        attached feature store in one read (store.fetch) — the identity is
        captured at read time, so the cache can never bind a fresh key to
        stale tensors. The single place the store→cache-key contract lives;
        serving (_intake) and predict() both come through here. Stores
        without fetch() (minimal test doubles) just skip device caching."""
        if self.feature_store is None:
            raise RuntimeError("prepare_from_store() needs a FeatureStore; "
                               "use prepare() with in-memory regions instead")
        fetch = getattr(self.feature_store, "fetch", None)
        t_fetch = time.perf_counter()
        with obs.span("engine.features", source="store",
                      n_images=len(image_paths), task_id=task_id):
            if fetch is not None:
                pairs = [fetch(p) for p in image_paths]
                regions = [r for r, _ in pairs]
                cache_keys: Optional[List[str]] = [k for _, k in pairs]
            else:
                regions = self.feature_store.get_batch(image_paths)
                cache_keys = None
        fetch_s = time.perf_counter() - t_fetch
        req = self.prepare(task_id, question, regions, image_paths,
                           cache_keys=cache_keys)
        # prepare() booked the host-side region encode; the store read
        # belongs to the same "features" stage.
        self.stage_times["features_s"] = (
            self.stage_times.get("features_s", 0.0) + fetch_s)
        return req

    @property
    def transfer_dtype(self) -> np.dtype:
        """Dtype region features ship to the device in: the compute dtype
        when it's a 16-bit float (bit-identical — the model casts inputs to
        compute dtype at its first dense layer — and half the bytes over the
        host↔TPU link), f32 otherwise."""
        if (jnp.issubdtype(self.compute_dtype, jnp.floating)
                and self.compute_dtype.itemsize == 2):
            return self.compute_dtype
        return np.dtype(np.float32)

    def prepare(
        self,
        task_id: int,
        question: str,
        regions: Sequence[RegionFeatures],
        image_paths: Optional[Sequence[str]] = None,
        *,
        cache_keys: Optional[Sequence[str]] = None,
    ) -> PreparedRequest:
        """Host-side preprocessing: validate, tokenize, encode, bucket.

        Mirrors ``custom_prediction`` (worker.py:388-458) + the repeat
        semantics in ``prediction`` (worker.py:256-284).

        ``cache_keys`` (one stable identity string per image, e.g. the
        store path) opts this request's region tensors into the device
        input cache — pass them ONLY for content-stable images; never
        derived from the synthetic ``image_paths`` defaults.
        """
        if task_id not in TASK_REGISTRY:
            raise ValueError(f"unknown task_id {task_id}")
        spec = TASK_REGISTRY[task_id]
        n = len(regions)
        spec.validate_num_images(n)
        ecfg = self.cfg.engine
        bucket = n if n == 1 else ecfg.bucket_for(n)

        t_tok = time.perf_counter()
        with obs.span("engine.tokenize", task_id=task_id):
            text = encode_question(
                self.tokenizer, question, ecfg.max_text_len, task_id=task_id,
                lowercase=self.cfg.serving.lowercase_questions,
            ).stack(bucket)
        self.stage_times["tokenize_s"] = time.perf_counter() - t_tok
        t_feat = time.perf_counter()
        with obs.span("engine.features", source="encode", n_images=n,
                      task_id=task_id):
            # Feature files are confidence-ordered (extractor top-K order,
            # same as the reference's .npy dumps), so an over-provisioned
            # store clips to this engine's region budget instead of erroring.
            regions = clip_regions(regions, ecfg.max_regions,
                                   num_features=ecfg.num_features)
            encoded = [encode_image(r, ecfg.max_regions) for r in regions]
            feats, spatials, image_mask = batch_images(encoded, pad_to=bucket)
            feats = feats.astype(self.transfer_dtype, copy=False)
        self.stage_times["features_s"] = time.perf_counter() - t_feat
        task_ids = np.full((bucket, 1), task_id, np.int32)
        if cache_keys is not None:
            if len(cache_keys) != n:
                raise ValueError(
                    f"got {len(cache_keys)} cache keys for {n} images")
            cache_keys = (list(cache_keys)
                          if ecfg.device_input_cache_entries > 0 else None)
        paths = list(image_paths or [f"image_{i}" for i in range(n)])
        if len(paths) != n:
            raise ValueError(
                f"got {len(paths)} image paths for {n} feature sets"
            )
        images = [
            dec.ImageMeta(p, r.image_width, r.image_height)
            for p, r in zip(paths, regions)
        ]
        return PreparedRequest(spec, n, bucket, text, feats, spatials,
                               image_mask, task_ids, images,
                               cache_keys=cache_keys)

    # ---------------------------------------------------------------- decode
    def decode(self, req: PreparedRequest, bundle, row: int = 0
               ) -> dec.TaskResult:
        """Decode one request from the host decode bundle, batch row ``row``.

        ``bundle`` is the already-fetched pytree from :meth:`_decode_bundle`
        — pure numpy from here on; no device traffic in this method.
        """
        spec = req.spec
        if spec.decode == "labels":
            top_p, top_i = bundle["labels_top"][spec.head]
            return dec.decode_labels_topk(
                spec, np.asarray(top_i)[row], np.asarray(top_p)[row],
                self.labels)
        if spec.decode == "binary":
            # paired head: batch row 2k/2k+1 → pair row k (row must be even)
            return dec.decode_binary(
                spec, np.asarray(bundle["vil_binary_prediction"])[row // 2])
        if spec.decode == "trinary":
            return dec.decode_trinary(
                spec, np.asarray(bundle["vil_tri_prediction"])[row])
        if spec.decode == "ranking":
            scores = np.asarray(bundle["vil_logit"])[
                row : row + len(req.images)]
            return dec.decode_ranking(spec, scores, req.images)
        if spec.decode == "grounding":
            return dec.decode_grounding(
                spec, np.asarray(bundle["vision_logit"])[row],
                req.spatials[0], req.images[0])
        raise ValueError(f"unknown decode family {spec.decode}")

    # ---------------------------------------------------------------- serve
    def _row_slab(self) -> dict:
        """The device-resident row slab: one (S, Nv, ...) tensor per image
        input kind, S = 1 pad slot + cache slots + scratch slots.

        - slot 0 is the permanent padding row (zero features, global box,
          mask[0]=1 — features/pipeline.py batch_images): bucket padding
          references it by index and uploads nothing, ever;
        - slots 1..cache_entries hold content-stable store rows (LRU, keyed
          by the cache_keys from prepare()) — the round-3 input cache,
          relocated from loose per-row device dicts into slab slots so the
          forward can GATHER them with one index vector instead of taking
          3×bucket leaf arguments;
        - the trailing max_batch_rows() scratch slots receive novel/keyless
          uploads, rotor-allocated per pack.

        Built lazily ON DEVICE (a jitted zeros/constant program — no
        multi-MB boot upload). Updates are functional (``.at[slot].set``),
        so a forward dispatched against an older slab value keeps reading
        consistent rows while later packs insert — which is what makes
        run_many's bounded pipelining and scratch-rotor reuse safe.
        """
        if self._slab is None:
            with self._input_cache_lock:
                if self._slab is None:
                    ecfg, mcfg = self.cfg.engine, self.cfg.model
                    cache_slots = ecfg.device_input_cache_entries
                    scratch = ecfg.max_batch_rows()
                    n_rows = 1 + cache_slots + scratch
                    nv, dim = ecfg.max_regions, mcfg.v_feature_size
                    tdt = self.transfer_dtype
                    box = tuple(float(v) for v in GLOBAL_BOX)

                    def _build():
                        spat = jnp.zeros((n_rows, nv, 5), jnp.float32)
                        spat = spat.at[0, 0].set(jnp.array(box, jnp.float32))
                        mask = jnp.zeros((n_rows, nv), jnp.int32)
                        mask = mask.at[0, 0].set(1)
                        return dict(
                            features=jnp.zeros((n_rows, nv, dim), tdt),
                            spatials=spat, image_mask=mask)

                    self._slab_scratch0 = 1 + cache_slots
                    self._slab_scratch_n = scratch
                    self._slab_free = list(range(1, 1 + cache_slots))
                    self._slab = jax.jit(_build)()
        return self._slab

    def _slab_insert(self, slot: int, host_row: dict) -> None:
        """Upload one image row and write it into slab ``slot`` (caller
        holds _input_cache_lock). One fused explicit device_put per row —
        the same per-miss upload cost as the pre-slab cache — then one
        tiny constant-leaf jitted update dispatch."""
        if self._slab_insert_fn is None:
            def _ins(slab, row):
                i = row["slot"]
                return {k: slab[k].at[i].set(row[k].astype(slab[k].dtype))
                        for k in slab}

            self._slab_insert_fn = jax.jit(_ins)
        placed = jax.device_put(dict(
            features=host_row["features"], spatials=host_row["spatials"],
            image_mask=host_row["image_mask"],
            slot=np.asarray(slot, np.int32)))
        self._slab = self._slab_insert_fn(self._slab, placed)

    def _row_slot_locked(self, host_row: dict, key: Optional[str]) -> int:
        """Slab slot for one image row (caller holds _input_cache_lock):
        cache hit → existing slot; keyed miss → LRU cache slot + insert;
        keyless → next scratch slot + insert."""
        if key is not None:
            slot = self._input_cache.get(key)
            if slot is not None:
                self._input_cache.move_to_end(key)
                self._input_cache_hits += 1
                return slot
            self._input_cache_misses += 1
            if self._slab_free:
                slot = self._slab_free.pop()
            else:
                # Cache full: reuse the LRU entry's slot. In-flight
                # forwards captured the pre-insert slab value, so the
                # overwrite cannot corrupt a dispatched batch.
                _, slot = self._input_cache.popitem(last=False)
            self._input_cache[key] = slot
        else:
            # No stable identity → scratch rotor. One pack needs at most
            # max_batch_rows slots (= the scratch region size), and the
            # pack captures its slab value before releasing the lock, so
            # rotor wrap-around by later packs is invisible to it.
            slot = self._slab_scratch0 + (
                self._scratch_next % self._slab_scratch_n)
            self._scratch_next += 1
        self._slab_insert(slot, host_row)
        return slot

    @property
    def input_cache_stats(self) -> Dict[str, int]:
        """entries/hits/misses of the device input cache (observability)."""
        with self._input_cache_lock:
            return {"entries": len(self._input_cache),
                    "hits": self._input_cache_hits,
                    "misses": self._input_cache_misses}

    def live_stats(self) -> Dict[str, float]:
        """Point-in-time engine internals for the obs sampler: slab/cache
        occupancy, compiled-program count, dispatch-breaker state (the
        knobs an operator watches during a soak). Cheap — two lock holds,
        no device work."""
        cache_slots = self.cfg.engine.device_input_cache_entries
        with self._input_cache_lock:
            # Before the slab is lazily built every cache slot is free.
            free = (len(self._slab_free) if self._slab is not None
                    else cache_slots)
            stats = {
                "engine_cache_entries": float(len(self._input_cache)),
                "engine_slab_slots_used": float(cache_slots - free),
                "engine_slab_slots_total": float(cache_slots),
            }
        with self._compile_lock:
            stats["engine_compiled_programs"] = float(len(self._compiled))
            progs = [f for f in self._compiled.values()
                     if isinstance(f, _AotProgram)]
        if self._aot is not None:
            stats["engine_aot_hits"] = float(
                sum(1 for p in progs if p.from_cache))
            stats["engine_aot_compiled"] = float(
                sum(1 for p in progs if p.resolved and not p.from_cache
                    and not p.fell_back))
            stats["engine_aot_fallbacks"] = float(
                sum(1 for p in progs if p.fell_back))
        with self._boot_lock:
            for phase, secs in self.boot_times.items():
                stats[f"engine_boot_{phase}"] = float(secs)
        stats["engine_breaker_open"] = float(
            self._breaker.state != "closed")
        return stats

    def _pack_rows(self, rows: Sequence[Tuple[dict, Optional[str]]],
                   bucket: int) -> Tuple[dict, np.ndarray]:
        """Resolve each (host_row, cache_key) to a slab slot and return
        (slab value, (bucket,) int32 slot vector); pad slots are 0. The
        whole pack runs under one lock hold and captures the slab value
        before releasing it, so concurrent packs can never recycle this
        pack's scratch slots out from under its forward."""
        self._row_slab()  # built outside the (non-reentrant) lock hold
        with self._input_cache_lock:
            slots = [self._row_slot_locked(row, key) for row, key in rows]
            slab = self._slab
        slots.extend([0] * (bucket - len(slots)))
        return slab, np.asarray(slots, np.int32)

    def _run_rows(self, bucket: int, collect_attention: bool,
                  text_host: dict, rows: Sequence[Tuple[dict, Optional[str]]]):
        """Dispatch the O(1)-leaf rows program: pack the image rows into
        the slab, then ship text + slot indices as ONE fused explicit
        device_put (the donated ``pack`` argument)."""
        slab, slots = self._pack_rows(rows, bucket)
        pack = jax.device_put({**text_host, "rows": slots})
        return self._call_forward(bucket, collect_attention, slab, pack,
                                  rows=True)

    def _request_rows(self, req: PreparedRequest
                      ) -> List[Tuple[dict, Optional[str]]]:
        """A request's real image rows as (host_row, cache_key) pairs."""
        return [(dict(features=req.features[i], spatials=req.spatials[i],
                      image_mask=req.image_mask[i]),
                 req.cache_keys[i] if req.cache_keys is not None else None)
                for i in range(req.n_images)]

    def run(self, req: PreparedRequest, *, collect_attention: bool = False,
            deadline=None):
        """Device forward for a prepared request → (output, decoded result).

        ``deadline`` (a :class:`resilience.Deadline`) is checked at entry:
        dispatching a forward for a client that already gave up is the most
        expensive possible no-op, so an expired budget raises
        :class:`DeadlineExceeded` before any device work.
        """
        if deadline is not None and deadline.expired():
            raise DeadlineExceeded(
                f"deadline expired {-deadline.remaining_s():.2f}s before "
                f"dispatch (task {req.spec.task_id})")
        text = dict(
            input_ids=req.text.input_ids, segment_ids=req.text.segment_ids,
            input_mask=req.text.input_mask, task_ids=req.task_ids,
        )
        t0 = time.perf_counter()
        # The forward span closes only after the blocking device_get below —
        # jax dispatch is async, so fencing on the fetch is what makes the
        # span (and forward_s) measure device time instead of enqueue time.
        with obs.span("engine.forward", bucket=req.bucket,
                      task_id=req.spec.task_id,
                      replica=self.replica_id or ""):
            if self.mesh is not None:
                # Mesh serving ships the batched tree with batch shardings (a
                # local multi-chip host: PCIe upload is cheap; the row cache
                # is a single-device optimization).
                batch = {**text, "features": req.features,
                         "spatials": req.spatials,
                         "image_mask": req.image_mask}
                batch = shd.place_batch(batch, self.mesh)
                out, bundle = self._call_forward(req.bucket,
                                                 collect_attention, batch)
            else:
                # Slab path: cached rows resolve to slot indices (zero
                # upload); text + the index vector ship as one explicit
                # device_put inside _run_rows.
                out, bundle = self._run_rows(
                    req.bucket, collect_attention, text,
                    self._request_rows(req))
            # One blocking fetch of the few-KB decode bundle — forward_s
            # includes the single device→host round trip; decode is then
            # pure host math.
            bundle = jax.device_get(bundle)
        self.stage_times["forward_s"] = time.perf_counter() - t0
        t0 = time.perf_counter()
        with obs.span("engine.decode", task_id=req.spec.task_id):
            result = self.decode(req, bundle)
        self.stage_times["decode_s"] = time.perf_counter() - t0
        return out, result

    def run_many(
        self, reqs: Sequence[PreparedRequest], *,
        chunk_rows: Optional[int] = None, deadline=None,
        on_result=None,
    ) -> List[dec.TaskResult]:
        """Cross-task micro-batching: many single-image requests, ONE forward.

        The BASELINE.md "full 12-task round-robin batch (shared trunk, all
        heads hot)" serving mode — every head computes over the whole batch
        anyway (the trunk dominates), and per-row ``task_ids`` keep the
        task-token embeddings per-request, so any mix of tasks packs into
        MXU-efficient batches. Multi-image requests (NLVR2 pairs,
        retrieval) batch too — MIXED image counts share chunks: a
        request's rows stay consecutive inside a chunk, every decode
        family reads its own row span (see :meth:`decode`), and
        even-image-count requests lead each chunk so NLVR2 pairs keep the
        binary head's 2k/2k+1 alignment (see :meth:`chunk_plan`).

        ``on_result(pos, result)`` streams each member's decoded result as
        its chunk drains — the continuous-batching scheduler hands results
        to its completion stage while later chunks are still on the
        device. Exceptions from the callback propagate (the caller owns
        per-member error handling).
        """
        if not reqs:
            return []
        # Entry-only deadline check (batches carry per-job deadlines — the
        # worker sheds expired members BEFORE packing; this guards callers
        # that pass one shared budget for the whole batch, e.g. evals).
        if deadline is not None and deadline.expired():
            raise DeadlineExceeded(
                f"deadline expired {-deadline.remaining_s():.2f}s before "
                f"batch dispatch ({len(reqs)} requests)")
        # Oversized batches split into max-bucket chunks rather than erroring
        # (callers pick batch sizes; compiled buckets cap per-forward rows).
        # Bounded pipelining: up to _MAX_INFLIGHT_CHUNKS chunks dispatch
        # ahead of the oldest fetch — jax dispatch is async, so the host
        # packs/uploads chunk k+1 while the device computes chunk k (upload
        # hides behind compute on a network-attached chip) without letting
        # an arbitrarily long request list pile every chunk's buffers into
        # HBM at once.
        from collections import deque

        plan = self.chunk_plan([r.n_images for r in reqs],
                               chunk_rows=chunk_rows)
        chunks: List[List[Tuple[int, PreparedRequest]]] = [
            [(pos, reqs[pos]) for pos in idxs] for idxs in plan
        ]
        out: List[Optional[dec.TaskResult]] = [None] * len(reqs)
        pending: deque = deque()
        dec_s = 0.0
        t0 = time.perf_counter()

        def _drain_one() -> None:
            nonlocal dec_s
            c, bundle = pending.popleft()
            bundle = jax.device_get(bundle)
            td = time.perf_counter()
            with obs.span("engine.decode", n_requests=len(c)):
                row = 0
                for pos, r in c:
                    out[pos] = self.decode(r, bundle, row=row)
                    row += r.n_images
                    if on_result is not None:
                        on_result(pos, out[pos])
            dec_s += time.perf_counter() - td

        with obs.span("engine.run_many", replica=self.replica_id or "",
                      n_requests=len(reqs),
                      n_chunks=len(chunks)):
            for c in chunks:
                pending.append((c, self._dispatch_many([r for _, r in c])))
                if len(pending) >= self._MAX_INFLIGHT_CHUNKS:
                    _drain_one()
            while pending:
                _drain_one()
        # forward_s = dispatch + device + fetch wall time; host decode is
        # booked separately (same split as run()).
        self.stage_times["forward_s"] = time.perf_counter() - t0 - dec_s
        self.stage_times["decode_s"] = dec_s
        return out

    # At most this many chunks in flight (inputs + un-fetched bundles in
    # HBM) during a chunked run_many: 2 gives full upload/compute overlap;
    # more only grows the memory footprint.
    _MAX_INFLIGHT_CHUNKS = 2

    def chunk_plan(self, image_counts: Sequence[int], *,
                   chunk_rows: Optional[int] = None) -> List[List[int]]:
        """run_many's packing, exposed: request indices per chunk.

        Chunks pack at the largest throughput bucket when configured — the
        10-row retrieval cap on the image buckets doesn't bound a packed
        chunk; a 32-row chunk keeps the MXU fed instead of paying a
        dispatch round trip per 10 rows. ``chunk_rows`` overrides for
        callers tuning backlog shape (and the bench's 10-vs-32
        comparison); it must fit a compiled bucket.

        Mixed image counts SHARE chunks (round 5; the per-count grouping
        before it paid one partial chunk per count — a ragged
        NLVR2+retrieval+VQA backlog dispatched 3 forwards where one
        suffices). Two invariants make that safe:

        - a request's rows stay consecutive (each chunk lists whole
          requests; _dispatch_many packs spans in plan order);
        - EVEN-image-count requests precede odd ones inside a chunk, so
          every even-count request starts at an even row offset — the
          binary head pairs batch rows 2k/2k+1, and NLVR2's pair must BE
          one of those pairs (decode reads pair row offset//2). Sums of
          even numbers are even, so ordering evens first guarantees it
          without knowing task ids.

        This is the ONE copy of the packing arithmetic: run_many executes
        it and the bench's padded-row FLOP accounting consumes it
        (:meth:`padded_rows`), so a change here cannot silently skew the
        reported TFLOP/s (ADVICE r4 #4).
        """
        max_bucket = (chunk_rows if chunk_rows is not None
                      else self.cfg.engine.max_batch_rows())
        self.cfg.engine.row_bucket_for(max_bucket)  # raises on <1 or misfit
        for n in image_counts:
            if n > max_bucket:
                raise ValueError(
                    f"request with {n} images exceeds the "
                    f"{max_bucket}-row chunk; raise throughput_buckets or "
                    f"chunk_rows")
        order = ([i for i, n in enumerate(image_counts) if n % 2 == 0]
                 + [i for i, n in enumerate(image_counts) if n % 2])
        chunks: List[List[int]] = []
        cur: List[int] = []
        cur_rows = 0
        for i in order:
            n = image_counts[i]
            if cur_rows + n > max_bucket:
                chunks.append(cur)
                cur, cur_rows = [], 0
            cur.append(i)
            cur_rows += n
        if cur:
            chunks.append(cur)
        return chunks

    def padded_rows(self, image_counts: Sequence[int], *,
                    chunk_rows: Optional[int] = None) -> int:
        """Total device rows a run_many over these requests dispatches,
        INCLUDING bucket padding — the denominator-side work term for
        throughput/TFLOP accounting."""
        counts = list(image_counts)
        return sum(
            self.cfg.engine.row_bucket_for(sum(counts[i] for i in chunk))
            for chunk in self.chunk_plan(counts, chunk_rows=chunk_rows))

    def _dispatch_many(self, reqs: Sequence[PreparedRequest]):
        """Pack one ≤max-bucket chunk and dispatch its forward; returns the
        un-fetched device decode bundle. A request's rows (one per image,
        text replicated — the multi-image contract of :meth:`prepare`) stay
        consecutive, in request order."""
        spans = [(r, i) for r in reqs for i in range(r.n_images)]
        n = len(spans)
        bucket = self.cfg.engine.row_bucket_for(n)
        pad = bucket - n

        def pack(rows, pad_row):
            rows = list(rows) + [pad_row] * pad
            return np.stack(rows, axis=0)

        text = dict(
            input_ids=pack([r.text.input_ids[i] for r, i in spans],
                           reqs[-1].text.input_ids[-1]),
            segment_ids=pack([r.text.segment_ids[i] for r, i in spans],
                             reqs[-1].text.segment_ids[-1]),
            input_mask=pack([r.text.input_mask[i] for r, i in spans],
                            reqs[-1].text.input_mask[-1]),
            task_ids=pack([r.task_ids[i] for r, i in spans],
                          reqs[-1].task_ids[-1]),
        )
        if self.mesh is not None:
            batch = dict(
                text,
                features=pack([r.features[i] for r, i in spans],
                              reqs[-1].features[-1]),
                spatials=pack([r.spatials[i] for r, i in spans],
                              reqs[-1].spatials[-1]),
                image_mask=pack([r.image_mask[i] for r, i in spans],
                                reqs[-1].image_mask[-1]),
            )
            batch = shd.place_batch(batch, self.mesh)
            _, bundle = self._call_forward(bucket, False, batch)
        else:
            # Slab rows: store-backed rows ride the device cache here too —
            # under queue backlog (the batched path) repeat images resolve
            # to cached slab slots and cost no upload, same as solo
            # serving. Pad slots reference the permanent pad slot 0
            # (discarded at decode). Packed text + the slot-index vector
            # move in one deliberate device_put inside _run_rows — the
            # compiled signature stays O(1) in chunk rows.
            rows = [(dict(features=r.features[i], spatials=r.spatials[i],
                          image_mask=r.image_mask[i]),
                     r.cache_keys[i] if r.cache_keys is not None else None)
                    for r, i in spans]
            _, bundle = self._run_rows(bucket, False, text, rows)
        return bundle

    def predict(
        self,
        task_id: int,
        question: str,
        image_paths: Sequence[str],
        *,
        collect_attention: bool = False,
    ) -> dec.TaskResult:
        """Full request path: feature lookup → prepare → forward → decode.

        The library-level equivalent of one queue callback's model section
        (worker.py:556-576) — requires a ``FeatureStore``.
        """
        if self.feature_store is None:
            raise RuntimeError("predict() needs a FeatureStore; use "
                               "prepare()+run() with in-memory regions instead")
        t0 = time.perf_counter()
        # One store read yields regions + device-cache identities together.
        req = self.prepare_from_store(task_id, question, image_paths)
        self.stage_times["prepare_s"] = time.perf_counter() - t0
        _, result = self.run(req, collect_attention=collect_attention)
        return result
