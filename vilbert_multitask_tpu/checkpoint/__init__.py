"""Checkpoint subsystem: torch→native conversion + Orbax store.

Replaces the reference's in-process ``from_pretrained`` torch load
(reference worker.py:83,530-532) with an offline converter and a shard-aware
native store (SURVEY.md §5 "Checkpoint / resume").
"""

from vilbert_multitask_tpu.checkpoint.convert import (
    build_name_map,
    convert_torch_state_dict,
    load_torch_checkpoint,
    to_torch_state_dict,
)
from vilbert_multitask_tpu.checkpoint.store import (
    AsyncRestore,
    convert_and_save,
    restore_params,
    restore_params_async,
    save_params,
)

__all__ = [
    "AsyncRestore",
    "build_name_map",
    "convert_and_save",
    "convert_torch_state_dict",
    "load_torch_checkpoint",
    "restore_params",
    "restore_params_async",
    "save_params",
    "to_torch_state_dict",
]
