"""One-command real-asset onboarding: convert → boot → smoke → parity.

The reference deployment needs exactly three external assets (none of which
ship in either repo): the published 12-in-1 checkpoint
``pytorch_model_9.bin`` (reference worker.py:470), the real
bert-base-uncased WordPiece vocab (worker.py:537-539), and the VQA/GQA
answer-vocabulary pickles (worker.py:299-315). This CLI is the rehearsed
path a deployer follows when those files are in hand — no source reading
required:

    python -m vilbert_multitask_tpu.checkpoint.onboard \
        --torch-bin save/multitask_model/pytorch_model_9.bin \
        --vocab bert-base-uncased-vocab.txt \
        --labels answer_vocabs/ \
        --out onboarded/ \
        --eval vqa=data/vqa_val.jsonl --features feats/ \
        --expect expected_scores.json

Steps, each reported on stderr and in the final JSON report:

1. **convert**  the torch state dict onto the Flax tree (declarative name
   map, fused-QKV repack — checkpoint/convert.py) and save it as an Orbax
   checkpoint under ``<out>/params`` for every later boot.
2. **boot**     an ``InferenceEngine`` on the converted params with the
   given vocab/labels (the boot-time vocab-coherence guard runs here: a
   vocab larger than the embedding table fails loudly).
3. **smoke**    one forward per single-image task family on synthetic
   regions: answers must decode out of the *provided* label maps.
4. **parity**   (optional) run the score-parity eval harness on the given
   JSONL/feature data; compare against ``--expect`` scores within
   ``--tol``. Exit 1 on any miss — the report says exactly which.

The whole flow is rehearsed end-to-end in tests/test_onboard.py with an
oracle-generated ``.bin`` + the synthetic vocab/labels standing in for the
real assets.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Dict, List


def _log(msg: str) -> None:
    print(f"# {msg}", file=sys.stderr, flush=True)


def _parse_evals(items: List[str]) -> Dict[str, str]:
    out: Dict[str, str] = {}
    for it in items:
        if "=" not in it:
            raise SystemExit(f"--eval wants TASK=DATA.jsonl, got {it!r}")
        task, path = it.split("=", 1)
        out[task] = path
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="convert real assets, boot, and rehearse score parity")
    p.add_argument("--torch-bin", required=True,
                   help="published checkpoint, e.g. pytorch_model_9.bin")
    p.add_argument("--vocab", required=True,
                   help="WordPiece vocab file (bert-base-uncased-vocab.txt)")
    p.add_argument("--labels", required=True,
                   help="answer-vocabulary dir (JSON/pickle label maps)")
    p.add_argument("--out", required=True,
                   help="output dir: converted Orbax params + report.json")
    p.add_argument("--detector-bin", default=None,
                   help="optional Faster R-CNN torch checkpoint (the "
                        "reference's X-152 detectron weights, worker.py:82-85)"
                        " — converted for --live-extract serving")
    p.add_argument("--eval", action="append", default=[],
                   metavar="TASK=DATA.jsonl",
                   help="run the eval harness on this task/data (repeatable)")
    p.add_argument("--features", default=None,
                   help="precomputed feature dir for --eval")
    p.add_argument("--expect", default=None,
                   help="JSON {task: score} to check parity against")
    p.add_argument("--tol", type=float, default=0.01,
                   help="max |score - expected| accepted (scores are 0-1 "
                        "fractions; 0.01 = one point)")
    p.add_argument("--batch", type=int, default=8)
    from vilbert_multitask_tpu.config import (
        FrameworkConfig,
        add_backend_args,
        apply_backend_args,
    )

    add_backend_args(p)
    args = p.parse_args(argv)

    # Validate the request shape before any expensive work.
    evals = _parse_evals(args.eval)
    if args.expect and not evals:
        raise SystemExit("--expect without --eval would verify nothing; "
                         "add --eval TASK=DATA.jsonl per expected task")
    if evals and not args.features:
        raise SystemExit("--eval needs --features")

    import dataclasses

    from vilbert_multitask_tpu.checkpoint import save_params
    from vilbert_multitask_tpu.checkpoint.convert import load_torch_checkpoint
    from vilbert_multitask_tpu.engine.runtime import InferenceEngine

    cfg = apply_backend_args(FrameworkConfig(), args)
    cfg = dataclasses.replace(cfg, engine=dataclasses.replace(
        cfg.engine, vocab_path=args.vocab, labels_root=args.labels))

    report: Dict = {"torch_bin": args.torch_bin, "steps": {}}

    # 1. convert ------------------------------------------------------------
    t0 = time.perf_counter()
    params = load_torch_checkpoint(args.torch_bin, cfg.model)
    params_dir = os.path.abspath(os.path.join(args.out, "params"))
    save_params(params_dir, params, force=True)  # re-running must work
    report["steps"]["convert"] = {
        "ok": True, "params_dir": params_dir,
        "wall_s": round(time.perf_counter() - t0, 1)}
    _log(f"convert ok → {params_dir}")

    # 1b. detector (optional) ----------------------------------------------
    if args.detector_bin:
        from vilbert_multitask_tpu.config import DetectorConfig
        from vilbert_multitask_tpu.detect.convert import load_torch_detector
        from vilbert_multitask_tpu.detect.extractor import LiveFeatureExtractor

        t0 = time.perf_counter()
        dcfg = DetectorConfig().tiny() if args.tiny else DetectorConfig()
        # Same derivation serving uses (serve/app.py): the detector's fc6
        # width IS the trunk's region-feature width — a mismatch here would
        # pass onboarding and crash at the first live-extraction request.
        dcfg = dataclasses.replace(
            dcfg, representation_size=cfg.model.v_feature_size)
        det_params = load_torch_detector(args.detector_bin, dcfg)
        det_dir = os.path.abspath(os.path.join(args.out, "detector_params"))
        save_params(det_dir, det_params, force=True)
        # Smoke the live path the converted weights will serve
        # (serve.app --live-extract): one synthetic image through the full
        # extractor, boxes out.
        import numpy as np

        ex = LiveFeatureExtractor(dcfg, params=det_params)
        img = (np.random.default_rng(0).random((300, 400, 3)) * 255
               ).astype(np.uint8)
        regions = ex.extract_array(img)
        # extract_array clamps to >=1 box, so n_boxes alone can't flag a
        # degenerate conversion — non-finite features and a feature-width
        # mismatch with the trunk are the real smoke signals.
        if not np.all(np.isfinite(regions.features)):
            raise SystemExit("detector smoke produced non-finite features "
                             "— converted weights are broken")
        if regions.features.shape[1] != cfg.model.v_feature_size:
            raise SystemExit(
                f"detector feature width {regions.features.shape[1]} != "
                f"trunk v_feature_size {cfg.model.v_feature_size}")
        report["steps"]["detector"] = {
            "ok": True, "params_dir": det_dir,
            "n_boxes": int(regions.features.shape[0]),
            "wall_s": round(time.perf_counter() - t0, 1)}
        _log(f"detector convert+smoke ok → {det_dir} "
             f"({regions.features.shape[0]} boxes)")

    # 2. boot ---------------------------------------------------------------
    t0 = time.perf_counter()
    engine = InferenceEngine(cfg, params=params)
    n_vocab = len(engine.tokenizer.vocab)
    report["steps"]["boot"] = {
        "ok": True, "vocab_tokens": n_vocab,
        "embedding_rows": cfg.model.vocab_size,
        "wall_s": round(time.perf_counter() - t0, 1)}
    _log(f"boot ok: vocab {n_vocab} tokens / table "
         f"{cfg.model.vocab_size} rows")

    # 3. smoke --------------------------------------------------------------
    from vilbert_multitask_tpu.features.pipeline import synthetic_regions

    regions = [synthetic_regions(cfg.model.v_feature_size, n_boxes=36)]
    smoke = {}
    for task_id, q in ((1, "what is the man holding"),
                       (15, "is the bowl right of the mug"),
                       (13, "two dogs play in the snow"),
                       (11, "the woman in the red coat")):
        t0 = time.perf_counter()
        _, result = engine.run(engine.prepare(task_id, q, regions))
        top = (result.answers[0]["answer"] if result.answers
               else f"{len(result.boxes or [])} boxes")
        smoke[task_id] = {"top": top,
                          "ms": round((time.perf_counter() - t0) * 1e3, 1)}
        _log(f"smoke task {task_id}: {top!r} "
             f"({smoke[task_id]['ms']} ms)")
    report["steps"]["smoke"] = {"ok": True, "tasks": smoke}

    # 4. parity -------------------------------------------------------------
    failures: List[str] = []
    if evals:
        from vilbert_multitask_tpu.evals.harness import Evaluator, load_jsonl
        from vilbert_multitask_tpu.features.store import FeatureStore

        engine.feature_store = FeatureStore(args.features)
        expected = {}
        if args.expect:
            with open(args.expect) as f:
                expected = json.load(f)
        ev = Evaluator(engine, batch=args.batch)
        scores: Dict[str, Dict] = {}
        for task, data in evals.items():
            res = ev.run(task, load_jsonl(data))
            scores[task] = res
            # Expected format mirrors the harness output (the committed
            # golden fixture tests/fixtures/golden/scores.json): compare
            # every numeric field the expectation pins (accuracy, R@1, …).
            exp = expected.get(task)
            if exp is None:
                _log(f"eval {task}: {res}")
                continue
            if not isinstance(exp, dict):
                exp = {"accuracy": exp}  # plain-number shorthand
            for key, want in exp.items():
                if not isinstance(want, (int, float)) or key == "task_id":
                    continue
                got = res.get(key)
                delta = (abs(float(got) - float(want))
                         if got is not None else float("inf"))
                ok = delta <= args.tol
                _log(f"eval {task}.{key}: {got} vs expected {want} "
                     f"(|Δ|={delta:.4f} tol={args.tol}) "
                     + ("PASS" if ok else "FAIL"))
                if not ok:
                    failures.append(
                        f"{task}.{key}: {got} != {want} ±{args.tol}")
        # "Exit 0 = every expected score reproduced": an expectation with
        # no corresponding --eval was never measured — that's a failure,
        # not a silent pass.
        for task in sorted(set(expected) - set(evals)):
            failures.append(
                f"{task}: expected but never evaluated "
                f"(add --eval {task}=DATA.jsonl)")
        report["steps"]["parity"] = {
            "ok": not failures, "scores": scores,
            "expected": expected, "failures": failures}

    report["ok"] = not failures
    os.makedirs(args.out, exist_ok=True)
    with open(os.path.join(args.out, "report.json"), "w") as f:
        json.dump(report, f, indent=2)
    print(json.dumps(report))
    if failures:
        _log(f"PARITY FAILED: {failures}")
        return 1
    _log("onboarding complete")
    return 0


if __name__ == "__main__":
    sys.exit(main())
