"""Native checkpoint store: Orbax save/restore + msgpack fallback.

Reference capability: checkpoint *loading* only (torch.load at reference
worker.py:83,530-532 — no saving, no resume; SURVEY.md §5). The TPU build
adds the full lifecycle: params (and optionally train state) saved via Orbax
so restores are memory-mapped per-chip and shard-aware — a host param tree
restores directly onto a ``Mesh`` placement without a host-RAM spike.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np


def save_params(path: str, params: Any) -> None:
    """Save a param pytree with Orbax (directory checkpoint)."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(path, jax.tree_util.tree_map(np.asarray, params))


def restore_params(path: str, *, mesh=None, like: Optional[Any] = None) -> Any:
    """Restore a param pytree onto the accelerator.

    With ``mesh``, leaves land already sharded per the partition rules (no
    replicated staging copy); without one, the tree is device_put to the
    default device — restores are always device-resident, matching the
    reference's load-once-to-accelerator contract (worker.py:530-536). A
    host copy is never the steady state.
    """
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    with ocp.PyTreeCheckpointer() as ckptr:
        params = ckptr.restore(path)
    if mesh is not None:
        from vilbert_multitask_tpu.parallel import sharding as shd

        params = jax.device_put(params, shd.param_shardings(params, mesh))
    else:
        params = jax.device_put(params)
    return params


def convert_and_save(torch_path: str, out_path: str, cfg=None) -> Any:
    """One-shot offline conversion: pytorch_model_*.bin → Orbax directory.

    The deployment-time replacement for the reference's in-process
    ``from_pretrained`` (worker.py:530-532).
    """
    from vilbert_multitask_tpu.checkpoint.convert import load_torch_checkpoint
    from vilbert_multitask_tpu.config import ViLBertConfig

    cfg = cfg or ViLBertConfig()
    params = load_torch_checkpoint(torch_path, cfg)
    save_params(out_path, params)
    return params
