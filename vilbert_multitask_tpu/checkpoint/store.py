"""Native checkpoint store: Orbax save/restore + msgpack fallback.

Reference capability: checkpoint *loading* only (torch.load at reference
worker.py:83,530-532 — no saving, no resume; SURVEY.md §5). The TPU build
adds the full lifecycle: params (and optionally train state) saved via Orbax
so restores are memory-mapped per-chip and shard-aware — a host param tree
restores directly onto a ``Mesh`` placement without a host-RAM spike.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import jax
import numpy as np


def save_params(path: str, params: Any, *, force: bool = False) -> None:
    """Save a param pytree with Orbax (directory checkpoint).

    ``force`` overwrites an existing checkpoint dir — re-runnable flows
    (the onboarding CLI) replace their own output instead of erroring."""
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(path, jax.tree_util.tree_map(np.asarray, params),
                   force=force)


def restore_params(path: str, *, mesh=None, like: Optional[Any] = None,
                   dtype=None) -> Any:
    """Restore a param pytree onto the accelerator.

    With ``mesh``, leaves land already sharded per the partition rules (no
    replicated staging copy); without one, the tree is device_put to the
    default device — restores are always device-resident, matching the
    reference's load-once-to-accelerator contract (worker.py:530-536). A
    host copy is never the steady state.

    ``dtype`` is the serving param-storage cast (EngineConfig.param_dtype,
    e.g. ``"bfloat16"``): floating leaves cast HOST-side before the upload,
    so a bf16 restore ships half the checkpoint bytes. ``dtype="int8"``
    quantizes host-side instead (quant.py per-channel pairs), shipping ~¼
    of the f32 bytes; a checkpoint saved from an int8 engine restores
    unchanged because the cast is idempotent over quantized pairs.
    Checkpoints on disk stay f32 masters — training restores
    (:func:`restore_train_state`) never take this path and never downcast.
    """
    import orbax.checkpoint as ocp

    from vilbert_multitask_tpu.parallel import sharding as shd

    path = os.path.abspath(path)
    with ocp.PyTreeCheckpointer() as ckptr:
        params = ckptr.restore(path)
    params = shd.cast_floating(params, dtype)
    if mesh is not None:
        params = jax.device_put(params, shd.param_shardings(params, mesh))
    else:
        params = jax.device_put(params)
    return params


class AsyncRestore:
    """Handle on a background :func:`restore_params` — ``join()`` returns
    the restored tree (re-raising any restore failure) and reports how
    long the restore ran."""

    def __init__(self, thread, box: dict):
        self._thread = thread
        self._box = box

    def join(self) -> Any:
        self._thread.join()
        if "error" in self._box:
            raise self._box["error"]
        return self._box["params"]

    @property
    def seconds(self) -> float:
        """Wall time of the restore itself (valid after join())."""
        return self._box.get("seconds", 0.0)


def restore_params_async(path: str, *, mesh=None, dtype=None) -> AsyncRestore:
    """:func:`restore_params` on a background thread.

    Boot overlap (engine/aotcache.py): the checkpoint read + host cast +
    device upload touch disk/network/PCIe while the AOT executable cache
    deserializes compiled programs — disjoint resources, so the two
    longest boot phases run concurrently instead of back to back.
    """
    import threading
    import time

    box: dict = {}

    def _run() -> None:
        t0 = time.perf_counter()
        try:
            box["params"] = restore_params(path, mesh=mesh, dtype=dtype)
        except BaseException as e:  # noqa: BLE001 — joined and re-raised
            box["error"] = e
        box["seconds"] = time.perf_counter() - t0

    thread = threading.Thread(target=_run, daemon=True,
                              name="checkpoint-restore")
    thread.start()
    return AsyncRestore(thread, box)


def save_train_state(path: str, state: Any) -> None:
    """Save a full TrainState (step/params/opt_state/rng) with Orbax.

    The resume half of SURVEY.md §5's checkpoint/resume gap: the reference
    only ever loads inference weights (worker.py:530-532); training state
    never survives a crash there because training lives out-of-repo.
    """
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    tree = {"step": state.step, "params": state.params,
            "opt_state": state.opt_state, "rng": state.rng}
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(path, jax.tree_util.tree_map(np.asarray, tree))


def restore_train_state(path: str, template: Any, *, mesh=None) -> Any:
    """Restore a TrainState saved by :func:`save_train_state`.

    ``template`` (a freshly built TrainState with the same model/optimizer)
    supplies the pytree structure — Orbax stores raw trees, and optax states
    are NamedTuple chains that must be rebuilt around the restored leaves.
    With ``mesh``, params and the optimizer's param-shaped moments land
    directly in their sharded placement.
    """
    import orbax.checkpoint as ocp

    path = os.path.abspath(path)
    tree = {"step": template.step, "params": template.params,
            "opt_state": template.opt_state, "rng": template.rng}
    host = jax.tree_util.tree_map(np.asarray, tree)
    with ocp.PyTreeCheckpointer() as ckptr:
        restored = ckptr.restore(path, item=host)
    state = type(template)(
        step=restored["step"], params=restored["params"],
        opt_state=restored["opt_state"], rng=restored["rng"])
    if mesh is not None:
        from vilbert_multitask_tpu.train.step import shard_train_state

        return shard_train_state(state, mesh)
    return jax.device_put(state)


def convert_and_save(torch_path: str, out_path: str, cfg=None) -> Any:
    """One-shot offline conversion: pytorch_model_*.bin → Orbax directory.

    The deployment-time replacement for the reference's in-process
    ``from_pretrained`` (worker.py:530-532).
    """
    from vilbert_multitask_tpu.checkpoint.convert import load_torch_checkpoint
    from vilbert_multitask_tpu.config import ViLBertConfig

    cfg = cfg or ViLBertConfig()
    params = load_torch_checkpoint(torch_path, cfg)
    save_params(out_path, params)
    return params
