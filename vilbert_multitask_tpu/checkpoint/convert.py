"""Torch-checkpoint → native param-tree converter.

Reference capability: the one-time load of the published 12-in-1 weights —
``VILBertForVLTasks.from_pretrained('save/multitask_model/pytorch_model_9.bin')``
at reference worker.py:470,530-532. Here conversion is explicit and offline:
a declarative name map from the torch state-dict layout of the upstream
``vilbert`` package (the external model package imported at worker.py:44-46)
onto this framework's Flax tree, with the tensor-layout transforms
TPU checkpoints need:

- torch ``nn.Linear`` stores ``weight`` as (out, in) → Flax kernels are
  (in, out): transpose;
- the three per-stream Q/K/V linears fuse into one (in, 3·out) ``qkv``
  kernel (ops/attention.py packs q|k|v along the output axis);
- ``LayerNorm.weight`` → ``scale``;
- embedding tables pass through untransposed;
- the tied MLM decoder keeps only its bias (the table itself is the word
  embedding, models/heads.py).

Both directions are provided; ``to_torch_state_dict`` is the exact inverse,
which the tests use to prove the bookkeeping is lossless without the real
checkpoint asset (it is not vendored in the reference repo either,
SURVEY.md §0).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from vilbert_multitask_tpu.config import ViLBertConfig

# ---------------------------------------------------------------------------
# Name map. Each entry: flax path (tuple) → (torch keys, pack, unpack) where
# pack(torch arrays…) → flax array and unpack(flax array) → torch arrays.
# ---------------------------------------------------------------------------

Arr = np.ndarray


def _t(w: Arr) -> Arr:  # torch Linear weight → flax kernel
    return np.ascontiguousarray(w.T)


def _linear(flax_prefix: Tuple[str, ...], torch_prefix: str):
    return [
        (flax_prefix + ("kernel",), ([f"{torch_prefix}.weight"],
                                     lambda w: _t(w), lambda k: [_t(k)])),
        (flax_prefix + ("bias",), ([f"{torch_prefix}.bias"],
                                   lambda b: b, lambda b: [b])),
    ]


def _layernorm(flax_prefix: Tuple[str, ...], torch_prefix: str):
    return [
        (flax_prefix + ("scale",), ([f"{torch_prefix}.weight"],
                                    lambda w: w, lambda s: [s])),
        (flax_prefix + ("bias",), ([f"{torch_prefix}.bias"],
                                   lambda b: b, lambda b: [b])),
    ]


def _embed(flax_prefix: Tuple[str, ...], torch_key: str):
    return [(flax_prefix + ("embedding",),
             ([torch_key], lambda w: w, lambda e: [e]))]


def _fused_qkv(flax_prefix: Tuple[str, ...], torch_prefix: str):
    """query/key/value linears → one (in, 3·out) kernel + (3·out,) bias."""
    qkv = [f"{torch_prefix}.{n}" for n in ("query", "key", "value")]
    return [
        (flax_prefix + ("kernel",),
         ([f"{k}.weight" for k in qkv],
          lambda q, k, v: np.concatenate([_t(q), _t(k), _t(v)], axis=1),
          lambda ker: [_t(a) for a in np.split(ker, 3, axis=1)])),
        (flax_prefix + ("bias",),
         ([f"{k}.bias" for k in qkv],
          lambda q, k, v: np.concatenate([q, k, v]),
          lambda b: list(np.split(b, 3)))),
    ]


def build_name_map(cfg: ViLBertConfig):
    """flax-path → (torch keys, pack, unpack), for the full serving model."""
    m: List = []
    E = ("bert", "embeddings")
    m += _embed(E + ("word_embeddings",), "bert.embeddings.word_embeddings.weight")
    m += _embed(E + ("position_embeddings",),
                "bert.embeddings.position_embeddings.weight")
    m += _embed(E + ("token_type_embeddings",),
                "bert.embeddings.token_type_embeddings.weight")
    if cfg.task_specific_tokens:
        m += _embed(E + ("task_embeddings",),
                    "bert.embeddings.task_embeddings.weight")
    m += _layernorm(E + ("norm",), "bert.embeddings.LayerNorm")

    V = ("bert", "v_embeddings")
    m += _linear(V + ("image_embeddings",), "bert.v_embeddings.image_embeddings")
    m += _linear(V + ("image_location_embeddings",),
                 "bert.v_embeddings.image_location_embeddings")
    m += _layernorm(V + ("norm",), "bert.v_embeddings.LayerNorm")

    # Single-stream layers. Torch: bert.encoder.layer.{i} (text),
    # bert.encoder.v_layer.{i} (visual).
    def stream(n_layers: int, flax_fmt: str, torch_fmt: str):
        out = []
        for i in range(n_layers):
            F = ("bert", "encoder", flax_fmt.format(i))
            T = torch_fmt.format(i)
            out += _fused_qkv(F + ("attention", "qkv"), f"{T}.attention.self")
            out += _linear(F + ("attention_output", "dense"),
                           f"{T}.attention.output.dense")
            out += _layernorm(F + ("attention_output", "norm"),
                              f"{T}.attention.output.LayerNorm")
            out += _linear(F + ("ffn", "intermediate"), f"{T}.intermediate.dense")
            out += _linear(F + ("ffn", "output"), f"{T}.output.dense")
            out += _layernorm(F + ("ffn", "norm"), f"{T}.output.LayerNorm")
        return out

    m += stream(cfg.num_hidden_layers, "t_layer_{}", "bert.encoder.layer.{}")
    m += stream(cfg.v_num_hidden_layers, "v_layer_{}", "bert.encoder.v_layer.{}")

    # Co-attention bridges. Torch biattention convention (upstream vilbert):
    # *1 projections act on the VISUAL stream, *2 on TEXT. Text queries attend
    # image keys/values → (query2, key1, value1); image queries attend text →
    # (query1, key2, value2). biOutput.dense1/LayerNorm1 close the visual
    # residual, dense2/LayerNorm2 the text residual.
    for i in range(cfg.num_connection_layers):
        F = ("bert", "encoder", f"c_layer_{i}")
        T = f"bert.encoder.c_layer.{i}"
        for ours, theirs in (("query", "query2"), ("key", "key1"),
                             ("value", "value1")):
            m += _linear(F + ("text_attends_image", ours),
                         f"{T}.biattention.{theirs}")
        for ours, theirs in (("query", "query1"), ("key", "key2"),
                             ("value", "value2")):
            m += _linear(F + ("image_attends_text", ours),
                         f"{T}.biattention.{theirs}")
        m += _linear(F + ("v_output", "dense"), f"{T}.biOutput.dense1")
        m += _layernorm(F + ("v_output", "norm"), f"{T}.biOutput.LayerNorm1")
        m += _linear(F + ("t_output", "dense"), f"{T}.biOutput.dense2")
        m += _layernorm(F + ("t_output", "norm"), f"{T}.biOutput.LayerNorm2")
        m += _linear(F + ("v_ffn", "intermediate"), f"{T}.v_intermediate.dense")
        m += _linear(F + ("v_ffn", "output"), f"{T}.v_output.dense")
        m += _layernorm(F + ("v_ffn", "norm"), f"{T}.v_output.LayerNorm")
        m += _linear(F + ("t_ffn", "intermediate"), f"{T}.t_intermediate.dense")
        m += _linear(F + ("t_ffn", "output"), f"{T}.t_output.dense")
        m += _layernorm(F + ("t_ffn", "norm"), f"{T}.t_output.LayerNorm")

    m += _linear(("bert", "t_pooler", "dense"), "bert.t_pooler.dense")
    m += _linear(("bert", "v_pooler", "dense"), "bert.v_pooler.dense")

    # Masked-modeling heads (cls.*). Text decoder table is tied to the word
    # embedding — only its bias converts.
    m += _linear(("cls_text", "transform_dense"),
                 "cls.predictions.transform.dense")
    m += _layernorm(("cls_text", "transform_norm"),
                    "cls.predictions.transform.LayerNorm")
    m.append((("cls_text", "decoder_bias"),
              (["cls.predictions.bias"], lambda b: b, lambda b: [b])))
    m += _linear(("cls_image", "transform_dense"),
                 "cls.imagePredictions.transform.dense")
    m += _layernorm(("cls_image", "transform_norm"),
                    "cls.imagePredictions.transform.LayerNorm")
    m += _linear(("cls_image", "decoder"), "cls.imagePredictions.decoder")

    # Task heads. SimpleClassifier in torch is Sequential(Linear, GELU,
    # LayerNorm, Linear) → keys logit_fc.{0,2,3}.
    for head in ("vil_prediction", "vil_prediction_gqa",
                 "vil_binary_prediction"):
        m += _linear((head, "dense1"), f"{head}.logit_fc.0")
        m += _layernorm((head, "norm"), f"{head}.logit_fc.2")
        m += _linear((head, "dense2"), f"{head}.logit_fc.3")
    for head in ("vil_logit", "vil_tri_prediction", "vision_logit",
                 "linguisic_logit"):
        m += _linear((head,), head)
    return m


# ---------------------------------------------------------------------- api


def _set_path(tree: Dict, path: Tuple[str, ...], value: Arr) -> None:
    node = tree
    for k in path[:-1]:
        node = node.setdefault(k, {})
    node[path[-1]] = value


def _get_path(tree: Dict, path: Tuple[str, ...]):
    node = tree
    for k in path:
        node = node[k]
    return node


def convert_torch_state_dict(
    state_dict: Dict[str, Arr],
    cfg: ViLBertConfig,
    *,
    strict: bool = True,
    report: Optional[Dict[str, List[str]]] = None,
    dtype=np.float32,
) -> Dict:
    """Torch state dict (numpy-valued) → nested Flax param dict.

    ``strict`` raises when mapped torch keys are missing. Pass a dict as
    ``report`` to receive ``{"missing": [...], "unmapped": [...]}`` — torch
    keys the map does not cover (optimizer stats, pretraining-only heads)
    are reported there instead of silently dropped. ``dtype`` is the param
    storage dtype (float32 for serving; the conversion-oracle tests use
    float64 so parity tolerances sit far below perturbation signals).
    ``dtype="int8"`` converts at full f32 precision first and then runs the
    per-channel symmetric quantizer (quant.py) over the finished tree — a
    direct ``np.asarray(x, "int8")`` cast would truncate real weights to
    garbage, so the integer path never reaches the per-leaf cast below.
    """
    quantize = dtype is not None and np.dtype(dtype).kind in "iu"
    if quantize:
        if np.dtype(dtype) != np.int8:
            raise ValueError(
                f"integer storage dtype {np.dtype(dtype)} unsupported; only "
                "int8 per-channel quantization is implemented")
        dtype = np.float32
    params: Dict = {}
    used: set = set()
    missing: List[str] = []
    for flax_path, (torch_keys, pack, _un) in build_name_map(cfg):
        try:
            args = [np.asarray(state_dict[k]) for k in torch_keys]
        except KeyError:
            missing.extend(k for k in torch_keys if k not in state_dict)
            continue
        used.update(torch_keys)
        _set_path(params, flax_path, np.asarray(pack(*args), dtype))
    if quantize:
        from vilbert_multitask_tpu import quant

        params = quant.quantize_tree(params)
    if strict and missing:
        raise KeyError(f"torch checkpoint missing {len(missing)} keys, "
                       f"e.g. {missing[:5]}")
    if report is not None:
        report["missing"] = missing
        report["unmapped"] = sorted(k for k in state_dict if k not in used)
    return params


def to_torch_state_dict(params: Dict, cfg: ViLBertConfig) -> Dict[str, Arr]:
    """Exact inverse of :func:`convert_torch_state_dict` (plus the tied
    decoder weight torch materializes)."""
    out: Dict[str, Arr] = {}
    for flax_path, (torch_keys, _pack, unpack) in build_name_map(cfg):
        arrs = unpack(np.asarray(_get_path(params, flax_path)))
        for k, a in zip(torch_keys, arrs):
            out[k] = np.asarray(a)
    # torch ties cls.predictions.decoder.weight to the embedding table.
    out["cls.predictions.decoder.weight"] = np.asarray(
        params["bert"]["embeddings"]["word_embeddings"]["embedding"])
    return out


def load_torch_checkpoint(path: str, cfg: ViLBertConfig, *,
                          strict: bool = True, dtype=np.float32) -> Dict:
    """Read a ``pytorch_model_*.bin`` (torch pickle) and convert.

    CPU-mapped, mirroring the reference's load (worker.py:83,530-532).
    ``dtype`` feeds :func:`convert_torch_state_dict`'s leaf cast — keep the
    f32 default for conversion-to-master-checkpoint flows; a serving-only
    conversion may pass the engine's param_dtype (including ``"int8"``,
    which quantizes the finished f32 tree) to skip the second cast.
    """
    import torch

    raw = torch.load(path, map_location="cpu", weights_only=True)
    if isinstance(raw, dict) and "state_dict" in raw:
        raw = raw["state_dict"]
    sd = {k.replace("module.", "", 1) if k.startswith("module.") else k:
          v.numpy() if hasattr(v, "numpy") else np.asarray(v)
          for k, v in raw.items()}
    return convert_torch_state_dict(sd, cfg, strict=strict, dtype=dtype)
