"""Deterministic, seeded fault injection for the serving stack.

The chaos half of ``resilience/``: named fault sites live UNCONDITIONALLY
on production paths — ``fault_point("queue.claim")`` at the top of the
claim, ``fault_point("remote.post")`` before every transport request — and
a :class:`FaultPlan` installed for a test or a ``serve_soak.py --chaos``
run decides, per call, whether to inject an exception, added latency, or
payload corruption. Because the decision stream is a per-site PRNG seeded
from ``(plan seed, site name)``, the k-th call at a site sees the same
verdict on every run with the same seed: failures found under chaos are
reproducible by seed, which is the whole point.

Disabled mode (no plan installed — production, and every test that didn't
opt in) is a single module-global read + ``is None`` compare, the same
shape as obs's disabled span; tier-1 guards it < 5 µs per call so sites
can stay on hot paths.

Fault-site inventory (see ARCHITECTURE.md for the table):
``queue.publish``, ``queue.claim``, ``worker.intake``, ``remote.post``,
``push.publish``, ``engine.dispatch``.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, Optional, Sequence

from vilbert_multitask_tpu import obs


class FaultInjected(ConnectionError):
    """An error injected by an active :class:`FaultPlan`.

    Subclasses :class:`ConnectionError` so injected failures exercise the
    SAME handling as real transport loss: remote shims treat a failed
    claim as a drained queue, the worker nacks toward dead-letter, the
    push hub drops the frame — no test-only code paths.
    """


@dataclass(frozen=True)
class FaultRule:
    """One injection behavior bound to a site (or site prefix).

    ``site`` matches exactly, or by prefix when it ends with ``"*"``
    (``"queue.*"`` covers publish and claim). ``kind`` is one of
    ``"error"`` (raise :class:`FaultInjected`), ``"delay"`` (sleep
    ``delay_s`` then proceed), or ``"corrupt"`` (return a visibly mangled
    copy of the payload). ``rate`` is the per-call injection probability;
    ``max_injections`` caps total injections from this rule (None =
    unbounded) so a flap can be scripted to heal.
    """

    site: str
    kind: str = "error"      # "error" | "delay" | "corrupt"
    rate: float = 1.0
    delay_s: float = 0.0
    max_injections: Optional[int] = None

    def matches(self, site: str) -> bool:
        if self.site.endswith("*"):
            return site.startswith(self.site[:-1])
        return site == self.site


class FaultPlan:
    """A seeded schedule of injections across named sites.

    Determinism contract: for a fixed ``(seed, rules)`` the verdict for
    the k-th call at each site is a pure function of ``(seed, site, k)``
    — each site gets its own ``random.Random(f"{seed}:{site}")`` stream
    and draws exactly one variate per call, so interleaving across sites
    (thread scheduling) cannot perturb any single site's schedule.
    """

    def __init__(self, seed: int = 0,
                 rules: Sequence[FaultRule] = ()):
        self.seed = int(seed)
        self.rules = tuple(rules)
        self._lock = threading.Lock()
        self._rngs: Dict[str, random.Random] = {}
        self._injected: Dict[str, int] = {}
        self._calls: Dict[str, int] = {}

    def _rule_for(self, site: str) -> Optional[FaultRule]:
        for rule in self.rules:
            if rule.matches(site):
                return rule
        return None

    def decide(self, site: str) -> Optional[FaultRule]:
        """Record one call at ``site``; return the rule to apply or None."""
        rule = self._rule_for(site)
        with self._lock:
            self._calls[site] = self._calls.get(site, 0) + 1
            if rule is None:
                return None
            rng = self._rngs.get(site)
            if rng is None:
                rng = self._rngs[site] = random.Random(
                    f"{self.seed}:{site}")
            # Always draw, THEN gate on the cap: the variate sequence per
            # site stays aligned with the call index regardless of how
            # many injections already fired.
            hit = rng.random() < rule.rate
            if not hit:
                return None
            if (rule.max_injections is not None
                    and self._injected.get(site, 0) >= rule.max_injections):
                return None
            self._injected[site] = self._injected.get(site, 0) + 1
            return rule

    def apply(self, site: str, payload: Any = None) -> Any:
        rule = self.decide(site)
        if rule is None:
            return payload
        if rule.kind == "delay":
            time.sleep(rule.delay_s)
            return payload
        if rule.kind == "corrupt":
            return _corrupt(payload)
        # Error-kind faults are incidents by construction: freeze the
        # evidence (with whatever trace is live on this thread) before
        # the exception starts unwinding through the real handling.
        obs.record_event("fault_injected", site=site, seed=self.seed,
                         trace_id=obs.current_trace_id())
        raise FaultInjected(
            f"injected fault at {site} (seed={self.seed})")

    def injections(self) -> Dict[str, int]:
        """Site → injection count so far (snapshot)."""
        with self._lock:
            return dict(self._injected)

    def calls(self) -> Dict[str, int]:
        """Site → total fault_point calls so far (snapshot)."""
        with self._lock:
            return dict(self._calls)


def _corrupt(payload: Any) -> Any:
    """Visibly mangle a payload copy (never mutate the original)."""
    if isinstance(payload, dict):
        out = dict(payload)
        out["__fault_corrupted__"] = True
        for k, v in out.items():
            if isinstance(v, str):
                out[k] = v[::-1]
        return out
    if isinstance(payload, str):
        return payload[::-1]
    if isinstance(payload, (bytes, bytearray)):
        return bytes(payload)[::-1]
    return payload


# ------------------------------------------------------------- the plane
_PLAN: Optional[FaultPlan] = None


def install_plan(plan: FaultPlan) -> FaultPlan:
    """Activate ``plan`` process-wide (chaos soak / opted-in tests)."""
    global _PLAN
    _PLAN = plan
    return plan


def clear_plan() -> None:
    global _PLAN
    _PLAN = None


def active_plan() -> Optional[FaultPlan]:
    return _PLAN


def fault_point(site: str, payload: Any = None) -> Any:
    """A named injection site on a production path.

    With no plan installed this is one global read and an ``is None``
    compare (< 5 µs, tier-1 guarded) — cheap enough to live on hot paths
    unconditionally. With a plan, the site's rule may raise
    :class:`FaultInjected`, sleep, or return a corrupted ``payload``;
    otherwise ``payload`` passes through unchanged.
    """
    plan = _PLAN
    if plan is None:
        return payload
    return plan.apply(site, payload)
