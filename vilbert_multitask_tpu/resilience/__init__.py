"""resilience/ — failure-domain policy + deterministic fault injection.

Policy half (:mod:`.policy`): deadlines that ride the job body, the shared
retry loop (full jitter + process budget), circuit breakers, and the HTTP
admission controller. Faults half (:mod:`.faults`): seeded `fault_point`
sites on production paths for reproducible chaos. Host-side stdlib + obs
only — no jax (layer contract enforced by vmtlint VMT112).
"""

from vilbert_multitask_tpu.resilience.policy import (
    AdmissionController,
    AdmissionDecision,
    BreakerBoard,
    CircuitBreaker,
    CircuitOpenError,
    Deadline,
    DeadlineExceeded,
    PROCESS_RETRY_BUDGET,
    ReplicaKilled,
    RetryBudget,
    RetryPolicy,
)
from vilbert_multitask_tpu.resilience.faults import (
    FaultInjected,
    FaultPlan,
    FaultRule,
    active_plan,
    clear_plan,
    fault_point,
    install_plan,
)

__all__ = [
    "AdmissionController",
    "AdmissionDecision",
    "BreakerBoard",
    "CircuitBreaker",
    "CircuitOpenError",
    "Deadline",
    "DeadlineExceeded",
    "PROCESS_RETRY_BUDGET",
    "ReplicaKilled",
    "RetryBudget",
    "RetryPolicy",
    "FaultInjected",
    "FaultPlan",
    "FaultRule",
    "active_plan",
    "clear_plan",
    "fault_point",
    "install_plan",
]
