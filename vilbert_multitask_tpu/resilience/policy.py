"""Resilience policy plane: deadlines, retries, breakers, admission.

The north star is heavy multi-user traffic, and the failure behavior that
keeps tail latency bounded under partial failure is policy, not luck. This
module is the one home for those policies so the serving tiers share a
single vocabulary instead of hand-rolling loops per call site:

- :class:`Deadline` — a time budget minted at ``POST /`` that rides the
  DurableQueue job body next to ``trace_id``; the worker and engine check
  remaining budget and terminate expired jobs with a terminal push instead
  of burning a device forward on a client that stopped waiting.
- :class:`RetryPolicy` — bounded attempts, exponential backoff with FULL
  jitter (the un-jittered variant retries a worker fleet in lockstep — the
  thundering herd VMT114 lints for), plus a per-process
  :class:`RetryBudget` so a dead dependency can't turn every caller into a
  retry storm.
- :class:`CircuitBreaker` — closed/open/half-open over a sliding failure
  window; open calls fail fast (no connect timeout burned per call) and
  half-open probes detect recovery.
- :class:`AdmissionController` — shed-before-enqueue at the HTTP layer:
  once queue depth or age says the backlog can't be served within a useful
  latency, a fast ``429 Retry-After`` beats a slow success.

Everything here is host-side stdlib + obs instruments — importable without
jax (the ``resilience -> jax`` layer contract in pyproject enforces it).
Telemetry rides the shared registry: ``vmt_retries_total{site}``,
``vmt_shed_total{reason}``, ``vmt_breaker_state{breaker}``.
"""

from __future__ import annotations

import logging
import random
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Tuple, Type

from vilbert_multitask_tpu import obs

log = logging.getLogger(__name__)


class DeadlineExceeded(Exception):
    """A job's time budget ran out before (or while) serving it."""


class CircuitOpenError(ConnectionError):
    """Raised instead of attempting a call while a breaker is open.

    Subclasses :class:`ConnectionError` on purpose: every transport-error
    handler in the serving tiers (remote shims, worker failure isolation)
    already treats connection failures correctly, and a fast-failed call
    IS a connection failure from the caller's point of view — just one
    that cost microseconds instead of a connect timeout.
    """


class ReplicaKilled(ConnectionError):
    """An engine replica died (or was chaos-killed) with work in hand.

    Subclasses :class:`ConnectionError` for the same reason as
    :class:`CircuitOpenError`: to its callers a dead replica IS a lost
    connection. The replica pool turns this into failover — in-flight
    members go back to the queue via ``release()`` (no attempt charged;
    infra death is not the job's fault) and redeliver to a live replica,
    with ``queue_max_deliveries`` bounding how many replicas one poison
    job may take down before it dead-letters.
    """


# --------------------------------------------------------------- deadlines
class Deadline:
    """A monotonic time budget with a wall-clock wire form.

    In-process, expiry is tracked against ``time.perf_counter`` (the
    repo's duration clock — VMT109). Across processes (HTTP submit on the
    web host, claim on a remote worker) monotonic clocks don't compare, so
    the wire form carries ``(budget_s, issued_unix)`` and the receiving
    process re-anchors the remaining budget to its own monotonic clock
    once at parse time.
    """

    __slots__ = ("budget_s", "issued_unix", "_expires_perf")

    def __init__(self, budget_s: float, *,
                 issued_unix: Optional[float] = None):
        now_wall = time.time()
        self.budget_s = float(budget_s)
        self.issued_unix = (float(issued_unix) if issued_unix is not None
                            else now_wall)
        # Elapsed-so-far against a persisted cross-process wall stamp: a
        # monotonic clock cannot be compared with another process's epoch.
        elapsed = max(0.0, now_wall - self.issued_unix)  # vmtlint: disable=VMT109
        self._expires_perf = time.perf_counter() + self.budget_s - elapsed

    def remaining_s(self) -> float:
        """Budget left (negative once expired) — monotonic from here on."""
        return self._expires_perf - time.perf_counter()

    def expired(self) -> bool:
        return self.remaining_s() <= 0.0

    def expires_at(self) -> float:
        """Absolute expiry on this process's ``perf_counter`` timeline —
        the EDF sort key (comparable across Deadlines in one process,
        meaningless across processes)."""
        return self._expires_perf

    def to_wire(self) -> Dict[str, float]:
        """The job-body form (rides next to ``trace_id``)."""
        return {"budget_s": self.budget_s, "issued_unix": self.issued_unix}

    @classmethod
    def from_wire(cls, wire: Any) -> Optional["Deadline"]:
        """Parse a job body's ``deadline`` value; None on absent/garbage
        (jobs published by pre-deadline clients must keep serving)."""
        if not isinstance(wire, dict):
            return None
        try:
            return cls(float(wire["budget_s"]),
                       issued_unix=float(wire["issued_unix"]))
        except (KeyError, TypeError, ValueError):
            return None


# ----------------------------------------------------------------- retries
class RetryBudget:
    """Per-process token bucket bounding TOTAL retry volume.

    Backoff shapes one caller's retries; the budget bounds the sum over
    all of them — when a dependency dies, N threads each "politely"
    retrying is still an N-fold storm at the moment it recovers. Once the
    bucket is empty, callers fail with their last error instead of
    sleeping for another attempt.
    """

    def __init__(self, rate_per_s: float = 2.0, capacity: float = 20.0):
        self.rate_per_s = float(rate_per_s)
        self.capacity = float(capacity)
        self._lock = threading.Lock()
        self._tokens = self.capacity
        self._last = time.perf_counter()

    def try_spend(self, n: float = 1.0) -> bool:
        with self._lock:
            now = time.perf_counter()
            self._tokens = min(self.capacity,
                               self._tokens + (now - self._last)
                               * self.rate_per_s)
            self._last = now
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False


#: The default per-process budget every RetryPolicy without its own shares.
PROCESS_RETRY_BUDGET = RetryBudget()


@dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff + full jitter + bounded attempts.

    Full jitter (``uniform(0, min(cap, base * 2**attempt))``) is the
    AWS-architecture-blog shape: the un-jittered ladder synchronizes every
    client that observed the same failure into retry waves. ``call`` is
    the one retry loop the serving tiers use (serve/remote.py's hand-rolled
    copy folded into it).
    """

    max_attempts: int = 5
    base_delay_s: float = 0.5
    max_delay_s: float = 30.0
    budget: Optional[RetryBudget] = None  # None → PROCESS_RETRY_BUDGET

    def backoff_s(self, attempt: int,
                  rng: Optional[random.Random] = None) -> float:
        """Full-jitter delay for ``attempt`` (0-based)."""
        cap = min(self.max_delay_s, self.base_delay_s * (2 ** attempt))
        return (rng or random).uniform(0.0, cap)

    def call(self, fn: Callable[[], Any], *, site: str,
             retry_on: Tuple[Type[BaseException], ...] = (Exception,),
             no_retry: Tuple[Type[BaseException], ...] = (),
             breaker: Optional["CircuitBreaker"] = None,
             sleep: Callable[[float], None] = time.sleep,
             rng: Optional[random.Random] = None) -> Any:
        """Run ``fn`` with retries; ``site`` labels ``vmt_retries_total``.

        ``no_retry`` wins over ``retry_on`` (deterministic failures like an
        HTTP 4xx must surface immediately even when they subclass a
        transport error). A ``breaker`` is consulted before every attempt
        (open → :class:`CircuitOpenError`, no attempt made) and fed the
        outcome of each one.
        """
        budget = self.budget if self.budget is not None \
            else PROCESS_RETRY_BUDGET
        last: Optional[BaseException] = None
        for attempt in range(self.max_attempts):
            if breaker is not None:
                breaker.preflight()
            try:
                result = fn()
            except no_retry:
                raise
            except retry_on as e:
                last = e
                if breaker is not None:
                    breaker.record_failure()
                if attempt >= self.max_attempts - 1:
                    break
                if not budget.try_spend():
                    log.warning("%s: retry budget exhausted after %s (%d "
                                "attempts); failing fast", site, e,
                                attempt + 1)
                    break
                obs.RETRY_COUNTER.inc(site=site)
                delay = self.backoff_s(attempt, rng=rng)
                log.warning("%s failed (%s); retry %d/%d in %.2fs",
                            site, e, attempt + 1, self.max_attempts - 1,
                            delay)
                sleep(delay)
                continue
            if breaker is not None:
                breaker.record_success()
            return result
        assert last is not None
        raise last


# ---------------------------------------------------------------- breakers
_STATE_CODES = {"closed": 0, "half_open": 1, "open": 2}


class CircuitBreaker:
    """Closed / open / half-open over a sliding failure window.

    Closed: calls flow; failures are stamped into a window and the breaker
    opens once ``failure_threshold`` land within ``window_s``. Open: every
    ``preflight`` fails fast with :class:`CircuitOpenError` until
    ``reset_timeout_s`` has passed. Half-open: up to ``half_open_probes``
    calls are let through — a success closes the breaker (window cleared),
    a failure re-opens it and restarts the timer.

    Thread-safe (the worker thread, parallel warmup threads, and HTTP
    handler threads all share breakers); every mutable field is written
    under ``_lock``. State transitions publish to the
    ``vmt_breaker_state{breaker}`` gauge.
    """

    def __init__(self, name: str = "default", *,
                 failure_threshold: int = 5, window_s: float = 30.0,
                 reset_timeout_s: float = 10.0, half_open_probes: int = 1,
                 clock: Callable[[], float] = time.perf_counter):
        self.name = name
        self.failure_threshold = int(failure_threshold)
        self.window_s = float(window_s)
        self.reset_timeout_s = float(reset_timeout_s)
        self.half_open_probes = int(half_open_probes)
        self._clock = clock
        self._lock = threading.Lock()
        self._failures: deque = deque()
        self._state = "closed"
        self._opened_at = 0.0
        self._probes = 0
        obs.BREAKER_GAUGE.set(0, breaker=self.name)

    def _set_state_locked(self, state: str) -> None:
        self._state = state
        obs.BREAKER_GAUGE.set(_STATE_CODES[state], breaker=self.name)

    def _tick_locked(self) -> None:
        """open → half_open once the reset timeout elapses."""
        if (self._state == "open"
                and self._clock() - self._opened_at >= self.reset_timeout_s):
            self._set_state_locked("half_open")
            self._probes = 0

    @property
    def state(self) -> str:
        with self._lock:
            self._tick_locked()
            return self._state

    def preflight(self) -> None:
        """Gate one call: returns to proceed, raises CircuitOpenError to
        shed. Half-open admits only the probe quota."""
        with self._lock:
            self._tick_locked()
            if self._state == "closed":
                return
            if (self._state == "half_open"
                    and self._probes < self.half_open_probes):
                self._probes += 1
                return
            raise CircuitOpenError(
                f"circuit '{self.name}' is {self._state}; call shed "
                f"(retry after {self.reset_timeout_s:.1f}s)")

    def record_success(self) -> None:
        with self._lock:
            if self._state != "closed":
                self._failures.clear()
                self._set_state_locked("closed")

    def record_failure(self) -> None:
        opened = ""
        with self._lock:
            now = self._clock()
            if self._state == "half_open":
                # The probe failed: the dependency is still down.
                self._set_state_locked("open")
                self._opened_at = now
                opened = "half_open_probe_failed"
            else:
                self._failures.append(now)
                while (self._failures
                       and now - self._failures[0] > self.window_s):
                    self._failures.popleft()
                if (self._state == "closed"
                        and len(self._failures) >= self.failure_threshold):
                    log.warning("circuit '%s' opened: %d failures in %.1fs",
                                self.name, len(self._failures),
                                self.window_s)
                    self._set_state_locked("open")
                    self._opened_at = now
                    opened = "failure_threshold"
        if opened:
            # Flight-recorder trigger OUTSIDE the lock: the enqueue is
            # cheap, but preflight() on other threads must never wait on
            # it.
            obs.record_event("breaker_open", breaker=self.name,
                             cause=opened)


class BreakerBoard:
    """A family of same-shaped :class:`CircuitBreaker` instances, one per
    member of a replica set.

    The replica pool needs N independent breakers — one replica's dispatch
    failures must trip ONLY that replica out of the rotation — but they
    should share thresholds and publish under one gauge family
    (``vmt_breaker_state{breaker="<prefix>.<member>"}``). ``get()`` is
    idempotent per member name; iteration yields ``(member, breaker)``.
    """

    def __init__(self, prefix: str, *, failure_threshold: int = 3,
                 window_s: float = 30.0, reset_timeout_s: float = 5.0,
                 half_open_probes: int = 1,
                 clock: Callable[[], float] = time.perf_counter):
        self.prefix = prefix
        self._kwargs = dict(failure_threshold=failure_threshold,
                            window_s=window_s,
                            reset_timeout_s=reset_timeout_s,
                            half_open_probes=half_open_probes, clock=clock)
        self._lock = threading.Lock()
        self._members: Dict[str, CircuitBreaker] = {}

    def get(self, member: str) -> CircuitBreaker:
        with self._lock:
            breaker = self._members.get(member)
            if breaker is None:
                breaker = CircuitBreaker(
                    name=f"{self.prefix}.{member}", **self._kwargs)
                self._members[member] = breaker
            return breaker

    def states(self) -> Dict[str, str]:
        with self._lock:
            members = list(self._members.items())
        return {name: b.state for name, b in members}

    def __iter__(self):
        with self._lock:
            return iter(list(self._members.items()))


# --------------------------------------------------------------- admission
@dataclass(frozen=True)
class AdmissionDecision:
    admitted: bool
    reason: str = ""          # "queue_depth" | "queue_age" when shed
    retry_after_s: float = 0.0


class AdmissionController:
    """Shed-before-enqueue: overload is answered at the HTTP door.

    Two signals, both read from the durable queue at submit time: *depth*
    (pending + inflight — how much work is ahead of this request) and
    *age* (how long the oldest pending job has waited — depth can look
    fine while a stalled worker starves the line). Either crossing its
    threshold sheds the request with a ``429`` + ``Retry-After`` instead
    of enqueueing work the client will have abandoned by completion time.
    A threshold of 0/None disables that signal.
    """

    def __init__(self, *, max_queue_depth: int = 512,
                 max_queue_age_s: float = 120.0,
                 retry_after_s: float = 2.0):
        self.max_queue_depth = int(max_queue_depth or 0)
        self.max_queue_age_s = float(max_queue_age_s or 0.0)
        self.retry_after_s = float(retry_after_s)

    def admit(self, *, depth: int,
              oldest_age_s: Optional[float] = None) -> AdmissionDecision:
        if self.max_queue_depth and depth >= self.max_queue_depth:
            obs.SHED_COUNTER.inc(reason="queue_depth")
            return AdmissionDecision(False, "queue_depth",
                                     self.retry_after_s)
        if (self.max_queue_age_s and oldest_age_s is not None
                and oldest_age_s >= self.max_queue_age_s):
            obs.SHED_COUNTER.inc(reason="queue_age")
            return AdmissionDecision(False, "queue_age", self.retry_after_s)
        return AdmissionDecision(True)
