"""vmtlint: JAX-aware static analysis for this repo's real failure modes.

The worst perf bug in this repo's history — host-numpy params silently
re-transferred ~1GB per forward (23.7 s p50, round 2) — was invisible to
unit tests but statically visible in the AST. This package is the scalable
defense: an AST lint pass with a rule registry targeting host-transfer,
recompile, donation, sqlite-threading, and bench-timing hazards, wired
into tier-1 via ``tests/test_repo_clean.py``.

CLI::

    python -m vilbert_multitask_tpu.analysis [--strict] [--baseline FILE]
        [--write-baseline FILE] [--json] [paths...]

Suppress a finding inline with ``# vmtlint: disable=VMT101`` (rule id or
rule name; ``disable=all`` silences the line). Grandfathered findings live
in the baseline file (default from ``[tool.vmtlint]`` in pyproject.toml),
each entry carrying a one-line justification.
"""

from vilbert_multitask_tpu.analysis.core import (  # noqa: F401
    Finding,
    Rule,
    analyze_file,
    analyze_paths,
    analyze_project,
    analyze_source,
)
from vilbert_multitask_tpu.analysis.graph import ProjectGraph  # noqa: F401
from vilbert_multitask_tpu.analysis.rules import RULES  # noqa: F401
