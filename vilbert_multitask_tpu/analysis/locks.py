"""Project-wide lock-order analysis and the flow-sensitive rules.

Built on ``analysis/cfg.py`` + ``analysis/dataflow.py``:

1. :class:`LockRegistry` gives every lock **object** in the project a
   canonical identity — ``module:Class.attr`` for ``self._x = threading.Lock()``
   fields (so the same field unifies across methods, the same resolution
   `_ClassLockAnalysis` uses for VMT110), ``module:name`` for module-level
   locks (chased through imports via the ``ProjectGraph`` symbol tables), and
   a function-scoped id for locals.  Conditions, queues, events and threads
   are registered too — they are the receivers of the blocking calls VMT120
   cares about.

2. Per function, the must-hold lock-set dataflow yields a
   :class:`FnLockSummary`: every acquisition with the set held *before* it,
   every blocking call (``Condition.wait``/``queue.get``/``join``/
   ``Event.wait``) with the set held at it, and every resolvable project call
   made while at least one lock is held.

3. :class:`LockFlow` composes the summaries through the existing
   :class:`~.callgraph.CallGraph` into a lock-acquisition-order graph: an
   edge ``A -> B`` means some path acquires ``B`` while holding ``A`` —
   directly, or through a chain of calls.  A cycle in that graph is an ABBA
   deadlock candidate (**VMT119**), reported with one witness chain per
   conflicting order.  Blocking calls whose held-set contains any lock other
   than the waited condition's own are **VMT120**.

**VMT121** is the flow-sensitive upgrade of VMT102: reaching-definitions over
the enclosing function's CFG catch a jitted closure whose captured local has
more than one definition reaching a call site (the first trace bakes one
value; paths through the other definition silently reuse the stale constant),
plus trace-time reads of ``self.*``/module globals that some other method
rebinds.

Everything is stdlib-only (``ast`` + the local dataflow tier) per the
layering contracts in pyproject.toml.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, FrozenSet, Iterator, List, Optional, Sequence, Set, Tuple

from vilbert_multitask_tpu.analysis.cfg import (
    WithEnter, build_cfg, iter_event_nodes)
from vilbert_multitask_tpu.analysis.context import ModuleContext
from vilbert_multitask_tpu.analysis.core import Finding, Rule
from vilbert_multitask_tpu.analysis.dataflow import (
    LockSetAnalysis, ReachingDefs, _strip_acquire_call, iter_event_facts,
    solve)

# Constructors that mint an identity the analysis tracks. "lock" and
# "condition" participate in held-sets; the rest are blocking-call receivers.
CTOR_KINDS = {
    "threading.Lock": "lock",
    "threading.RLock": "lock",
    "threading.Semaphore": "lock",
    "threading.BoundedSemaphore": "lock",
    "threading.Condition": "condition",
    "queue.Queue": "queue",
    "queue.LifoQueue": "queue",
    "queue.PriorityQueue": "queue",
    "queue.SimpleQueue": "queue",
    "threading.Thread": "thread",
    "threading.Event": "event",
}
_HELD_KINDS = ("lock", "condition")
_INIT_METHODS = {"__init__", "__new__", "__post_init__", "__del__"}
_BLOCKING_ATTRS = ("wait", "wait_for", "get", "join")


@dataclasses.dataclass
class LockDecl:
    lock_id: str
    kind: str
    display: str  # short human name, e.g. "ReplicaPool._cond"
    path: str
    line: int


class LockRegistry:
    """Canonical identities for every lock-ish object in the project."""

    def __init__(self, project) -> None:
        self.project = project
        self.by_id: Dict[str, LockDecl] = {}
        self.class_locks: Dict[Tuple[str, Tuple[str, ...]],
                               Dict[str, LockDecl]] = {}
        self.module_locks: Dict[str, Dict[str, LockDecl]] = {}
        self.local_locks: Dict[str, Dict[str, LockDecl]] = {}
        cg = project.callgraph
        for mod in project.modules.values():
            self._collect_module(mod, cg)

    def _collect_module(self, mod, cg) -> None:
        ctx = mod.ctx
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Assign):
                value, targets = node.value, node.targets
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                value, targets = node.value, [node.target]
            else:
                continue
            kind = self._ctor_kind(ctx, value)
            if kind is None:
                continue
            for target in targets:
                self._register(mod, cg, node, target, kind)

    @staticmethod
    def _ctor_kind(ctx: ModuleContext, value: ast.AST) -> Optional[str]:
        # Walk the whole RHS: `self.stop = ev if ev else threading.Event()`
        # still registers the identity.
        for n in ast.walk(value):
            if isinstance(n, ast.Call):
                kind = CTOR_KINDS.get(ctx.resolve(n.func))
                if kind is not None:
                    return kind
        return None

    def _register(self, mod, cg, assign: ast.AST, target: ast.expr,
                  kind: str) -> None:
        ctx = mod.ctx
        if (isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"):
            owner = ctx.enclosing_function(assign)
            fnode = cg.by_node.get(id(owner)) if owner is not None else None
            if fnode is None or not fnode.cls_scope:
                return
            key = (mod.name, fnode.cls_scope)
            decl = self._mk(
                f"{mod.name}:{'.'.join(fnode.cls_scope)}.{target.attr}",
                kind, f"{fnode.cls_scope[-1]}.{target.attr}",
                ctx.rel_path, assign.lineno)
            self.class_locks.setdefault(key, {})[target.attr] = decl
        elif isinstance(target, ast.Name):
            owner = ctx.enclosing_function(assign)
            if owner is None:
                leaf = mod.name.split(".")[-1]
                decl = self._mk(f"{mod.name}:{target.id}", kind,
                                f"{leaf}.{target.id}", ctx.rel_path,
                                assign.lineno)
                self.module_locks.setdefault(mod.name, {})[target.id] = decl
            else:
                fnode = cg.by_node.get(id(owner))
                if fnode is None:
                    return
                decl = self._mk(f"{fnode.qualname}.<local>.{target.id}",
                                kind, target.id, ctx.rel_path, assign.lineno)
                self.local_locks.setdefault(
                    fnode.qualname, {})[target.id] = decl

    def _mk(self, lock_id: str, kind: str, display: str, path: str,
            line: int) -> LockDecl:
        # First declaration wins; re-assignment of the same field keeps one
        # identity (it is the same slot).
        decl = self.by_id.get(lock_id)
        if decl is None:
            decl = LockDecl(lock_id, kind, display, path, line)
            self.by_id[lock_id] = decl
        return decl

    # ------------------------------------------------------------ resolve
    def resolve_decl(self, fnode, expr: ast.AST) -> Optional[LockDecl]:
        """The declaration a lock expression denotes inside ``fnode``."""
        mod = fnode.module
        if (isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self" and fnode.cls_scope):
            return self.class_locks.get(
                (mod.name, fnode.cls_scope), {}).get(expr.attr)
        if isinstance(expr, ast.Name):
            decl = self.local_locks.get(fnode.qualname, {}).get(expr.id)
            if decl is not None:
                return decl
            decl = self.module_locks.get(mod.name, {}).get(expr.id)
            if decl is not None:
                return decl
            target = mod.refs.get(expr.id)
            if target:
                return self._module_symbol(target)
            return None
        if isinstance(expr, ast.Attribute):
            dotted = mod.ctx.resolve(expr)
            if dotted:
                return self._module_symbol(dotted)
        return None

    def _module_symbol(self, dotted: str) -> Optional[LockDecl]:
        resolved = self.project.resolve_symbol(dotted)
        if resolved is None:
            return None
        tmod, sym = resolved
        if sym and "." not in sym:
            return self.module_locks.get(tmod.name, {}).get(sym)
        return None

    def held_resolver(self, fnode):
        """Resolver for the lock-set domain: only held-kind identities."""
        def resolve(expr: ast.AST) -> Optional[str]:
            decl = self.resolve_decl(fnode, expr)
            if decl is not None and decl.kind in _HELD_KINDS:
                return decl.lock_id
            return None
        return resolve


# ---------------------------------------------------------------------------
# Per-function summaries
# ---------------------------------------------------------------------------

LockSet = FrozenSet[str]


@dataclasses.dataclass
class FnLockSummary:
    fn: object  # FuncNode
    # (decl, site node, locks definitely held before the acquisition)
    acquires: List[Tuple[LockDecl, ast.AST, LockSet]]
    # (description, own lock id or None, site node, locks held)
    waits: List[Tuple[str, Optional[str], ast.AST, LockSet]]
    # (callee qualname, call node, locks held) — held-nonempty calls only
    calls: List[Tuple[str, ast.AST, LockSet]]


def _interesting(fn_node: ast.AST) -> bool:
    """Cheap prefilter: anything lock-shaped in this body at all?"""
    stack = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        if isinstance(node, (ast.With, ast.AsyncWith)):
            return True
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _BLOCKING_ATTRS + ("acquire",)):
            return True
        stack.extend(ast.iter_child_nodes(node))
    return False


class LockFlow:
    """The composed, project-wide view: summaries, order graph, findings."""

    def __init__(self, project) -> None:
        self.project = project
        self.cg = project.callgraph
        self.registry = LockRegistry(project)
        self.summaries: Dict[str, FnLockSummary] = {}
        self._unique_methods = self._index_unique_methods()
        for fn in self.cg.functions.values():
            if _interesting(fn.node):
                summary = self._summarize(fn)
                if summary.acquires or summary.waits or summary.calls:
                    self.summaries[fn.qualname] = summary
        # Transitive facts keyed by function qualname.
        self.inner_acquires: Dict[str, Dict[str, Tuple[str, object]]] = {}
        self.inner_waits: Dict[
            str, Dict[Tuple[str, Optional[str]], Tuple[str, object]]] = {}
        # (held, acquired) -> representative witness steps
        self.edges: Dict[Tuple[str, str], List[dict]] = {}
        self.inversions: List[dict] = []
        self.wait_findings: List[dict] = []
        self._compose()

    # ----------------------------------------------------------- indexing
    def _index_unique_methods(self) -> Dict[str, Optional[str]]:
        """Leaf method name -> qualname when project-unique, else None.

        The fallback for calls like ``self.pool.checkout()`` whose receiver
        type is unknown statically: if exactly one class method in the whole
        project bears the name, assume it is the target.  Under-approximate
        on ambiguity — a wrong edge would fabricate deadlocks.
        """
        seen: Dict[str, Optional[str]] = {}
        for fn in self.cg.functions.values():
            if not fn.cls_scope:
                continue
            leaf = fn.scope[-1]
            seen[leaf] = None if leaf in seen else fn.qualname
        return seen

    def display(self, lock_id: str) -> str:
        decl = self.registry.by_id.get(lock_id)
        return decl.display if decl is not None else lock_id

    # --------------------------------------------------------- summaries
    def _summarize(self, fn) -> FnLockSummary:
        mod = fn.module
        cfg = build_cfg(fn.node)
        analysis = LockSetAnalysis(self.registry.held_resolver(fn))
        in_facts = solve(cfg, analysis)
        summary = FnLockSummary(fn, [], [], [])
        seen_calls: Set[int] = set()
        for event, fact in iter_event_facts(cfg, analysis, in_facts):
            if isinstance(event, WithEnter):
                decl = self.registry.resolve_decl(
                    fn, _strip_acquire_call(event.item.context_expr))
                if decl is not None and decl.kind in _HELD_KINDS:
                    summary.acquires.append(
                        (decl, event.item.context_expr, fact))
                continue
            for node in iter_event_nodes(event):
                if not isinstance(node, ast.Call) or id(node) in seen_calls:
                    continue
                seen_calls.add(id(node))
                self._scan_call(fn, mod, node, fact, summary)
        return summary

    def _scan_call(self, fn, mod, node: ast.Call, fact: LockSet,
                   summary: FnLockSummary) -> None:
        func = node.func
        if isinstance(func, ast.Attribute):
            decl = self.registry.resolve_decl(fn, func.value)
            if func.attr == "acquire" and decl is not None \
                    and decl.kind in _HELD_KINDS:
                summary.acquires.append((decl, node, fact))
                return
            if func.attr in _BLOCKING_ATTRS and decl is not None:
                wait = self._blocking_record(decl, func.attr, node, fact)
                if wait is not None:
                    summary.waits.append(wait)
                    return
        if not fact:
            return
        qual = self.cg.resolve_callable(mod, func, fn.scope, fn.cls_scope)
        if (qual is None and isinstance(func, ast.Attribute)
                and not (isinstance(func.value, ast.Name)
                         and func.value.id == "self")):
            qual = self._unique_methods.get(func.attr)
        if qual is not None and qual != fn.qualname:
            summary.calls.append((qual, node, fact))

    @staticmethod
    def _blocking_record(decl: LockDecl, attr: str, node: ast.Call,
                         fact: LockSet):
        desc = f"`{decl.display}.{attr}()`"
        if attr in ("wait", "wait_for"):
            if decl.kind == "condition":
                return (desc, decl.lock_id, node, fact)
            if decl.kind == "event":
                return (desc, None, node, fact)
            return None
        if attr == "get" and decl.kind == "queue":
            for kw in node.keywords:
                if (kw.arg == "block" and isinstance(kw.value, ast.Constant)
                        and kw.value.value is False):
                    return None  # non-blocking get
            return (desc, None, node, fact)
        if attr == "join" and decl.kind in ("thread", "queue"):
            return (desc, None, node, fact)
        return None

    # -------------------------------------------------------- composition
    def _call_edges(self, fn) -> Iterator[str]:
        for target, is_call in fn.edges:
            if is_call:
                yield target
        summary = self.summaries.get(fn.qualname)
        if summary is not None:
            for qual, _node, _held in summary.calls:
                yield qual  # includes by-name fallback targets

    def _compose(self) -> None:
        for qual, s in self.summaries.items():
            mine = self.inner_acquires.setdefault(qual, {})
            for decl, node, _held in s.acquires:
                mine.setdefault(decl.lock_id, ("direct", node))
            waits = self.inner_waits.setdefault(qual, {})
            for desc, own, node, _held in s.waits:
                waits.setdefault((desc, own), ("direct", node))
        changed = True
        while changed:
            changed = False
            for fn in self.cg.functions.values():
                for callee in self._call_edges(fn):
                    for lock_id in self.inner_acquires.get(callee, ()):
                        mine = self.inner_acquires.setdefault(
                            fn.qualname, {})
                        if lock_id not in mine:
                            mine[lock_id] = ("via", callee)
                            changed = True
                    for key in self.inner_waits.get(callee, ()):
                        mine_w = self.inner_waits.setdefault(
                            fn.qualname, {})
                        if key not in mine_w:
                            mine_w[key] = ("via", callee)
                            changed = True
        self._build_edges()
        self._find_inversions()
        self._find_wait_findings()

    def _rel_path(self, qual: str) -> str:
        return self.cg.functions[qual].module.ctx.rel_path

    def _step(self, text: str, path: str, line: int) -> dict:
        return {"message": text, "path": path, "line": line}

    def _acquire_chain(self, qual: str, lock_id: str) -> List[dict]:
        """Witness steps from ``qual`` down to the concrete acquisition."""
        steps: List[dict] = []
        cur = qual
        for _ in range(len(self.cg.functions) + 1):  # cycle guard
            how, val = self.inner_acquires[cur][lock_id]
            if how == "direct":
                steps.append(self._step(
                    f"`{cur}` acquires `{self.display(lock_id)}`",
                    self._rel_path(cur), getattr(val, "lineno", 1)))
                return steps
            callee = val
            steps.append(self._step(
                f"`{cur}` calls `{callee}`", self._rel_path(cur),
                self.cg.functions[cur].node.lineno))
            cur = callee
        return steps

    def _wait_chain(self, qual: str,
                    key: Tuple[str, Optional[str]]) -> List[dict]:
        steps: List[dict] = []
        cur = qual
        for _ in range(len(self.cg.functions) + 1):
            how, val = self.inner_waits[cur][key]
            if how == "direct":
                steps.append(self._step(
                    f"`{cur}` blocks on {key[0]}",
                    self._rel_path(cur), getattr(val, "lineno", 1)))
                return steps
            callee = val
            steps.append(self._step(
                f"`{cur}` calls `{callee}`", self._rel_path(cur),
                self.cg.functions[cur].node.lineno))
            cur = callee
        return steps

    def _add_edge(self, held: str, acquired: str,
                  steps: List[dict]) -> None:
        self.edges.setdefault((held, acquired), steps)

    def _build_edges(self) -> None:
        for qual, s in self.summaries.items():
            path = self._rel_path(qual)
            for decl, node, held in s.acquires:
                for h in held:
                    if h == decl.lock_id:
                        continue  # RLock re-entry is not an order edge
                    self._add_edge(h, decl.lock_id, [self._step(
                        f"`{qual}` acquires `{decl.display}` while "
                        f"holding `{self.display(h)}`",
                        path, getattr(node, "lineno", 1))])
            for callee, node, held in s.calls:
                inner = self.inner_acquires.get(callee)
                if not inner:
                    continue
                for lock_id in inner:
                    for h in held:
                        if h == lock_id:
                            continue
                        steps = [self._step(
                            f"`{qual}` holds `{self.display(h)}` at the "
                            f"call to `{callee}`",
                            path, getattr(node, "lineno", 1))]
                        steps += self._acquire_chain(callee, lock_id)
                        self._add_edge(h, lock_id, steps)

    # ------------------------------------------------------------ cycles
    def _find_inversions(self) -> None:
        adj: Dict[str, Set[str]] = {}
        for held, acquired in self.edges:
            adj.setdefault(held, set()).add(acquired)
            adj.setdefault(acquired, set())
        reach: Dict[str, Set[str]] = {}
        for start in adj:
            seen: Set[str] = set()
            stack = [start]
            while stack:
                cur = stack.pop()
                for nxt in adj[cur]:
                    if nxt not in seen:
                        seen.add(nxt)
                        stack.append(nxt)
            reach[start] = seen
        # SCCs over mutual reachability; one report per component.
        assigned: Set[str] = set()
        for a in sorted(adj):
            if a in assigned or a not in reach[a]:
                continue  # not on any cycle
            scc = {b for b in adj if a in reach[b] and b in reach[a]}
            assigned |= scc
            cycle = self._shortest_cycle(a, scc, adj)
            if cycle is None:
                continue
            chains = [self.edges[edge] for edge in cycle]
            locks = " -> ".join(self.display(e[0]) for e in cycle)
            detail = "; versus ".join(
                " -> ".join(step["message"] for step in chain)
                for chain in chains)
            anchor = chains[0][0]
            self.inversions.append({
                "path": anchor["path"], "line": anchor["line"],
                "flows": chains,
                "message": (
                    f"lock-order inversion (`{locks}` -> "
                    f"`{self.display(cycle[0][0])}`): {detail} — these "
                    "orders deadlock when the threads interleave"),
            })

    @staticmethod
    def _shortest_cycle(start: str, scc: Set[str],
                        adj: Dict[str, Set[str]]
                        ) -> Optional[List[Tuple[str, str]]]:
        """Shortest edge path start -> ... -> start within the SCC."""
        parents: Dict[str, Optional[str]] = {start: None}
        order = [start]
        i = 0
        while i < len(order):
            cur = order[i]
            i += 1
            for nxt in sorted(adj[cur] & scc):
                if nxt == start:
                    path = [(cur, start)]
                    while parents[cur] is not None:
                        path.append((parents[cur], cur))
                        cur = parents[cur]
                    return list(reversed(path))
                if nxt not in parents:
                    parents[nxt] = cur
                    order.append(nxt)
        return None

    # ------------------------------------------------------------- waits
    def _find_wait_findings(self) -> None:
        reported: Set[Tuple[str, int]] = set()

        def emit(path: str, node: ast.AST, message: str) -> None:
            key = (path, getattr(node, "lineno", 1))
            if key not in reported:
                reported.add(key)
                self.wait_findings.append(
                    {"path": path, "line": key[1],
                     "col": getattr(node, "col_offset", 0),
                     "message": message})

        for qual, s in self.summaries.items():
            path = self._rel_path(qual)
            for desc, own, node, held in s.waits:
                foreign = held - {own} if own else held
                if not foreign:
                    continue
                names = ", ".join(sorted(
                    f"`{self.display(h)}`" for h in foreign))
                release = (" (the condition releases its own lock during "
                           "the wait; the others stay held)" if own else "")
                emit(path, node,
                     f"blocks on {desc} while holding {names}{release} — "
                     "every thread needing those locks stalls for the "
                     "duration of the wait")
            for callee, node, held in s.calls:
                for key, _how in self.inner_waits.get(callee, {}).items():
                    desc, own = key
                    foreign = held - {own} if own else held
                    if not foreign:
                        continue
                    names = ", ".join(sorted(
                        f"`{self.display(h)}`" for h in foreign))
                    chain = " -> ".join(
                        step["message"]
                        for step in self._wait_chain(callee, key))
                    emit(path, node,
                         f"holds {names} across a call that blocks on "
                         f"{desc}: {chain} — the held locks are pinned "
                         "for the full wait")


def lock_flow(project) -> LockFlow:
    flow = getattr(project, "_lock_flow", None)
    if flow is None:
        flow = LockFlow(project)
        project._lock_flow = flow
    return flow


class _Anchor:
    """Line/col shim so Rule.finding can anchor precomputed findings."""

    __slots__ = ("lineno", "col_offset")

    def __init__(self, line: int, col: int = 0) -> None:
        self.lineno = line
        self.col_offset = col


# ---------------------------------------------------------------------------
# VMT119 / VMT120
# ---------------------------------------------------------------------------


class LockOrderInversion(Rule):
    id = "VMT119"
    name = "lock-order-inversion"
    severity = "error"
    description = ("Cycle in the project-wide lock-acquisition-order graph "
                   "(ABBA deadlock candidate), with one witness chain per "
                   "conflicting order.")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        flow = lock_flow(ctx.project)
        for inv in flow.inversions:
            if inv["path"] != ctx.rel_path:
                continue
            f = self.finding(ctx, _Anchor(inv["line"]), inv["message"])
            f.flows = [list(chain) for chain in inv["flows"]]
            yield f


class WaitHoldingForeignLock(Rule):
    id = "VMT120"
    name = "wait-holding-foreign-lock"
    severity = "error"
    description = ("Condition.wait / queue.get / join / Event.wait reached "
                   "while the lock-set holds any lock other than the "
                   "condition's own.")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        flow = lock_flow(ctx.project)
        for w in flow.wait_findings:
            if w["path"] != ctx.rel_path:
                continue
            yield self.finding(ctx, _Anchor(w["line"], w["col"]),
                               w["message"])


# ---------------------------------------------------------------------------
# VMT121 jit-closure-capture
# ---------------------------------------------------------------------------


def _free_loads(body: ast.AST) -> Set[str]:
    """Names the (jitted) body reads from an enclosing scope."""
    bound: Set[str] = set()
    loads: Set[str] = set()
    for node in ast.walk(body):
        if isinstance(node, ast.Name):
            if isinstance(node.ctx, ast.Load):
                loads.add(node.id)
            else:
                bound.add(node.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                               ast.Lambda)):
            args = node.args
            for a in (args.args + args.posonlyargs + args.kwonlyargs):
                bound.add(a.arg)
            if args.vararg:
                bound.add(args.vararg.arg)
            if args.kwarg:
                bound.add(args.kwarg.arg)
            if not isinstance(node, ast.Lambda):
                bound.add(node.name)
        elif isinstance(node, ast.comprehension):
            for n in ast.walk(node.target):
                if isinstance(n, ast.Name):
                    bound.add(n.id)
    return loads - bound


def _own_assigned_names(fn: ast.AST) -> Set[str]:
    """Locals of ``fn``: params plus names stored outside nested scopes."""
    names: Set[str] = set()
    args = fn.args
    for a in (args.args + args.posonlyargs + args.kwonlyargs):
        names.add(a.arg)
    if args.vararg:
        names.add(args.vararg.arg)
    if args.kwarg:
        names.add(args.kwarg.arg)
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            if not isinstance(node, ast.Lambda):
                names.add(node.name)
            continue
        if isinstance(node, ast.Name) and isinstance(
                node.ctx, (ast.Store, ast.Del)):
            names.add(node.id)
        stack.extend(ast.iter_child_nodes(node))
    return names


class JitClosureCapture(Rule):
    id = "VMT121"
    name = "jit-closure-capture"
    severity = "error"
    description = ("Flow-sensitive VMT102: a jitted closure captures a value "
                   "that has more than one definition reaching the traced "
                   "region, or reads mutable self./global state at trace "
                   "time.")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        yield from self._local_rebinds(ctx)
        yield from self._mutable_trace_reads(ctx)

    # ----------------------------------------------- captured local rebinds
    def _local_rebinds(self, ctx: ModuleContext) -> Iterator[Finding]:
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            creations = list(self._jit_creations(ctx, fn))
            if not creations:
                continue
            fn_locals = _own_assigned_names(fn)
            for bound, body in creations:
                captured = frozenset(
                    (_free_loads(body) & fn_locals) - {bound})
                if not captured:
                    continue
                yield from self._check_captures(ctx, fn, bound, captured)

    def _jit_creations(self, ctx: ModuleContext, fn: ast.AST
                       ) -> Iterator[Tuple[str, ast.AST]]:
        """(bound name, jitted body) pairs created directly inside ``fn``."""
        nested = {child.name: child for child in ast.walk(fn)
                  if isinstance(child, (ast.FunctionDef,
                                        ast.AsyncFunctionDef))
                  and child is not fn}
        for node in ast.iter_child_nodes(fn):
            stack = [node]
            while stack:
                cur = stack.pop()
                if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    if any(ctx.is_jit_entry(
                            d.func if isinstance(d, ast.Call) else d)
                           for d in cur.decorator_list):
                        yield cur.name, cur
                    continue
                if (isinstance(cur, ast.Assign)
                        and isinstance(cur.value, ast.Call)
                        and ctx.is_jit_entry(cur.value.func)
                        and cur.value.args):
                    target_fn = cur.value.args[0]
                    body: Optional[ast.AST] = None
                    if isinstance(target_fn, ast.Lambda):
                        body = target_fn
                    elif isinstance(target_fn, ast.Name):
                        body = nested.get(target_fn.id)
                    if body is not None:
                        for t in cur.targets:
                            if isinstance(t, ast.Name):
                                yield t.id, body
                stack.extend(ast.iter_child_nodes(cur))

    def _check_captures(self, ctx: ModuleContext, fn: ast.AST, bound: str,
                        captured: FrozenSet[str]) -> Iterator[Finding]:
        cfg = build_cfg(fn)
        analysis = ReachingDefs(captured, params_line=fn.lineno)
        in_facts = solve(cfg, analysis)
        per_name: Dict[str, Set[int]] = {}
        flagged: Set[str] = set()
        for event, fact in iter_event_facts(cfg, analysis, in_facts):
            for node in iter_event_nodes(event):
                if not (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Name)
                        and node.func.id == bound):
                    continue
                for name in captured:
                    if name in flagged:
                        continue
                    lines = {line for n, line in fact if n == name}
                    seen = per_name.setdefault(name, set())
                    seen |= lines
                    if len(seen) > 1:
                        flagged.add(name)
                        where = ", ".join(
                            str(ln) if ln else "entry"
                            for ln in sorted(seen))
                        yield self.finding(
                            ctx, node,
                            f"`{name}` is captured by the jitted `{bound}` "
                            f"but has multiple definitions reaching its "
                            f"calls (lines {where}) — the first trace bakes "
                            f"one value and later calls silently reuse that "
                            f"stale constant; pass `{name}` as an argument "
                            f"instead")

    # --------------------------------------------- mutable trace-time reads
    def _mutable_trace_reads(self, ctx: ModuleContext) -> Iterator[Finding]:
        rebound_globals = self._rebound_globals(ctx)
        mutable_cache: Dict[int, Dict[str, str]] = {}
        for info in ctx.jit_bodies:
            cls = next((a for a in ctx.ancestors(info.body)
                        if isinstance(a, ast.ClassDef)), None)
            reported: Set[str] = set()
            if cls is not None:
                mutable = mutable_cache.get(id(cls))
                if mutable is None:
                    mutable = self._class_mutable_attrs(cls)
                    mutable_cache[id(cls)] = mutable
                aliases = self._self_aliases(ctx, info.body)
                for node in ast.walk(info.body):
                    if not (isinstance(node, ast.Attribute)
                            and isinstance(node.ctx, ast.Load)
                            and isinstance(node.value, ast.Name)
                            and node.value.id in aliases):
                        continue
                    if node.attr in mutable and node.attr not in reported:
                        reported.add(node.attr)
                        yield self.finding(
                            ctx, node,
                            f"jit-traced code reads `self.{node.attr}`, "
                            f"which `{mutable[node.attr]}` rebinds — the "
                            f"value is baked in at trace time, so a rebind "
                            f"after tracing leaves the compiled program on "
                            f"the stale value; hoist it to a local and pass "
                            f"it as an argument (or key the compile cache "
                            f"on it)")
            for node in ast.walk(info.body):
                if (isinstance(node, ast.Name)
                        and isinstance(node.ctx, ast.Load)
                        and node.id in rebound_globals
                        and node.id not in reported):
                    reported.add(node.id)
                    yield self.finding(
                        ctx, node,
                        f"jit-traced code reads module global `{node.id}`, "
                        f"which `{rebound_globals[node.id]}` rebinds via "
                        f"`global` — the traced program keeps whichever "
                        f"value was live at trace time")

    @staticmethod
    def _self_aliases(ctx: ModuleContext, body: ast.AST) -> Set[str]:
        aliases = {"self"}
        encl = ctx.enclosing_function(body)
        if encl is not None:
            for node in ast.walk(encl):
                if (isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Name)
                        and node.value.id == "self"):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            aliases.add(t.id)
        return aliases

    @staticmethod
    def _class_mutable_attrs(cls: ast.ClassDef) -> Dict[str, str]:
        """self.* attrs rebound outside __init__-like methods -> witness."""
        mutable: Dict[str, str] = {}
        for stmt in cls.body:
            if not isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if stmt.name in _INIT_METHODS:
                continue
            for node in ast.walk(stmt):
                targets: List[ast.expr] = []
                if isinstance(node, ast.Assign):
                    targets = node.targets
                elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                    targets = [node.target]
                for t in targets:
                    if (isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        mutable.setdefault(t.attr, stmt.name)
        return mutable

    @staticmethod
    def _rebound_globals(ctx: ModuleContext) -> Dict[str, str]:
        """Module-level names some function rebinds via `global` -> fn."""
        module_names: Set[str] = set()
        for stmt in ctx.tree.body:
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        module_names.add(t.id)
            elif (isinstance(stmt, ast.AnnAssign)
                  and isinstance(stmt.target, ast.Name)):
                module_names.add(stmt.target.id)
        rebound: Dict[str, str] = {}
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            declared: Set[str] = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Global):
                    declared.update(node.names)
            if not declared:
                continue
            for node in ast.walk(fn):
                if isinstance(node, ast.Name) and isinstance(
                        node.ctx, ast.Store) and node.id in declared \
                        and node.id in module_names:
                    rebound.setdefault(node.id, fn.name)
        return rebound
