"""Compile-surface manifest: the engine's XLA program-key universe.

Drives the shape-tier interpreter (:mod:`analysis.shapes`) over every
compiled-program builder the engine declares and emits
``COMPILE_SURFACE.json`` — one record per program family × bucket ×
param_dtype × fused mode × mesh topology × attention mode, each
dimension carrying witness chains for where its values originate in
source. The manifest is the answer to "what can this engine ever
compile": ROADMAP item 1's AOT cache pre-warms from it, CI pins it with
``vmtlint surface --check``, and the runtime cross-check test asserts
every key the live engine actually compiles maps onto a record.

Discovery is structural, not name-driven: a *program family* is any
function that builds a ``key = ("<family>", ...)`` tuple and stores into
``...._compiled[key]`` — the engine's compile-cache idiom — so new
families (a third program, a training step) appear in the manifest the
moment they adopt the idiom.
"""

from __future__ import annotations

import ast
import json
from typing import Dict, Iterator, List, Optional, Tuple

from vilbert_multitask_tpu.analysis.context import ModuleContext
from vilbert_multitask_tpu.analysis.shapes import (
    BOUNDED_ORIGINS,
    KnobTable,
    Scalar,
    interpret_function,
    knob_table,
)

SURFACE_VERSION = 1
MANIFEST_NAME = "COMPILE_SURFACE.json"

# The quant-mode axis of the key universe (ISSUE/ROADMAP item 1): params
# are served in exactly one of these storages; int8 implies the
# {"int8","scale"} leaf pair and dequant-inside-jit.
PARAM_DTYPES = ("float32", "bfloat16", "int8")


def _witness(path: str, line: int, note: str) -> dict:
    return {"path": path, "line": line, "note": note}


def load_project(sources: Dict[str, str]):
    """Parse {rel_path: source} into a linked ProjectGraph (the same
    construction analyze_project uses, minus the rules pass). Files that
    don't parse are skipped — the lint gate owns reporting those."""
    from vilbert_multitask_tpu.analysis.graph import ProjectGraph

    ctxs = []
    for rel_path in sorted(sources):
        try:
            tree = ast.parse(sources[rel_path])
        except SyntaxError:
            continue
        ctxs.append(ModuleContext(rel_path, sources[rel_path], tree))
    project = ProjectGraph(ctxs)
    for ctx in ctxs:
        ctx.project = project
    return project


# ------------------------------------------------------------- discovery
class ProgramFamily:
    def __init__(self, family: str, builder: str, path: str, line: int,
                 static_args: Tuple[str, ...], key_params: Tuple[str, ...],
                 method: str):
        self.family = family
        self.builder = builder  # "module:Class.method"
        self.path = path
        self.line = line  # the `key = (...)` assignment
        self.static_args = static_args
        self.key_params = key_params  # builder params feeding the key
        self.method = method  # bare method name, for call-site search
        self.static_origins: Dict[str, List[dict]] = {}

    def to_json(self) -> dict:
        return {
            "family": self.family,
            "builder": self.builder,
            "key_witness": _witness(
                self.path, self.line,
                f"compile-cache key built here: "
                f"(\"{self.family}\", {', '.join(self.key_params)}, "
                f"model_gen)"),
            "jit_static_args": list(self.static_args),
            "key_params": list(self.key_params),
            "static_origins": self.static_origins,
        }


def _compiled_key_fn(fn: ast.AST) -> Optional[Tuple[str, ast.Assign]]:
    """(family, key-assignment) when ``fn`` is a compile-cache builder:
    assigns ``key = ("<family>", ...)`` and stores ``..._compiled[key]``.
    """
    key_assign: Optional[ast.Assign] = None
    family: Optional[str] = None
    stores_key = False
    for node in ast.walk(fn):
        if (isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "key"
                and isinstance(node.value, ast.Tuple)
                and node.value.elts
                and isinstance(node.value.elts[0], ast.Constant)
                and isinstance(node.value.elts[0].value, str)):
            key_assign = node
            family = node.value.elts[0].value
        if (isinstance(node, ast.Subscript)
                and isinstance(node.ctx, ast.Store)
                and isinstance(node.value, ast.Attribute)
                and node.value.attr == "_compiled"
                and isinstance(node.slice, ast.Name)
                and node.slice.id == "key"):
            stores_key = True
    if family is not None and key_assign is not None and stores_key:
        return family, key_assign
    return None


def _builder_qualname(ctx: ModuleContext, fn: ast.AST) -> str:
    parts = [getattr(fn, "name", "<lambda>")]
    for anc in ctx.ancestors(fn):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef)):
            parts.append(anc.name)
    mod = ctx.rel_path[:-3].replace("/", ".")
    return f"{mod}:{'.'.join(reversed(parts))}"


def discover_programs(project) -> List[ProgramFamily]:
    out: List[ProgramFamily] = []
    for mod in sorted(project.modules.values(), key=lambda m: m.name):
        ctx = mod.ctx
        jit_statics = {id(info.body): info.static_params
                       for info in ctx.jit_bodies}
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            hit = _compiled_key_fn(fn)
            if hit is None:
                continue
            family, key_assign = hit
            params = tuple(a.arg for a in fn.args.args if a.arg != "self")
            key_tuple = key_assign.value
            key_params = tuple(
                e.id for e in key_tuple.elts[1:]
                if isinstance(e, ast.Name) and e.id in params)
            statics: Tuple[str, ...] = ()
            for node in ast.walk(fn):
                sp = jit_statics.get(id(node))
                if sp:
                    statics = tuple(sp)
                    break
            out.append(ProgramFamily(
                family, _builder_qualname(ctx, fn), ctx.rel_path,
                key_assign.lineno, statics, key_params,
                getattr(fn, "name", "")))
    out.sort(key=lambda p: p.family)
    return out


# ---------------------------------------------------- static-arg origins
# Builder call sites are searched under the builder method name AND the
# dispatch funnels that forward a (bucket, collect_attention) prefix
# verbatim — the provenance that matters is at the mouth of the funnel,
# not the passthrough hops.
_FUNNELS = ("_call_forward", "_run_rows", "_dispatch_forward")


def collect_static_origins(project, programs: List[ProgramFamily],
                           knobs: KnobTable) -> None:
    """For each builder parameter that feeds the compile key, record the
    abstract origins of every value reaching it through direct calls or
    the dispatch funnels. Passthrough hops (a funnel forwarding its own
    parameter) are skipped; what remains is the real key material: bucket
    values from ``bucket_for``/``all_row_buckets``, literals, knobs — or
    an unbounded source, which the manifest surfaces loudly."""
    names = {p.method: p for p in programs}
    targets = set(names) | set(_FUNNELS)
    for mod in sorted(project.modules.values(), key=lambda m: m.name):
        ctx = mod.ctx
        if not any(t in ctx.source for t in targets):
            continue
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            calls = [n for n in ast.walk(fn)
                     if isinstance(n, ast.Call)
                     and isinstance(n.func, ast.Attribute)
                     and n.func.attr in targets]
            if not calls or _compiled_key_fn(fn) is not None:
                continue
            interp = None
            for call in calls:
                if ctx.enclosing_function(call) is not fn:
                    continue
                if interp is None:
                    interp = interpret_function(ctx, fn, knobs)
                env = _env_at(interp, call)
                for prog in programs:
                    _record_call(ctx, interp, env, call, prog)


def _env_at(interp, call: ast.Call) -> Dict[str, object]:
    from vilbert_multitask_tpu.analysis.shapes import call_nodes_in

    for event, fact in interp.iter_facts():
        for node in call_nodes_in(event):
            if node is call:
                return fact
    return {}


def _record_call(ctx: ModuleContext, interp, env, call: ast.Call,
                 prog: ProgramFamily) -> None:
    # Positional prefix convention shared by the builders and funnels:
    # (bucket, collect_attention, ...).
    for i, pname in enumerate(prog.key_params):
        if i >= len(call.args):
            continue
        arg = call.args[i]
        if isinstance(arg, ast.Starred):
            continue
        val = interp.eval(arg, env)
        if not isinstance(val, Scalar):
            continue
        if val.origin == "param":
            # A passthrough hop — the origin lives at an outer call site.
            continue
        entry = {
            "origin": val.origin,
            "bounded": val.origin in BOUNDED_ORIGINS,
            "symbol": val.sym,
            "value": val.value if isinstance(val.value,
                                             (int, str, bool)) else None,
            "call_site": _witness(
                ctx.rel_path, call.lineno,
                f"`{ast.unparse(arg)}` flows into `{pname}` of "
                f"`{prog.family}` program dispatch"),
            "witness": [_witness(p, ln, msg)
                        for p, ln, msg in val.witness],
        }
        bucket_entries = prog.static_origins.setdefault(pname, [])
        if entry not in bucket_entries:
            bucket_entries.append(entry)


# ------------------------------------------------------------ dimensions
def _knob_witnesses(knobs: KnobTable, fields: Tuple[str, ...]
                    ) -> List[dict]:
    out = []
    for f in fields:
        knob = knobs.field(f)
        if knob is not None:
            out.append(_witness(knob.path, knob.line,
                                f"declared `{knob.sym} = {knob.value!r}`"))
    return out


def _find_def(project, name: str) -> Optional[Tuple[str, int]]:
    for mod in sorted(project.modules.values(), key=lambda m: m.name):
        for node in ast.walk(mod.ctx.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name == name:
                return mod.ctx.rel_path, node.lineno
    return None


def _find_attr_augassign(project, attr: str) -> Optional[Tuple[str, int]]:
    for mod in sorted(project.modules.values(), key=lambda m: m.name):
        for node in ast.walk(mod.ctx.tree):
            if isinstance(node, ast.AugAssign) \
                    and isinstance(node.target, ast.Attribute) \
                    and node.target.attr == attr:
                return mod.ctx.rel_path, node.lineno
    return None


def _bucket_dimension(project, knobs: KnobTable) -> dict:
    values: List[int] = []
    for f in ("image_buckets", "throughput_buckets"):
        knob = knobs.field(f)
        if knob is not None and isinstance(knob.value, (tuple, list)):
            values.extend(v for v in knob.value if isinstance(v, int))
    witnesses = _knob_witnesses(knobs, ("image_buckets",
                                        "throughput_buckets"))
    arb = _find_def(project, "all_row_buckets")
    if arb is not None:
        witnesses.append(_witness(
            arb[0], arb[1],
            "all_row_buckets(): the sorted union both warmup and "
            "run_many dispatch from"))
    return {"values": sorted(set(values)), "witnesses": witnesses}


def _dtype_dimension(project, knobs: KnobTable) -> dict:
    witnesses = _knob_witnesses(knobs, ("param_dtype",))
    for mod in sorted(project.modules.values(), key=lambda m: m.name):
        for node in ast.walk(mod.ctx.tree):
            if isinstance(node, ast.Assign) \
                    and any(isinstance(t, ast.Attribute)
                            and t.attr == "param_dtype"
                            for t in node.targets):
                witnesses.append(_witness(
                    mod.ctx.rel_path, node.lineno,
                    "engine pins the served param storage dtype here"))
                break
    return {"values": list(PARAM_DTYPES), "witnesses": witnesses}


def _attn_dimension(project) -> dict:
    witnesses: List[dict] = []
    for mod in sorted(project.modules.values(), key=lambda m: m.name):
        for info in mod.ctx.jit_bodies:
            if "attn" in info.static_params:
                witnesses.append(_witness(
                    mod.ctx.rel_path, info.body.lineno,
                    "jitted forward marks `attn` static — each value is "
                    "its own program"))
    return {"values": [False, True], "witnesses": witnesses}


def _topology_dimension(knobs: KnobTable) -> List[dict]:
    axes = {}
    for f in ("dp", "tp", "sp"):
        knob = knobs.get("MeshConfig", f)
        axes[f] = knob.value if knob is not None else None
    topo_id = "".join(f"{k}{v}." for k, v in axes.items()
                      if v is not None).rstrip(".")
    return [{
        "id": topo_id or "default",
        "axes": axes,
        "witnesses": _knob_witnesses(knobs, ("dp", "tp", "sp")),
        "note": ("default MeshConfig; a differently-shaped mesh is a "
                 "different XLA program for every record"),
    }]


# --------------------------------------------------------------- surface
def build_surface(project) -> dict:
    """The full manifest as a JSON-ready dict. Deterministic: no
    timestamps, stable ordering — byte-identical output for an unchanged
    tree is what makes ``surface --check`` a meaningful gate."""
    knobs = knob_table(project)
    programs = discover_programs(project)
    collect_static_origins(project, programs, knobs)

    buckets = _bucket_dimension(project, knobs)
    dtypes = _dtype_dimension(project, knobs)
    attn = _attn_dimension(project)
    fused = {
        "values": [True, False],
        "witnesses": _knob_witnesses(knobs, ("fused_task_heads",)),
    }
    topologies = _topology_dimension(knobs)

    records = []
    for prog in programs:
        for bucket in buckets["values"]:
            for dtype in dtypes["values"]:
                for fused_mode in (True, False):
                    for topo in topologies:
                        for a in attn["values"]:
                            records.append({
                                "key": _record_key(prog.family, bucket,
                                                   dtype, fused_mode,
                                                   topo["id"], a),
                                "family": prog.family,
                                "bucket": bucket,
                                "param_dtype": dtype,
                                "fused": fused_mode,
                                "topology": topo["id"],
                                "collect_attention": a,
                            })
    records.sort(key=lambda r: r["key"])

    gen = _find_attr_augassign(project, "_model_gen")
    model_gen = {
        "note": ("the key's generation counter: bumped on kernel-fallback "
                 "rebuild, which clears the cache — it versions programs "
                 "within a process, it does not widen the universe"),
    }
    if gen is not None:
        model_gen["witness"] = _witness(
            gen[0], gen[1], "generation bump on degrade-to-XLA")

    return {
        "version": SURFACE_VERSION,
        "generator": "vmtlint surface",
        "dimensions": {
            "program_families": [p.to_json() for p in programs],
            "buckets": buckets,
            "param_dtypes": dtypes,
            "fused_modes": fused,
            "collect_attention": attn,
            "topologies": topologies,
        },
        "model_gen": model_gen,
        "record_count": len(records),
        "records": records,
    }


def _record_key(family: str, bucket: int, dtype: str, fused: bool,
                topo: str, attn: bool) -> str:
    return (f"{family}/b{bucket}/{dtype}/"
            f"{'fused' if fused else 'perhead'}/{topo}/"
            f"{'attn' if attn else 'plain'}")


def record_key_for_engine(family: str, bucket: int, param_dtype: str,
                          fused: bool, topo: str, collect_attention: bool
                          ) -> str:
    """The manifest key a live ``engine._compiled`` entry maps onto —
    the runtime↔manifest contract used by the CPU cross-check test."""
    return _record_key(family, bucket, param_dtype, fused, topo,
                       collect_attention)


def render_surface(surface: dict) -> str:
    return json.dumps(surface, indent=2, sort_keys=True) + "\n"


# ------------------------------------------------------------------ check
def diff_surface(committed: Optional[dict], fresh: dict) -> List[str]:
    """Human-readable drift between the committed manifest and a fresh
    build — dimension-level first (the actionable story), then the record
    delta."""
    if committed is None:
        return [f"{MANIFEST_NAME} missing — run `vmtlint surface` and "
                f"commit it"]
    msgs: List[str] = []
    if committed.get("version") != fresh.get("version"):
        msgs.append(f"manifest version {committed.get('version')} != "
                    f"generator version {fresh.get('version')}")
    cd = committed.get("dimensions", {})
    fd = fresh.get("dimensions", {})
    for dim in ("buckets", "param_dtypes", "fused_modes",
                "collect_attention"):
        cv = cd.get(dim, {}).get("values")
        fv = fd.get(dim, {}).get("values")
        if cv != fv:
            msgs.append(f"dimension `{dim}` drifted: committed {cv} vs "
                        f"tree {fv}")
    cf = [p.get("family") for p in cd.get("program_families", [])]
    ff = [p.get("family") for p in fd.get("program_families", [])]
    if cf != ff:
        msgs.append(f"program families drifted: committed {cf} vs "
                    f"tree {ff}")
    ct = [t.get("id") for t in cd.get("topologies", [])]
    ft = [t.get("id") for t in fd.get("topologies", [])]
    if ct != ft:
        msgs.append(f"topologies drifted: committed {ct} vs tree {ft}")
    ckeys = {r["key"] for r in committed.get("records", [])}
    fkeys = {r["key"] for r in fresh.get("records", [])}
    gone = sorted(ckeys - fkeys)
    new = sorted(fkeys - ckeys)
    if gone:
        msgs.append(f"{len(gone)} record(s) vanished from the tree "
                    f"(first: {gone[0]})")
    if new:
        msgs.append(f"{len(new)} new record(s) not in the committed "
                    f"manifest (first: {new[0]})")
    if not msgs and committed != fresh:
        msgs.append("manifest metadata drifted (witness lines moved?) — "
                    "regenerate with `vmtlint surface`")
    return msgs


# ------------------------------------------------------------------ sarif
def render_surface_sarif(surface: dict) -> str:
    """SARIF view of the manifest: one informational result per program
    family, its witness chains as codeFlows — the same schema the rule
    findings use, so the same viewers consume it."""
    results = []
    for prog in surface["dimensions"]["program_families"]:
        kw = prog["key_witness"]
        flows = []
        steps = [kw]
        for pname, entries in sorted(prog.get("static_origins",
                                              {}).items()):
            for e in entries:
                chain = list(e.get("witness", [])) + [e["call_site"]]
                flows.append(_sarif_flow(chain))
        n = sum(1 for r in surface["records"]
                if r["family"] == prog["family"])
        results.append({
            "ruleId": "COMPILE-SURFACE",
            "level": "note",
            "message": {"text": (
                f"program family `{prog['family']}` "
                f"({prog['builder']}): {n} records in the compile "
                f"surface")},
            "locations": [_sarif_loc(kw)],
            "codeFlows": flows or [_sarif_flow(steps)],
        })
    doc = {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "vmtlint-surface",
                "informationUri": "",
                "rules": [{
                    "id": "COMPILE-SURFACE",
                    "shortDescription": {
                        "text": "compile-surface manifest witness"},
                }],
            }},
            "results": results,
        }],
    }
    return json.dumps(doc, indent=2, sort_keys=True) + "\n"


def _sarif_loc(w: dict) -> dict:
    return {"physicalLocation": {
        "artifactLocation": {"uri": w["path"]},
        "region": {"startLine": max(1, int(w.get("line", 1)))}},
        "message": {"text": w.get("note", "")}}


def _sarif_flow(steps: List[dict]) -> dict:
    return {"threadFlows": [{"locations": [
        {"location": _sarif_loc(s)} for s in steps]}]}
