"""Per-module analysis context: alias resolution and jit-boundary discovery.

Rules never look at raw names — ``import numpy as np``, ``from jax import
jit``, ``from functools import partial`` all normalize through
:meth:`ModuleContext.resolve` to canonical dotted paths ("numpy.asarray",
"jax.jit", ...), so a rule matches the *binding*, not the spelling.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterator, List, Optional, Set, Tuple, Union

JIT_ENTRYPOINTS = {
    "jax.jit",
    "jax.pjit",
    "jax.experimental.pjit.pjit",
}

JitBody = Union[ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda]


@dataclasses.dataclass
class JitInfo:
    """One jit-compiled scope plus the staticness facts rules need."""

    body: JitBody
    donate: Tuple[int, ...] = ()
    # Parameter names that are static under this jit (static_argnames, or
    # positions from static_argnums mapped onto the signature): host math
    # on them is trace-time constant, not a per-call transfer.
    static_params: Tuple[str, ...] = ()


class ModuleContext:
    """One parsed module plus the lookup tables every rule shares."""

    def __init__(self, rel_path: str, source: str, tree: ast.Module):
        self.rel_path = rel_path
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        # Set by the driver once all modules are parsed: the ProjectGraph
        # this module belongs to (analysis/graph.py). Even single-file
        # analysis gets a one-module project, so rules can rely on it.
        self.project = None
        self.aliases: Dict[str, str] = {}
        self._parents: Dict[int, ast.AST] = {}
        for parent in ast.walk(tree):
            for child in ast.iter_child_nodes(parent):
                self._parents[id(child)] = parent
        self._collect_aliases()
        # Every jit-compiled scope: decorated defs, defs wrapped by name,
        # lambdas passed inline.
        self.jit_bodies: List[JitInfo] = []
        # Local names bound to a jitted callable (``f = jax.jit(g, ...)``),
        # mapped to their donate_argnums (empty tuple = jitted, no donation).
        self.jit_bound_names: Dict[str, Tuple[int, ...]] = {}
        self._collect_jit_bodies()

    # ------------------------------------------------------------- aliases
    def _collect_aliases(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    self.aliases[a.asname or a.name] = (
                        f"{node.module}.{a.name}")

    def resolve(self, node: ast.AST) -> str:
        """Canonical dotted path for a Name/Attribute chain ("" if not one)."""
        if isinstance(node, ast.Name):
            return self.aliases.get(node.id, node.id)
        if isinstance(node, ast.Attribute):
            base = self.resolve(node.value)
            return f"{base}.{node.attr}" if base else ""
        return ""

    # --------------------------------------------------------------- tree
    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self._parents.get(id(node))

    def ancestors(self, node: ast.AST) -> Iterator[ast.AST]:
        cur = self.parent(node)
        while cur is not None:
            yield cur
            cur = self.parent(cur)

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc
        return None

    def in_loop(self, node: ast.AST, *, stop_at_function: bool = True
                ) -> bool:
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.For, ast.While)):
                return True
            if stop_at_function and isinstance(
                    anc, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
                return False
        return False

    def in_main_block(self, node: ast.AST) -> bool:
        """True under ``if __name__ == "__main__":`` or inside a function
        named like a CLI entrypoint (main / _main / cli*) — script-style
        code where prints are the user interface, not debris."""
        for anc in self.ancestors(node):
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                name = anc.name.lstrip("_")
                if name == "main" or name.startswith("cli"):
                    return True
            if isinstance(anc, ast.If) and _is_main_guard(anc.test):
                return True
        return False

    # ---------------------------------------------------------------- jit
    def is_jit_entry(self, node: ast.AST) -> bool:
        """Does this expression evaluate to jax.jit/pjit (directly or via
        ``functools.partial(jax.jit, ...)``)?"""
        if self.resolve(node) in JIT_ENTRYPOINTS:
            return True
        return (isinstance(node, ast.Call)
                and self.resolve(node.func) == "functools.partial"
                and bool(node.args)
                and self.resolve(node.args[0]) in JIT_ENTRYPOINTS)

    def _jit_kwargs(self, call: ast.Call) -> List[ast.keyword]:
        """Keywords of a jit(...) or partial(jit, ...)(...) call, with the
        partial's own kwargs merged in."""
        kwargs = list(call.keywords)
        inner = call.func
        if isinstance(inner, ast.Call):
            # partial(jax.jit, static_argnames=...)(fn) nests the jit
            # kwargs one call deeper; merge both levels.
            kwargs = list(inner.keywords) + kwargs
        return kwargs

    def _donate_of(self, call: ast.Call) -> Tuple[int, ...]:
        """Literal donate_argnums of a jit(...) or partial(jit, ...) call."""
        for kw in self._jit_kwargs(call):
            if kw.arg in ("donate_argnums", "donate_argnames"):
                return _literal_int_tuple(kw.value)
        return ()

    def _static_params_of(self, call: Optional[ast.Call], body: JitBody
                          ) -> Tuple[str, ...]:
        """Parameter names static under this jit: static_argnames verbatim,
        static_argnums mapped through the signature."""
        if call is None:
            return ()
        names: List[str] = []
        params = [a.arg for a in body.args.args] if not isinstance(
            body, ast.Lambda) else [a.arg for a in body.args.args]
        for kw in self._jit_kwargs(call):
            if kw.arg == "static_argnames":
                names.extend(_literal_str_tuple(kw.value))
            elif kw.arg == "static_argnums":
                names.extend(params[i] for i in _literal_int_tuple(kw.value)
                             if i < len(params))
        return tuple(names)

    def _collect_jit_bodies(self) -> None:
        defs: Dict[str, List[JitBody]] = {}
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(node.name, []).append(node)
                for deco in node.decorator_list:
                    if self.is_jit_entry(deco):
                        call = deco if isinstance(deco, ast.Call) else None
                        self.jit_bodies.append(JitInfo(
                            node,
                            self._donate_of(call) if call else (),
                            self._static_params_of(call, node)))
        for node in ast.walk(self.tree):
            if not (isinstance(node, ast.Call)
                    and self.is_jit_entry(node.func) and node.args):
                continue
            target, donate = node.args[0], self._donate_of(node)
            if isinstance(target, ast.Lambda):
                self.jit_bodies.append(JitInfo(
                    target, donate, self._static_params_of(node, target)))
            elif isinstance(target, ast.Name):
                for d in defs.get(target.id, []):
                    self.jit_bodies.append(JitInfo(
                        d, donate, self._static_params_of(node, d)))
            # f = jax.jit(g, ...): record the bound name for call-site rules.
            parent = self.parent(node)
            if isinstance(parent, ast.Assign):
                for t in parent.targets:
                    if isinstance(t, ast.Name):
                        self.jit_bound_names[t.id] = donate
        # Jit-decorated defs are themselves callable-by-name.
        for info in self.jit_bodies:
            if isinstance(info.body,
                          (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.jit_bound_names.setdefault(info.body.name, info.donate)

    def jitted_call_name(self, call: ast.Call) -> Optional[str]:
        """If ``call`` invokes a known-jitted local binding, its name."""
        if (isinstance(call.func, ast.Name)
                and call.func.id in self.jit_bound_names):
            return call.func.id
        return None


def _is_main_guard(test: ast.AST) -> bool:
    if not (isinstance(test, ast.Compare) and len(test.comparators) == 1):
        return False
    left, right = test.left, test.comparators[0]
    names = {n.id for n in (left, right) if isinstance(n, ast.Name)}
    consts = {c.value for c in (left, right) if isinstance(c, ast.Constant)}
    return "__name__" in names and "__main__" in consts


def _literal_int_tuple(node: ast.AST) -> Tuple[int, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, int):
                out.append(el.value)
        return tuple(out)
    return ()


def _literal_str_tuple(node: ast.AST) -> Tuple[str, ...]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(el.value for el in node.elts
                     if isinstance(el, ast.Constant)
                     and isinstance(el.value, str))
    return ()


def static_names_in(info: JitInfo) -> Set[str]:
    """Names that hold trace-time-static Python values inside a jit body:
    the jit's static params plus anything derived from ``.shape`` (shapes
    are concrete ints under tracing — host math on them is free and
    common in kernel code: ``B, H, N, D = q.shape``)."""
    static: Set[str] = set(info.static_params)
    stmts = (info.body.body if isinstance(info.body.body, list)
             else [info.body.body])
    changed = True
    while changed:  # fixed point: statics derived from statics
        changed = False
        for stmt in stmts:
            for node in ast.walk(stmt):
                if not isinstance(node, ast.Assign):
                    continue
                if not _is_static_expr(node.value, static):
                    continue
                for t in node.targets:
                    elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) \
                        else [t]
                    for el in elts:
                        if isinstance(el, ast.Name) and el.id not in static:
                            static.add(el.id)
                            changed = True
    return static


def _is_static_expr(node: ast.AST, static: Set[str]) -> bool:
    """Expression built only from literals, static names, and ``.shape``
    access — i.e. a compile-time Python value under tracing."""
    if is_literal(node):
        return True
    if isinstance(node, ast.Name):
        return node.id in static
    if isinstance(node, ast.Attribute):
        return node.attr in ("shape", "ndim", "size", "dtype")
    if isinstance(node, ast.Subscript):
        return _is_static_expr(node.value, static)
    if isinstance(node, ast.UnaryOp):
        return _is_static_expr(node.operand, static)
    if isinstance(node, ast.BinOp):
        return (_is_static_expr(node.left, static)
                and _is_static_expr(node.right, static))
    if isinstance(node, ast.Call):
        # len(x) / min(a, b) / np.sqrt(D)-style host math over statics.
        return all(_is_static_expr(a, static) for a in node.args)
    return False


def is_literal(node: ast.AST) -> bool:
    """Constant-foldable expression (safe to call numpy on inside a trace —
    it produces a compile-time constant, not a per-call host transfer)."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, (ast.Tuple, ast.List)):
        return all(is_literal(e) for e in node.elts)
    if isinstance(node, ast.UnaryOp):
        return is_literal(node.operand)
    if isinstance(node, ast.BinOp):
        return is_literal(node.left) and is_literal(node.right)
    return False
