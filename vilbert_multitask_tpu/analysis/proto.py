"""Typestate protocol analysis: statically prove exactly-one-terminal.

The sixth analyzer tier.  Where the lock tier proves ordering and the txn
tier proves atomicity, this tier proves *lifecycles*: every acquired
protocol handle reaches the right number of release events on every
control-flow path — including the exception edges and early-return
unwinds the CFG already models.

A :class:`ProtocolRegistry` declares each protocol as a tiny state
machine over acquire/release verbs, resolved against the classes that
actually declare them in library code (so a test fixture's ``claim``
never widens the real protocol, while fixture projects rooted elsewhere
still register their own providers):

* ``job`` — ``claim -> {ack | nack | release}`` (``serve/queue.py`` and
  its remote twin, composed through ``serve/worker.py`` and
  ``serve/scheduler.py``): the system's load-bearing invariant is that a
  claimed job reaches **exactly one** terminal.
* ``replica`` — ``checkout -> checkin`` (``serve/pool.py``).
* ``thread`` — ``threading.Thread(...).start() -> join()``.
* ``sqlite`` — ``sqlite3.connect() -> close()`` (``with``-managed
  connections release through ``__exit__`` and are never tracked).

Two engines consume the registry:

* a bounded all-paths walk (:meth:`ProtoFlow._verify_job_function`) that
  enumerates acyclic CFG paths from each ``claim`` and counts terminals
  per path, with ``is None`` claim-miss guards refined per branch edge
  and escape analysis (returned / stored / passed-on handles become the
  callee's obligation) — the proof behind **VMT132**; and
* the worklist solver of ``analysis.dataflow`` running a must-held
  domain (join = intersection) whose facts are the handles definitely
  live before each event — a ``raise`` reached with a non-empty fact is
  an exception edge escaping a scope that still owns a handle, the
  flow-sensitive upgrade of VMT117 behind **VMT133**.

Per-function summaries compose through the call graph to a fixed point,
the ``LockFlow`` pattern: ``worker._fail_job`` *is* a job terminal
because every path through it reaches ``queue.nack``, and
``worker._claim`` *is* an acquire because it returns a freshly claimed
handle — callers see the composed verbs with full witness chains.

Two project-level cross-checks ride on the same flow: every
``fault_point("site")`` in library code must be named by a
``FaultRule`` somewhere in tests/ or scripts/ (**VMT134**), and every
job-status string literal must be a state of the ``jobs.status`` machine
the txn tier recovered (**VMT135**, with did-you-mean).

Run generatively (``python -m vilbert_multitask_tpu.analysis proto``)
the tier emits ``PROTOCOL_SURFACE.json``: every protocol with its
states, declaration and acquire sites, composed wrappers with witness
chains, per-function path-proof verdicts, and the fault-site coverage
map — committed and drift-gated (``proto --check`` in check.sh).

Everything here is stdlib-only (the analysis-layer contract).
"""

from __future__ import annotations

import ast
import difflib
import json
from typing import Dict, FrozenSet, Iterator, List, Optional, Set, Tuple

from .cfg import CFG, Block, build_cfg, iter_event_nodes
from .dataflow import ForwardAnalysis, iter_event_facts, solve
from .txn import txn_flow

PROTO_VERSION = 1
MANIFEST_NAME = "PROTOCOL_SURFACE.json"

# Paths that never provide protocol declarations and never host findings:
# test idioms claim-and-drop on purpose.
_NON_LIBRARY_HEADS = ("tests", "scripts")

# Per-function path-walk budget.  Functions here are modest (the worst
# real offender, step_batch, stays well under); a blowup degrades to
# silence, never to wrong findings.
_MAX_PATHS = 600

PROTOCOLS: Dict[str, dict] = {
    "job": {
        "description": "a claimed job reaches exactly one terminal "
                       "(ack / nack / release)",
        "acquire": ("claim",),
        "terminal": ("ack", "nack", "release"),
        "states": ["unclaimed", "claimed", "terminal"],
    },
    "replica": {
        "description": "a checked-out replica is always checked back in",
        "acquire": ("checkout",),
        "terminal": ("checkin",),
        "states": ["ready", "checked_out"],
    },
    "thread": {
        "description": "a started thread is joined before its handle "
                       "is abandoned on an exception path",
        "acquire": ("start",),
        "terminal": ("join",),
        "states": ["created", "started", "joined"],
    },
    "sqlite": {
        "description": "a plain (non-with) sqlite3 connection is closed "
                       "before an exception path abandons it",
        "acquire": ("connect",),
        "terminal": ("close",),
        "states": ["open", "closed"],
    },
}

# Verb -> protocol, for call-site classification.  ``start``/``join``/
# ``connect``/``close`` are deliberately absent: those verbs are too
# generic for name-based matching and resolve through value tracking
# (thread ctor assignments, ``sqlite3.connect``) instead.
_ACQUIRE_VERBS = {"claim": "job", "checkout": "replica"}
_TERMINAL_VERBS = {"ack": "job", "nack": "job", "release": "job",
                   "checkin": "replica"}

_THREAD_CTORS = ("threading.Thread", "threading.Timer")


def _is_library(rel_path: str) -> bool:
    head = rel_path.split("/", 1)[0]
    if head in _NON_LIBRARY_HEADS:
        return False
    base = rel_path.rsplit("/", 1)[-1]
    return not (base.startswith("test_") or base == "conftest.py")


def _witness(path: str, line: int, note: str) -> dict:
    return {"path": path, "line": line, "message": note}


class _Anchor:
    __slots__ = ("lineno", "col_offset")

    def __init__(self, line: int, col: int = 0) -> None:
        self.lineno = line
        self.col_offset = col


# ---------------------------------------------------------------------------
# Per-function facts
# ---------------------------------------------------------------------------

class _FnProto:
    """What one function does to protocol handles, composed to a fixed
    point: ``terminal_params`` maps a parameter name to the witness chain
    proving some path terminates that handle; ``acquire_return`` is set
    when the function's return value is a freshly acquired handle."""

    __slots__ = ("fn", "acquire_calls", "terminal_params", "acquire_return")

    def __init__(self, fn) -> None:
        self.fn = fn
        # [(protocol, verb, line, col)] — direct acquire call sites.
        self.acquire_calls: List[Tuple[str, str, int, int]] = []
        # param name -> (protocol, [witness steps])
        self.terminal_params: Dict[str, Tuple[str, List[dict]]] = {}
        # (protocol, [witness steps]) when returning a fresh handle.
        self.acquire_return: Optional[Tuple[str, List[dict]]] = None


class _MustHeld(ForwardAnalysis):
    """Handles definitely live before each event (must: join = ∩).

    ``classify`` maps an event to its protocol ops; the domain only
    tracks replica/thread/sqlite handles — job claims can legitimately
    outlive a raise (the visibility sweep redelivers), and their
    exactly-one-terminal proof is the path walk's job."""

    def __init__(self, classify) -> None:
        self._classify = classify

    def initial(self) -> FrozenSet[str]:
        return frozenset()

    def join(self, a: FrozenSet[str], b: FrozenSet[str]) -> FrozenSet[str]:
        return a & b

    def transfer(self, event, fact: FrozenSet[str]) -> FrozenSet[str]:
        held = set(fact)
        for op in self._classify(event):
            kind = op[0]
            # Layouts differ: acquire carries its token at op[2],
            # terminal/escape at op[1] (see _classifier's docstring).
            if kind == "acquire" and op[1] != "job" and op[2] is not None:
                held.add(op[2])
            elif kind in ("terminal", "escape", "kill"):
                held.discard(op[1])
        return frozenset(held)


class ProtocolRegistry:
    """Protocol declarations resolved against the project.

    ``providers[verb]`` lists the library classes that declare the verb
    (``DurableQueue.claim``, ``RemoteQueueClient.claim``, ...).  A call
    ``x.claim(...)`` on a statically unknown receiver counts as the job
    protocol's acquire exactly when at least one provider exists — the
    same deliberate over-approximation thread-entry naming uses: missing
    an acquire hides a leaked claim."""

    def __init__(self, project) -> None:
        self.project = project
        self.providers: Dict[str, List[dict]] = {}
        verbs = set(_ACQUIRE_VERBS) | set(_TERMINAL_VERBS)
        for mod in sorted(project.modules.values(), key=lambda m: m.name):
            ctx = mod.ctx
            if not _is_library(ctx.rel_path):
                continue
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.ClassDef):
                    continue
                for stmt in node.body:
                    if isinstance(stmt, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)) \
                            and stmt.name in verbs:
                        self.providers.setdefault(stmt.name, []).append({
                            "method": f"{node.name}.{stmt.name}",
                            "path": ctx.rel_path,
                            "line": stmt.lineno,
                        })

    def acquire_protocol(self, verb: str) -> Optional[str]:
        proto = _ACQUIRE_VERBS.get(verb)
        return proto if proto and verb in self.providers else None

    def terminal_protocol(self, verb: str) -> Optional[str]:
        proto = _TERMINAL_VERBS.get(verb)
        return proto if proto and verb in self.providers else None


# ---------------------------------------------------------------------------
# The flow
# ---------------------------------------------------------------------------

class ProtoFlow:
    """Interprocedural typestate facts over the whole project.

    Built once per project (see :func:`proto_flow`) and consumed by the
    VMT132-135 rules and by :func:`build_proto_surface`.  All finding
    lists hold plain dicts ``{"path", "line", "col", "message"[,
    "flows"]}`` so rules stay thin adapters."""

    def __init__(self, project) -> None:
        self.project = project
        self.cg = project.callgraph
        self.registry = ProtocolRegistry(project)
        # Leaf method name -> qualname iff unique among library functions
        # (the LockFlow by-name fallback for self.* receivers).
        self._mention_cache: Dict[tuple, bool] = {}
        self._unique: Dict[str, Optional[str]] = {}
        for fn in self.cg.functions.values():
            if not _is_library(fn.module.ctx.rel_path):
                continue
            leaf = fn.scope[-1]
            self._unique[leaf] = (
                None if leaf in self._unique else fn.qualname)
        self.summaries: Dict[str, _FnProto] = {}
        for qual in sorted(self.cg.functions):
            fn = self.cg.functions[qual]
            if self._interesting(fn):
                self.summaries[qual] = self._summarize(fn)
        self._compose()
        # Finding dicts, populated by the passes below.
        self.job_findings: List[dict] = []
        self.leak_findings: List[dict] = []
        self.fault_findings: List[dict] = []
        self.frame_findings: List[dict] = []
        self.proof: List[dict] = []
        self.fault_points: List[dict] = []
        self._verify_functions()
        self._check_fault_coverage()
        self._check_terminal_frames()

    # ------------------------------------------------------------ summaries
    _VERBS = (set(_ACQUIRE_VERBS) | set(_TERMINAL_VERBS)
              | {"start", "join", "connect", "close"})

    def _module_mentions(self, mod, words: Set[str]) -> bool:
        """Cheap text prefilter: can ``mod`` possibly contain one of
        ``words`` as an identifier? Saves the per-function AST walk on
        the model/engine bulk, which never touches protocol verbs."""
        key = (id(mod), frozenset(words))
        cached = self._mention_cache.get(key)
        if cached is None:
            src = mod.ctx.source
            cached = any(w in src for w in words)
            self._mention_cache[key] = cached
        return cached

    def _interesting(self, fn) -> bool:
        if not _is_library(fn.module.ctx.rel_path):
            return False
        if not self._module_mentions(fn.module, self._VERBS):
            return False
        for node in self.cg._own_nodes(fn.node):
            if isinstance(node, ast.Attribute) and node.attr in self._VERBS:
                return True
            if isinstance(node, ast.Name) and node.id in self._VERBS:
                return True
        return False

    def _rel_path(self, qual: str) -> str:
        return self.cg.functions[qual].module.ctx.rel_path

    def _display(self, qual: str) -> str:
        mod, scope = qual.split(":", 1)
        return f"{mod}.{scope}"

    def _resolve_call(self, fn, call: ast.Call) -> Optional[str]:
        """Project callee of ``call``, with the by-name fallback for
        unknown receivers (``self.queue.claim`` resolves nowhere, but a
        project-unique ``_fail_job`` does)."""
        qual = self.cg.resolve_callable(
            fn.module, call.func, fn.scope, fn.cls_scope)
        if qual is not None:
            return qual
        func = call.func
        if isinstance(func, ast.Attribute) and not (
                isinstance(func.value, ast.Name)
                and func.value.id == "self"):
            qual = self._unique.get(func.attr)
            if qual is not None and qual != fn.qualname:
                return qual
        return None

    def _thread_vars(self, fn) -> Set[str]:
        """Local names assigned a ``threading.Thread``/``Timer`` ctor."""
        out: Set[str] = set()
        ctx = fn.module.ctx
        for node in self.cg._own_nodes(fn.node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name) \
                    and isinstance(node.value, ast.Call) \
                    and ctx.resolve(node.value.func) in _THREAD_CTORS:
                out.add(node.targets[0].id)
        return out

    def _mentioned_names(self, call: ast.Call) -> Set[str]:
        """Bare names a call touches — its receiver chain plus every
        name inside its arguments (``q.ack(job.id)`` mentions ``job``)."""
        names: Set[str] = set()
        roots: List[ast.AST] = list(call.args)
        roots.extend(kw.value for kw in call.keywords)
        base = call.func
        while isinstance(base, ast.Attribute):
            base = base.value
        roots.append(base)
        for root in roots:
            for node in ast.walk(root):
                if isinstance(node, ast.Name):
                    names.add(node.id)
        return names

    def _call_verbs(self, fn, call: ast.Call
                    ) -> Iterator[Tuple[str, str, str]]:
        """(kind, protocol, verb) protocol meanings of one call node."""
        func = call.func
        verb = func.attr if isinstance(func, ast.Attribute) else (
            func.id if isinstance(func, ast.Name) else None)
        if verb is None:
            return
        proto = self.registry.acquire_protocol(verb)
        if proto is not None:
            yield "acquire", proto, verb
        proto = self.registry.terminal_protocol(verb)
        if proto is not None:
            yield "terminal", proto, verb
        if verb == "join" and isinstance(func, ast.Attribute):
            yield "terminal", "thread", verb
        if verb == "close" and isinstance(func, ast.Attribute):
            yield "terminal", "sqlite", verb

    def _summarize(self, fn) -> _FnProto:
        info = _FnProto(fn)
        ctx = fn.module.ctx
        params = {a.arg for a in fn.node.args.args} - {"self"}
        thread_vars = self._thread_vars(fn)
        acquired_names: Dict[str, str] = {}  # local -> protocol
        for node in self.cg._own_nodes(fn.node):
            if not isinstance(node, ast.Call):
                continue
            for kind, proto, verb in self._call_verbs(fn, node):
                if kind == "acquire":
                    info.acquire_calls.append(
                        (proto, verb, node.lineno, node.col_offset))
                    parent = ctx.parent(node)
                    if isinstance(parent, ast.Assign) \
                            and parent.value is node \
                            and len(parent.targets) == 1 \
                            and isinstance(parent.targets[0], ast.Name):
                        acquired_names[parent.targets[0].id] = proto
                elif kind == "terminal":
                    if proto == "thread" and not (
                            isinstance(node.func, ast.Attribute)
                            and isinstance(node.func.value, ast.Name)
                            and node.func.value.id in (thread_vars
                                                       | params)):
                        continue
                    for name in self._mentioned_names(node) & params:
                        info.terminal_params.setdefault(name, (proto, [
                            _witness(ctx.rel_path, node.lineno,
                                     f"`{verb}` — {proto}-protocol "
                                     f"terminal"),
                        ]))
            # thread acquire: ``t.start()`` on a tracked thread value
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "start" \
                    and isinstance(node.func.value, ast.Name) \
                    and node.func.value.id in thread_vars:
                info.acquire_calls.append(
                    ("thread", "start", node.lineno, node.col_offset))
                acquired_names[node.func.value.id] = "thread"
            # sqlite acquire: plain ``conn = sqlite3.connect(...)``
            parent = ctx.parent(node)
            if ctx.resolve(node.func) == "sqlite3.connect" \
                    and isinstance(parent, ast.Assign) \
                    and parent.value is node \
                    and len(parent.targets) == 1 \
                    and isinstance(parent.targets[0], ast.Name):
                info.acquire_calls.append(
                    ("sqlite", "connect", node.lineno, node.col_offset))
                acquired_names[parent.targets[0].id] = "sqlite"
        # acquire-return seed: ``return <acquire call>`` or ``return x``
        # where x was bound by an acquire in this function.
        for node in self.cg._own_nodes(fn.node):
            if not isinstance(node, ast.Return) or node.value is None:
                continue
            if isinstance(node.value, ast.Call):
                for kind, proto, verb in self._call_verbs(fn, node.value):
                    if kind == "acquire":
                        info.acquire_return = (proto, [_witness(
                            ctx.rel_path, node.lineno,
                            f"returns a freshly `{verb}`-ed "
                            f"{proto} handle")])
            elif isinstance(node.value, ast.Name) \
                    and node.value.id in acquired_names:
                info.acquire_return = (acquired_names[node.value.id], [
                    _witness(ctx.rel_path, node.lineno,
                             f"returns `{node.value.id}`, a fresh "
                             f"{acquired_names[node.value.id]} handle")])
        return info

    # ------------------------------------------------------ composition
    def _callee_param(self, callee_qual: str, call: ast.Call,
                      arg: ast.AST) -> Optional[str]:
        """Name of the callee parameter ``arg`` lands in."""
        callee = self.cg.functions.get(callee_qual)
        if callee is None:
            return None
        params = [a.arg for a in callee.node.args.args]
        if callee.cls_scope and params and params[0] == "self" \
                and isinstance(call.func, ast.Attribute):
            params = params[1:]
        for i, a in enumerate(call.args):
            if a is arg:
                return params[i] if i < len(params) else None
        for kw in call.keywords:
            if kw.value is arg and kw.arg is not None:
                return kw.arg if kw.arg in params else None
        return None

    def _compose(self) -> None:
        """Fixed point: propagate terminal-param and acquire-return
        summaries through call edges (wrapper-of-wrapper chains)."""
        for _ in range(len(self.summaries) + 1):
            changed = False
            for qual in sorted(self.summaries):
                info = self.summaries[qual]
                fn = info.fn
                params = {a.arg for a in fn.node.args.args} - {"self"}
                ctx = fn.module.ctx
                for call in self.cg.own_call_nodes(fn):
                    callee = self._resolve_call(fn, call)
                    if callee is None or callee == qual:
                        continue
                    csum = self.summaries.get(callee)
                    if csum is None:
                        continue
                    # terminal through a wrapper: f(job) where f nacks
                    for arg in list(call.args) + [kw.value
                                                  for kw in call.keywords]:
                        if not isinstance(arg, ast.Name) \
                                or arg.id not in params \
                                or arg.id in info.terminal_params:
                            continue
                        pname = self._callee_param(callee, call, arg)
                        if pname is None \
                                or pname not in csum.terminal_params:
                            continue
                        proto, steps = csum.terminal_params[pname]
                        info.terminal_params[arg.id] = (proto, [
                            _witness(ctx.rel_path, call.lineno,
                                     f"via `{self._display(callee)}`"),
                        ] + steps)
                        changed = True
                    # acquire-return through a wrapper
                    if info.acquire_return is None \
                            and csum.acquire_return is not None:
                        parent = ctx.parent(call)
                        if isinstance(parent, ast.Return) \
                                and parent.value is call:
                            proto, steps = csum.acquire_return
                            info.acquire_return = (proto, [_witness(
                                ctx.rel_path, call.lineno,
                                f"returns `{self._display(callee)}`"
                                f"'s fresh {proto} handle")] + steps)
                            changed = True
            if not changed:
                return

    # ------------------------------------------------ event classification
    def _classifier(self, fn):
        """Per-event protocol ops for one function, memoized by event id.

        Ops (state-independent; the consumers apply them to their own
        domains):

        * ``("acquire", protocol, token|None, line, verb, witness)``
        * ``("terminal", token, line, verb, direct)``
        * ``("escape", token, line)``
        * ``("kill", token, line)`` — never emitted here; the path walk
          synthesizes kills from ``is None`` branch refinement.
        * ``("raise", None, line)`` / ``("return", None, line)``
        """
        ctx = fn.module.ctx
        qual = fn.qualname
        thread_vars = self._thread_vars(fn)
        memo: Dict[int, List[tuple]] = {}

        def classify(event) -> List[tuple]:
            key = id(event)
            if key in memo:
                return memo[key]
            ops: List[tuple] = []
            if isinstance(event, ast.AST):
                terminal_tokens: Set[str] = set()
                acquire_nodes: Set[int] = set()
                for node in iter_event_nodes(event):
                    if not isinstance(node, ast.Call):
                        continue
                    line = node.lineno
                    handled = False
                    for kind, proto, verb in self._call_verbs(fn, node):
                        if kind == "acquire":
                            token = self._binding(ctx, event, node)
                            ops.append(("acquire", proto, token, line,
                                        verb,
                                        _witness(ctx.rel_path, line,
                                                 f"`{verb}` acquires a "
                                                 f"{proto} handle")))
                            acquire_nodes.add(id(node))
                            handled = True
                        elif kind == "terminal":
                            if proto == "thread" and not (
                                    isinstance(node.func, ast.Attribute)
                                    and isinstance(node.func.value,
                                                   ast.Name)):
                                continue
                            for name in self._mentioned_names(node):
                                ops.append(("terminal", name, line, verb,
                                            True))
                                terminal_tokens.add(name)
                            handled = True
                    if not handled:
                        # thread/sqlite acquires + wrapper calls
                        if isinstance(node.func, ast.Attribute) \
                                and node.func.attr == "start" \
                                and isinstance(node.func.value, ast.Name) \
                                and node.func.value.id in thread_vars:
                            ops.append((
                                "acquire", "thread", node.func.value.id,
                                line, "start",
                                _witness(ctx.rel_path, line,
                                         f"`{node.func.value.id}"
                                         f".start()` starts a thread")))
                            terminal_tokens.add(node.func.value.id)
                            continue
                        if ctx.resolve(node.func) == "sqlite3.connect":
                            token = self._binding(ctx, event, node)
                            if token is not None:
                                ops.append((
                                    "acquire", "sqlite", token, line,
                                    "connect",
                                    _witness(ctx.rel_path, line,
                                             "`sqlite3.connect` opens a "
                                             "connection")))
                                acquire_nodes.add(id(node))
                            continue
                        callee = self._resolve_call(fn, node)
                        csum = self.summaries.get(callee) \
                            if callee and callee != qual else None
                        if csum is None:
                            continue
                        if csum.acquire_return is not None:
                            proto, steps = csum.acquire_return
                            token = self._binding(ctx, event, node)
                            ops.append(("acquire", proto, token, line,
                                        self._display(callee),
                                        _witness(ctx.rel_path, line,
                                                 f"`{self._display(callee)}`"
                                                 f" returns a fresh "
                                                 f"{proto} handle")))
                            acquire_nodes.add(id(node))
                        for arg in list(node.args) + [
                                kw.value for kw in node.keywords]:
                            if not isinstance(arg, ast.Name):
                                continue
                            pname = self._callee_param(callee, node, arg)
                            if pname is not None \
                                    and pname in csum.terminal_params:
                                ops.append(("terminal", arg.id, line,
                                            self._display(callee), False))
                                terminal_tokens.add(arg.id)
                # escapes: a bare handle name flowing somewhere we do not
                # model (returned, stored, aliased, passed to a callee
                # with no terminal summary) ends our obligation to track
                # it — under-approximate by design.
                for name, line in self._escaped_names(ctx, event,
                                                      terminal_tokens):
                    ops.append(("escape", name, line))
                if isinstance(event, ast.Raise):
                    ops.append(("raise", None, event.lineno))
                elif isinstance(event, ast.Return):
                    ops.append(("return", None, event.lineno))
            memo[key] = ops
            return ops

        return classify

    @staticmethod
    def _binding(ctx, event, call: ast.Call) -> Optional[str]:
        """Local name an acquire call binds to (None when the handle is
        returned straight through or dropped on the floor)."""
        parent = ctx.parent(call)
        if isinstance(parent, ast.Assign) and parent.value is call \
                and len(parent.targets) == 1 \
                and isinstance(parent.targets[0], ast.Name):
            return parent.targets[0].id
        if isinstance(parent, ast.AnnAssign) and parent.value is call \
                and isinstance(parent.target, ast.Name):
            return parent.target.id
        return None

    @staticmethod
    def _escaped_names(ctx, event, terminal_tokens: Set[str]
                       ) -> Iterator[Tuple[str, int]]:
        """Bare ``Name`` loads whose context gives the value away.

        Attribute reads (``job.id``), comparisons (``job is None``) and
        truthiness tests don't escape; anything else — call argument,
        return value, store target value, subscript, container literal —
        does.  Names already consumed by a terminal call in this same
        event stay with the terminal classification."""
        for node in iter_event_nodes(event):
            if not isinstance(node, ast.Name) \
                    or not isinstance(getattr(node, "ctx", None), ast.Load):
                continue
            if node.id in terminal_tokens:
                continue
            parent = ctx.parent(node)
            if isinstance(parent, (ast.Attribute, ast.Compare)):
                continue
            if isinstance(parent, ast.UnaryOp) \
                    and isinstance(parent.op, ast.Not):
                continue
            yield node.id, getattr(node, "lineno", 0)

    # ------------------------------------------------------ path walking
    def _wrapper_acquires(self, fn) -> Set[str]:
        """Protocols ``fn`` acquires through composed wrappers — a call
        whose callee summary returns a fresh handle (``self._claim()``
        is a job acquire even though the verb is ``_claim``)."""
        out: Set[str] = set()
        for call in self.cg.own_call_nodes(fn):
            callee = self._resolve_call(fn, call)
            if callee is None or callee == fn.qualname:
                continue
            csum = self.summaries.get(callee)
            if csum is not None and csum.acquire_return is not None:
                out.add(csum.acquire_return[0])
        return out

    def _verify_functions(self) -> None:
        # Every library function, not just the verb-mentioning ones the
        # summary prefilter kept: a function whose only acquire is a
        # composed wrapper call (``rep = self._checkout_for_dispatch()``)
        # has no protocol verb in its own text. The text prefilter keeps
        # the bulk of the tree out: a wrapper acquire needs the wrapper's
        # leaf name somewhere in the module source.
        wrapper_leaves = {
            self.cg.functions[q].scope[-1]
            for q, s in self.summaries.items() if s.acquire_return}
        for qual in sorted(self.cg.functions):
            fn = self.cg.functions[qual]
            if not _is_library(fn.module.ctx.rel_path):
                continue
            info = self.summaries.get(qual)
            if info is None and not (
                    wrapper_leaves
                    and self._module_mentions(fn.module, wrapper_leaves)):
                continue
            # A direct acquire verb would have made the function
            # summary-interesting, so un-summarized functions can only
            # acquire through wrappers.
            acquired = ({p for p, _, _, _ in info.acquire_calls}
                        if info else set())
            acquired |= self._wrapper_acquires(fn)
            if not acquired:
                continue
            try:
                cfg = build_cfg(fn.node)
            except RecursionError:  # pragma: no cover — pathological fns
                continue
            classify = self._classifier(fn)
            self._check_exception_leaks(fn, cfg, classify)
            if "job" in acquired:
                self._verify_job_function(fn, cfg, classify)

    # VMT133: must-held handles at a raise, via the worklist solver.
    def _check_exception_leaks(self, fn, cfg: CFG, classify) -> None:
        ctx = fn.module.ctx
        analysis = _MustHeld(classify)
        in_facts = solve(cfg, analysis)
        acquire_site: Dict[str, tuple] = {}
        for blk in cfg.reachable():
            for event in blk.events:
                for op in classify(event):
                    if op[0] == "acquire" and op[1] != "job" \
                            and op[2] is not None \
                            and op[2] not in acquire_site:
                        acquire_site[op[2]] = (op[1], op[3], op[5])
        if not acquire_site:
            return
        seen: Set[tuple] = set()
        for event, fact in iter_event_facts(cfg, analysis, in_facts):
            if not isinstance(event, ast.Raise) or not fact:
                continue
            for token in sorted(fact):
                if token not in acquire_site:
                    continue
                proto, aline, awit = acquire_site[token]
                key = (token, event.lineno)
                if key in seen:
                    continue
                seen.add(key)
                verb = "/".join(PROTOCOLS[proto]["terminal"])
                self.leak_findings.append({
                    "path": ctx.rel_path,
                    "line": event.lineno,
                    "col": event.col_offset + 1,
                    "message": (
                        f"exception path abandons `{token}`, a "
                        f"{proto} handle acquired at line {aline} and "
                        f"never released — every raise that unwinds "
                        f"this scope leaks it; call `{verb}` before "
                        f"re-raising (or hand the handle off first)"),
                    "flows": [[awit,
                               _witness(ctx.rel_path, event.lineno,
                                        f"raise escapes with `{token}` "
                                        f"still held")]],
                })

    # VMT132: per-path terminal counting for job handles.
    def _verify_job_function(self, fn, cfg: CFG, classify) -> None:
        ctx = fn.module.ctx
        if_tests: Dict[int, ast.If] = {}
        for node in self.cg._own_nodes(fn.node):
            if isinstance(node, ast.If):
                if_tests[id(node.test)] = node
        handler_entries = self._handler_entry_blocks(fn, cfg)
        paths = 0
        reported: Set[tuple] = set()
        findings_before = len(self.job_findings)
        # Path state: handles token -> [status, acquire_witness,
        # terminal_witnesses, exc_since_terminal]; statuses: "held",
        # "done", "dead" (claim-miss), "escaped".
        stack: List[tuple] = [(cfg.entry, {}, frozenset(), False)]
        while stack and paths < _MAX_PATHS:
            blk, handles, visited, raised = stack.pop()
            if blk.id in visited:
                continue
            visited = visited | {blk.id}
            handles = {t: list(h) for t, h in handles.items()}
            if blk.id in handler_entries:
                # Crossing an exception edge: a terminal already counted
                # may itself be the statement that raised mid-flight, so
                # one compensating terminal is allowed without a
                # double-terminal report.
                for h in handles.values():
                    if h[0] == "done":
                        h[3] = True
            for event in blk.events:
                for op in classify(event):
                    kind = op[0]
                    if kind == "acquire" and op[1] == "job":
                        token = op[2] if op[2] is not None \
                            else f"<job@{op[3]}>"
                        handles[token] = ["held", op[5], [], False]
                    elif kind == "terminal":
                        h = handles.get(op[1])
                        if h is None:
                            continue
                        wit = _witness(ctx.rel_path, op[2],
                                       f"terminal `{op[3]}`")
                        if h[0] == "held" or (h[0] == "done" and h[3]):
                            h[0], h[3] = "done", False
                            h[2].append(wit)
                        elif h[0] == "done" and op[4]:
                            key = ("double", op[1],
                                   h[2][-1]["line"], op[2])
                            if key not in reported:
                                reported.add(key)
                                self.job_findings.append({
                                    "path": ctx.rel_path,
                                    "line": op[2],
                                    "col": 1,
                                    "message": (
                                        f"double terminal for claimed "
                                        f"job `{op[1]}`: this path "
                                        f"already reached "
                                        f"`{h[2][-1]['message']}` at "
                                        f"line {h[2][-1]['line']} — a "
                                        f"second ack/nack/release "
                                        f"corrupts the queue row's "
                                        f"lifecycle"),
                                    "flows": [[h[1]] + h[2] + [wit]],
                                })
                            h[2].append(wit)
                    elif kind == "escape":
                        h = handles.get(op[1])
                        if h is not None and h[0] == "held":
                            h[0] = "escaped"
                    elif kind == "raise":
                        raised = True
            if blk is cfg.exit or not blk.succs:
                paths += 1
                for token in sorted(handles):
                    hstate, awit, terms, _ = handles[token]
                    if hstate != "held":
                        continue
                    key = ("leak", token, awit["line"], raised)
                    if key in reported:
                        continue
                    reported.add(key)
                    how = ("unwinds on an exception" if raised
                           else "returns")
                    self.job_findings.append({
                        "path": ctx.rel_path,
                        "line": awit["line"],
                        "col": 1,
                        "message": (
                            f"leaked claim: a path from this `claim` "
                            f"{how} without ever reaching ack/nack/"
                            f"release for `{token}` — the job stays "
                            f"inflight until the visibility sweep "
                            f"guesses, instead of the protocol "
                            f"deciding"),
                        "flows": [[awit,
                                   _witness(ctx.rel_path,
                                            self._exit_line(fn, blk),
                                            f"path {how} with `{token}`"
                                            f" still claimed")]],
                    })
                continue
            succs = blk.succs
            refine = self._branch_refinement(blk, if_tests)
            for i, succ in enumerate(reversed(succs)):
                idx = len(succs) - 1 - i
                nh = {t: list(h) for t, h in handles.items()}
                if refine is not None:
                    token, kill_on_true = refine
                    h = nh.get(token)
                    if h is not None and h[0] == "held" and (
                            (idx == 0) == kill_on_true):
                        h[0] = "dead"
                stack.append((succ, nh, visited, raised))
        verdict = "exactly-one" if len(self.job_findings) \
            == findings_before else "violations"
        if paths >= _MAX_PATHS:
            verdict = "path-capped"
        self.proof.append({
            "function": self._display(fn.qualname),
            "path": ctx.rel_path,
            "paths": paths,
            "verdict": verdict,
        })

    @staticmethod
    def _exit_line(fn, blk: Block) -> int:
        for event in reversed(blk.events):
            line = getattr(event, "lineno", None)
            if line:
                return line
        return getattr(fn.node, "lineno", 1)

    @staticmethod
    def _handler_entry_blocks(fn, cfg: CFG) -> Set[int]:
        firsts: Set[int] = set()
        for node in ast.walk(fn.node):
            if not isinstance(node, ast.Try):
                continue
            for handler in node.handlers:
                if handler.type is not None:
                    firsts.add(id(handler.type))
                elif handler.body:
                    firsts.add(id(handler.body[0]))
        out: Set[int] = set()
        for blk in cfg.blocks:
            if any(id(e) in firsts for e in blk.events):
                out.add(blk.id)
        return out

    @staticmethod
    def _branch_refinement(blk: Block, if_tests: Dict[int, ast.If]
                           ) -> Optional[Tuple[str, bool]]:
        """(token, kill_on_true_branch) for claim-miss guards: after
        ``if job is None:`` the true branch has no handle to terminate."""
        if len(blk.succs) < 2 or not blk.events:
            return None
        test = blk.events[-1]
        if id(test) not in if_tests:
            return None
        if isinstance(test, ast.Compare) and len(test.ops) == 1 \
                and isinstance(test.left, ast.Name) \
                and len(test.comparators) == 1 \
                and isinstance(test.comparators[0], ast.Constant) \
                and test.comparators[0].value is None:
            if isinstance(test.ops[0], ast.Is):
                return test.left.id, True
            if isinstance(test.ops[0], ast.IsNot):
                return test.left.id, False
        if isinstance(test, ast.Name):
            return test.id, False
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not) \
                and isinstance(test.operand, ast.Name):
            return test.operand.id, True
        return None

    # ------------------------------------------------- VMT134 fault sites
    def _check_fault_coverage(self) -> None:
        rules: List[dict] = []
        sites: List[dict] = []
        for mod in sorted(self.project.modules.values(),
                          key=lambda m: m.name):
            ctx = mod.ctx
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.Call):
                    continue
                name = node.func.attr \
                    if isinstance(node.func, ast.Attribute) else (
                        node.func.id if isinstance(node.func, ast.Name)
                        else None)
                if name == "fault_point" and node.args \
                        and isinstance(node.args[0], ast.Constant) \
                        and isinstance(node.args[0].value, str) \
                        and _is_library(ctx.rel_path):
                    sites.append({"site": node.args[0].value,
                                  "path": ctx.rel_path,
                                  "line": node.lineno,
                                  "col": node.col_offset + 1})
                elif name == "FaultRule":
                    pattern = None
                    if node.args and isinstance(node.args[0], ast.Constant) \
                            and isinstance(node.args[0].value, str):
                        pattern = node.args[0].value
                    for kw in node.keywords:
                        if kw.arg == "site" \
                                and isinstance(kw.value, ast.Constant) \
                                and isinstance(kw.value.value, str):
                            pattern = kw.value.value
                    if pattern is not None:
                        rules.append({"pattern": pattern,
                                      "path": ctx.rel_path,
                                      "line": node.lineno})

        def covers(pattern: str, site: str) -> bool:
            if pattern.endswith("*"):
                return site.startswith(pattern[:-1])
            return pattern == site

        for site in sorted(sites, key=lambda s: (s["path"], s["line"])):
            covered = sorted(
                ({"pattern": r["pattern"], "path": r["path"],
                  "line": r["line"]}
                 for r in rules if covers(r["pattern"], site["site"])),
                key=lambda c: (c["path"], c["line"]))
            self.fault_points.append({
                "site": site["site"],
                "path": site["path"],
                "line": site["line"],
                "covered_by": covered,
            })
            if not covered:
                self.fault_findings.append({
                    "path": site["path"],
                    "line": site["line"],
                    "col": site["col"],
                    "message": (
                        f"fault site `{site['site']}` is named by no "
                        f"FaultPlan/FaultRule anywhere in tests/ or "
                        f"scripts/ — chaos coverage silently drifted; "
                        f"add a rule that injects here (or a `prefix.*`"
                        f" rule that matches)"),
                })

    # --------------------------------------------- VMT135 terminal frames
    def _check_terminal_frames(self) -> None:
        machine = txn_flow(self.project).state_machines.get(
            "jobs", {}).get("status")
        if machine is None:
            return
        values = [v for v in machine["values"] if v is not None]
        for mod in sorted(self.project.modules.values(),
                          key=lambda m: m.name):
            ctx = mod.ctx
            if not _is_library(ctx.rel_path):
                continue
            for lit, node in self._status_literals(ctx):
                if lit in values:
                    continue
                hint = difflib.get_close_matches(lit, values, n=1,
                                                 cutoff=0.6)
                suffix = (f"; did you mean '{hint[0]}'?" if hint
                          else "")
                self.frame_findings.append({
                    "path": ctx.rel_path,
                    "line": node.lineno,
                    "col": node.col_offset + 1,
                    "message": (
                        f"job-status string '{lit}' is not a state of "
                        f"the recovered jobs.status machine "
                        f"({', '.join(repr(v) for v in values)}) — a "
                        f"terminal frame or status check drifting from "
                        f"the durable state machine compares against "
                        f"nothing{suffix}"),
                })

    @staticmethod
    def _status_literals(ctx) -> Iterator[Tuple[str, ast.AST]]:
        """String literals used as a job *status*: compared against a
        ``status`` name/attribute, stored under a ``"status"`` dict key,
        or assigned to a ``status`` slot."""

        def is_status(expr: ast.AST) -> bool:
            return (isinstance(expr, ast.Name) and expr.id == "status") \
                or (isinstance(expr, ast.Attribute)
                    and expr.attr == "status")

        def consts(expr: ast.AST) -> Iterator[ast.Constant]:
            if isinstance(expr, ast.Constant) \
                    and isinstance(expr.value, str):
                yield expr
            elif isinstance(expr, ast.IfExp):
                yield from consts(expr.body)
                yield from consts(expr.orelse)
            elif isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
                for elt in expr.elts:
                    yield from consts(elt)

        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Compare) and len(node.ops) == 1 \
                    and isinstance(node.ops[0], (ast.Eq, ast.NotEq,
                                                 ast.In, ast.NotIn)) \
                    and is_status(node.left):
                for c in consts(node.comparators[0]):
                    yield c.value, c
            elif isinstance(node, ast.Dict):
                for key, value in zip(node.keys, node.values):
                    if isinstance(key, ast.Constant) \
                            and key.value == "status" and value is not None:
                        for c in consts(value):
                            yield c.value, c
            elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and is_status(node.targets[0]):
                for c in consts(node.value):
                    yield c.value, c


def proto_flow(project) -> ProtoFlow:
    flow = getattr(project, "_proto_flow", None)
    if flow is None:
        flow = ProtoFlow(project)
        project._proto_flow = flow
    return flow


# ---------------------------------------------------------------------------
# The committed surface
# ---------------------------------------------------------------------------

def build_proto_surface(project) -> dict:
    """The protocol surface: every protocol with its states and sites,
    the composed wrappers with witness chains, per-function path proofs,
    and the fault coverage map.  Deterministic by construction (sorted
    everywhere, no timestamps) so the rendering is byte-stable."""
    flow = proto_flow(project)
    protocols: Dict[str, dict] = {}
    for name in sorted(PROTOCOLS):
        decl = PROTOCOLS[name]
        entry = {
            "description": decl["description"],
            "states": list(decl["states"]),
            "acquire_verbs": sorted(decl["acquire"]),
            "terminal_verbs": sorted(decl["terminal"]),
            "declared_by": sorted(
                (p for verb in decl["acquire"] + decl["terminal"]
                 for p in flow.registry.providers.get(verb, ())),
                key=lambda p: (p["path"], p["line"])),
            "acquire_sites": [],
            "wrappers": {"acquire": [], "terminal": []},
        }
        protocols[name] = entry
    for qual in sorted(flow.summaries):
        info = flow.summaries[qual]
        rel = flow._rel_path(qual)
        fn_name = flow._display(qual)
        for proto, verb, line, _col in sorted(info.acquire_calls,
                                              key=lambda a: a[2]):
            protocols[proto]["acquire_sites"].append(
                {"function": fn_name, "path": rel, "line": line,
                 "verb": verb})
        if info.acquire_return is not None:
            proto, steps = info.acquire_return
            protocols[proto]["wrappers"]["acquire"].append(
                {"function": fn_name, "witness": steps})
        for pname in sorted(info.terminal_params):
            proto, steps = info.terminal_params[pname]
            protocols[proto]["wrappers"]["terminal"].append(
                {"function": fn_name, "param": pname, "witness": steps})
    surface = {
        "version": PROTO_VERSION,
        "generator": "vmtlint proto",
        "protocols": protocols,
        "proof": sorted(flow.proof,
                        key=lambda p: (p["path"], p["function"])),
        "fault_points": flow.fault_points,
        "counts": {
            "protocols": len(protocols),
            "acquire_sites": sum(len(p["acquire_sites"])
                                 for p in protocols.values()),
            "wrappers": sum(len(p["wrappers"]["acquire"])
                            + len(p["wrappers"]["terminal"])
                            for p in protocols.values()),
            "functions_proved": len(flow.proof),
            "fault_points": len(flow.fault_points),
        },
    }
    return surface


def render_proto_surface(surface: dict) -> str:
    return json.dumps(surface, indent=2, sort_keys=True) + "\n"


def diff_proto_surface(committed: Optional[dict], fresh: dict
                       ) -> List[str]:
    """Human-readable drift between the committed manifest and a fresh
    build — empty when they agree."""
    if committed is None:
        return [f"{MANIFEST_NAME} missing — run `vmtlint proto` and "
                f"commit it"]
    msgs: List[str] = []
    if committed.get("version") != fresh.get("version"):
        msgs.append(f"manifest version drifted: committed "
                    f"{committed.get('version')!r}, tree expects "
                    f"{fresh.get('version')!r}")
        return msgs
    cp = committed.get("protocols", {})
    fp = fresh.get("protocols", {})
    for name in sorted(set(cp) | set(fp)):
        if name not in cp:
            msgs.append(f"protocol `{name}` is new in the tree")
            continue
        if name not in fp:
            msgs.append(f"protocol `{name}` is gone from the tree")
            continue
        csites = {(s["path"], s["line"], s["verb"])
                  for s in cp[name].get("acquire_sites", [])}
        fsites = {(s["path"], s["line"], s["verb"])
                  for s in fp[name].get("acquire_sites", [])}
        for path, line, verb in sorted(fsites - csites):
            msgs.append(f"`{name}` acquire site is new: `{verb}` at "
                        f"{path}:{line}")
        for path, line, verb in sorted(csites - fsites):
            msgs.append(f"`{name}` acquire site is gone: `{verb}` at "
                        f"{path}:{line}")
    csites = {(s["site"], s["path"]) for s in
              committed.get("fault_points", [])}
    fsites = {(s["site"], s["path"]) for s in fresh.get("fault_points", [])}
    for site, path in sorted(fsites - csites):
        msgs.append(f"fault site `{site}` ({path}) is new in the tree")
    for site, path in sorted(csites - fsites):
        msgs.append(f"fault site `{site}` ({path}) is gone from the tree")
    cverd = {p["function"]: p["verdict"]
             for p in committed.get("proof", [])}
    fverd = {p["function"]: p["verdict"] for p in fresh.get("proof", [])}
    for fn_name in sorted(set(cverd) | set(fverd)):
        if cverd.get(fn_name) != fverd.get(fn_name):
            msgs.append(f"proof verdict for `{fn_name}` drifted: "
                        f"{cverd.get(fn_name)!r} -> "
                        f"{fverd.get(fn_name)!r}")
    if not msgs and committed != fresh:
        msgs.append("manifest metadata drifted (witness lines moved?)")
    return msgs


# ---------------------------------------------------------------------------
# SARIF rendering
# ---------------------------------------------------------------------------

def _sarif_loc(w: dict) -> dict:
    return {
        "physicalLocation": {
            "artifactLocation": {"uri": w["path"]},
            "region": {"startLine": max(1, int(w.get("line", 1)))},
        },
        "message": {"text": w.get("message", "")},
    }


def _sarif_flow(steps: List[dict]) -> dict:
    return {"threadFlows": [{
        "locations": [{"location": _sarif_loc(s)} for s in steps],
    }]}


def render_proto_surface_sarif(surface: dict) -> str:
    """The surface as SARIF note-level results: one per acquire site
    (with the composed wrapper witnesses as codeFlows) and one per
    fault site."""
    results: List[dict] = []
    for name in sorted(surface.get("protocols", {})):
        proto = surface["protocols"][name]
        wrapper_flows = [
            _sarif_flow(w["witness"])
            for group in ("acquire", "terminal")
            for w in proto["wrappers"][group] if w.get("witness")
        ]
        for site in proto.get("acquire_sites", []):
            result = {
                "ruleId": "PROTO-SURFACE",
                "level": "note",
                "message": {"text": (
                    f"{name} protocol acquire `{site['verb']}` in "
                    f"`{site['function']}`")},
                "locations": [_sarif_loc({
                    "path": site["path"], "line": site["line"],
                    "message": f"`{site['verb']}` acquire"})],
            }
            if wrapper_flows:
                result["codeFlows"] = wrapper_flows
            results.append(result)
    for site in surface.get("fault_points", []):
        covered = ", ".join(c["pattern"] for c in site["covered_by"]) \
            or "NOTHING"
        results.append({
            "ruleId": "PROTO-FAULT-POINT",
            "level": "note",
            "message": {"text": (
                f"fault site `{site['site']}` covered by: {covered}")},
            "locations": [_sarif_loc({
                "path": site["path"], "line": site["line"],
                "message": f"fault_point(\"{site['site']}\")"})],
        })
    doc = {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "vmtlint-proto",
                "informationUri": "",
                "rules": [
                    {"id": "PROTO-SURFACE",
                     "shortDescription": {
                         "text": "protocol acquire site"}},
                    {"id": "PROTO-FAULT-POINT",
                     "shortDescription": {
                         "text": "fault-injection site coverage"}},
                ],
            }},
            "results": results,
        }],
    }
    return json.dumps(doc, indent=2, sort_keys=True) + "\n"
