import sys

from vilbert_multitask_tpu.analysis.cli import main

sys.exit(main())
