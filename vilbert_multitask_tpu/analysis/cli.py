"""vmtlint CLI: ``python -m vilbert_multitask_tpu.analysis [paths...]``.

Exit codes: 0 clean (new findings only at severities below the gate),
1 findings at/above the gate (``error`` by default, everything with
``--strict``), 2 usage/config errors. Stale baseline entries fail a
``--strict`` run so the baseline file shrinks as debt is paid.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional, Sequence

from vilbert_multitask_tpu.analysis import baseline as bl
from vilbert_multitask_tpu.analysis import report
from vilbert_multitask_tpu.analysis.config import load_config
from vilbert_multitask_tpu.analysis.core import analyze_paths, iter_python_files
from vilbert_multitask_tpu.analysis.rules import RULES, default_rules


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m vilbert_multitask_tpu.analysis",
        description="JAX-aware static analysis for this repo's failure "
                    "modes (host transfers in jit, recompile triggers, "
                    "donation reuse, bench-timing hazards, ...)")
    p.add_argument("paths", nargs="*",
                   help="files/dirs to scan (default: [tool.vmtlint] paths)")
    p.add_argument("--strict", action="store_true",
                   help="fail on warnings and stale baseline entries too")
    p.add_argument("--changed", nargs="?", const="HEAD", default=None,
                   metavar="REV",
                   help="scan only files changed vs REV (default HEAD) plus "
                        "their reverse-import closure and the changed "
                        "files' own imports; falls back to a full scan "
                        "when the closure exceeds half the project or "
                        "nothing relevant changed")
    p.add_argument("--baseline", default=None, metavar="FILE",
                   help="baseline file of grandfathered findings "
                        "(default: [tool.vmtlint] baseline)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any configured baseline")
    p.add_argument("--write-baseline", default=None, metavar="FILE",
                   help="write current findings as a new baseline and exit 0")
    p.add_argument("--prune-baseline", action="store_true",
                   help="rewrite the baseline dropping stale entries for "
                        "scanned files (keeps justifications) and exit 0")
    p.add_argument("--format", default=None, dest="fmt",
                   choices=("human", "json", "sarif"),
                   help="output format (default: human)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="alias for --format json")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule registry and exit")
    return p


def _changed_subset(paths: Sequence[str], root: str,
                    exclude: Sequence[str], rev: str
                    ) -> Optional[List[str]]:
    """The ``--changed`` scan set (absolute paths), or None for a full
    scan — when git is unavailable, nothing relevant changed, or the
    import closure exceeds half the project (at which point the subset
    machinery costs more than it saves and cross-module blind spots
    stop being worth it)."""
    import subprocess

    from vilbert_multitask_tpu.analysis.graph import import_closure

    try:
        proc = subprocess.run(
            ["git", "diff", "--name-only", rev, "--"],
            cwd=root, capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.SubprocessError):
        return None
    if proc.returncode != 0:
        print(f"vmtlint: --changed: git diff failed "
              f"({proc.stderr.strip().splitlines()[:1]}); full scan",
              file=sys.stderr)
        return None
    changed = {ln.strip() for ln in proc.stdout.splitlines() if ln.strip()}
    abs_of = {
        os.path.relpath(os.path.abspath(p), root).replace(os.sep, "/"): p
        for p in iter_python_files(paths, exclude=exclude)}
    seeds = changed & set(abs_of)
    if not seeds:
        return None
    sources = {}
    for rel, path in abs_of.items():
        try:
            with open(path, "r", encoding="utf-8") as f:
                sources[rel] = f.read()
        except OSError:
            continue
    closure = import_closure(sources, seeds)
    if len(closure) > len(abs_of) / 2:
        print(f"vmtlint: --changed: closure is {len(closure)}/"
              f"{len(abs_of)} files; full scan", file=sys.stderr)
        return None
    return [abs_of[rel] for rel in sorted(closure) if rel in abs_of]


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        for cls in RULES:
            print(f"{cls.id}  {cls.name:24s} [{cls.severity}] "
                  f"{cls.description}")
        return 0

    cfg, root = load_config(os.getcwd())
    root = root or os.getcwd()
    paths = list(args.paths) or [
        p if os.path.isabs(p) else os.path.join(root, p) for p in cfg.paths]
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(f"vmtlint: no such path(s): {', '.join(missing)}",
              file=sys.stderr)
        return 2

    partial = False
    if args.changed is not None:
        subset = _changed_subset(paths, root, cfg.exclude, args.changed)
        if subset is not None:
            paths, partial = subset, True

    rules = default_rules(cfg.severity, cfg.rule_paths)
    if partial:
        # A subset scan cannot prove project-wide absences (e.g. VMT122's
        # "never read anywhere") — rules that honor the flag degrade those
        # directions instead of reporting false drift.
        for r in rules:
            if hasattr(r, "partial_scan"):
                r.partial_scan = True
    findings = analyze_paths(paths, root=root, rules=rules,
                             exclude=cfg.exclude,
                             library_roots=cfg.library_roots,
                             layers=cfg.layers)
    scanned = {
        os.path.relpath(os.path.abspath(p), root).replace(os.sep, "/")
        for p in iter_python_files(paths, exclude=cfg.exclude)}
    files_scanned = len(scanned)

    if args.write_baseline:
        bl.write_baseline(args.write_baseline, findings)
        print(f"vmtlint: wrote {len(findings)} finding(s) to "
              f"{args.write_baseline}; add a one-line justification to "
              f"each entry", file=sys.stderr)
        return 0

    baseline_path = None
    if not args.no_baseline:
        baseline_path = args.baseline or (
            os.path.join(root, cfg.baseline) if cfg.baseline else None)
    baseline = {}
    if baseline_path and os.path.exists(baseline_path):
        try:
            baseline = bl.load_baseline(baseline_path)
        except (ValueError, OSError) as e:
            print(f"vmtlint: bad baseline: {e}", file=sys.stderr)
            return 2
    elif args.baseline:  # explicitly requested but absent → usage error
        print(f"vmtlint: baseline not found: {args.baseline}",
              file=sys.stderr)
        return 2

    new, baselined, stale = bl.split_baselined(findings, baseline)
    # Stale = "the grandfathered finding is gone" — only judgeable for
    # files this run actually scanned; a subset scan must not condemn
    # entries for files outside it.
    stale = [fp for fp in stale
             if baseline[fp].get("path") in scanned]

    if args.prune_baseline:
        if not baseline_path or not os.path.exists(baseline_path):
            print("vmtlint: --prune-baseline needs an existing baseline",
                  file=sys.stderr)
            return 2
        bl.prune_baseline(baseline_path, stale)
        noun = "entry" if len(stale) == 1 else "entries"
        print(f"vmtlint: pruned {len(stale)} stale baseline {noun} from "
              f"{baseline_path}", file=sys.stderr)
        return 0

    fmt = args.fmt or ("json" if args.as_json else "human")
    render = {"human": report.render_human, "json": report.render_json,
              "sarif": report.render_sarif}[fmt]
    out = render(new, baselined, stale, files_scanned)
    if out:
        print(out)

    gate: List = [f for f in new if f.severity == "error"]
    if args.strict or cfg.fail_on == "warning":
        gate = list(new)
        if stale and args.strict:
            return 1
    return 1 if gate else 0


if __name__ == "__main__":
    sys.exit(main())
