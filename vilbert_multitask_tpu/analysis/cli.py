"""vmtlint CLI: ``python -m vilbert_multitask_tpu.analysis [paths...]``.

Exit codes: 0 clean (new findings only at severities below the gate),
1 findings at/above the gate (``error`` by default, everything with
``--strict``), 2 usage/config errors. Stale baseline entries fail a
``--strict`` run so the baseline file shrinks as debt is paid.
"""

from __future__ import annotations

import argparse
import ast
import json
import os
import sys
from typing import List, Optional, Sequence, Set, Tuple

from vilbert_multitask_tpu.analysis import baseline as bl
from vilbert_multitask_tpu.analysis import report
from vilbert_multitask_tpu.analysis.config import load_config
from vilbert_multitask_tpu.analysis.core import analyze_paths, iter_python_files
from vilbert_multitask_tpu.analysis.rules import RULES, default_rules


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m vilbert_multitask_tpu.analysis",
        description="JAX-aware static analysis for this repo's failure "
                    "modes (host transfers in jit, recompile triggers, "
                    "donation reuse, bench-timing hazards, ...)")
    p.add_argument("paths", nargs="*",
                   help="files/dirs to scan (default: [tool.vmtlint] paths)")
    p.add_argument("--strict", action="store_true",
                   help="fail on warnings and stale baseline entries too")
    p.add_argument("--changed", nargs="?", const="HEAD", default=None,
                   metavar="REV",
                   help="scan only files changed vs REV (default HEAD) plus "
                        "their reverse-import closure and the changed "
                        "files' own imports; falls back to a full scan "
                        "when the closure exceeds half the project or "
                        "nothing relevant changed")
    p.add_argument("--baseline", default=None, metavar="FILE",
                   help="baseline file of grandfathered findings "
                        "(default: [tool.vmtlint] baseline)")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore any configured baseline")
    p.add_argument("--write-baseline", default=None, metavar="FILE",
                   help="write current findings as a new baseline and exit 0")
    p.add_argument("--prune-baseline", action="store_true",
                   help="rewrite the baseline dropping stale entries for "
                        "scanned files (keeps justifications) and exit 0")
    p.add_argument("--check", action="store_true",
                   help="with --prune-baseline: don't rewrite — fail "
                        "(exit 1) if the baseline carries stale "
                        "fingerprints, so fixed findings can't linger "
                        "as dead suppressions (the CI mode)")
    p.add_argument("--format", default=None, dest="fmt",
                   choices=("human", "json", "sarif"),
                   help="output format (default: human)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="alias for --format json")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule registry and exit")
    return p


def _parse_name_status(output: str) -> Tuple[Set[str], Set[str]]:
    """``git diff --name-status -M`` lines → (paths that exist now and
    changed, old paths that no longer exist: deletions + rename
    sources)."""
    changed: Set[str] = set()
    removed: Set[str] = set()
    for line in output.splitlines():
        parts = line.rstrip("\n").split("\t")
        if len(parts) < 2 or not parts[0]:
            continue
        code = parts[0][0]
        if code in ("R", "C") and len(parts) >= 3:
            # R<score>\told\tnew — the new path is scanned; for a rename
            # the old path is gone and its findings must go with it.
            changed.add(parts[2])
            if code == "R":
                removed.add(parts[1])
        elif code == "D":
            removed.add(parts[1])
        else:  # M, A, T, U ...
            changed.add(parts[1])
    return changed, removed


def _importers_of(sources: dict, removed_mods: Set[str]) -> Set[str]:
    """Current files importing any removed module (prefix-overlapping
    dotted names, over-approximate on purpose: a module that referenced
    the deleted/renamed file must be rescanned — its cross-module
    findings may have shifted)."""
    out: Set[str] = set()
    for rel, src in sources.items():
        try:
            tree = ast.parse(src)
        except SyntaxError:
            continue
        names: List[str] = []
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                names.extend(a.name for a in node.names)
            elif isinstance(node, ast.ImportFrom) and node.module:
                names.append(node.module)
                names.extend(f"{node.module}.{a.name}"
                             for a in node.names)
        if any(n == m or n.startswith(m + ".") or m.startswith(n + ".")
               for n in names for m in removed_mods):
            out.add(rel)
    return out


def _changed_subset(paths: Sequence[str], root: str,
                    exclude: Sequence[str], rev: str
                    ) -> Optional[Tuple[List[str], Set[str]]]:
    """The ``--changed`` scan: (absolute paths to scan, rel paths removed
    vs REV), or None for a full scan — when git is unavailable, nothing
    relevant changed, or the import closure exceeds half the project (at
    which point the subset machinery costs more than it saves and
    cross-module blind spots stop being worth it).

    Renames and deletions are first-class (``--name-status -M``): the
    rename target joins the scan set, importers of a removed module are
    rescanned (the symbols they referenced moved or died), and the
    removed rel-paths flow back so baseline entries anchored in them go
    stale instead of lingering forever."""
    import subprocess

    from vilbert_multitask_tpu.analysis.graph import (import_closure,
                                                      module_name_for)

    try:
        proc = subprocess.run(
            ["git", "diff", "--name-status", "-M", rev, "--"],
            cwd=root, capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.SubprocessError):
        return None
    if proc.returncode != 0:
        print(f"vmtlint: --changed: git diff failed "
              f"({proc.stderr.strip().splitlines()[:1]}); full scan",
              file=sys.stderr)
        return None
    changed, removed = _parse_name_status(proc.stdout)
    abs_of = {
        os.path.relpath(os.path.abspath(p), root).replace(os.sep, "/"): p
        for p in iter_python_files(paths, exclude=exclude)}
    seeds = changed & set(abs_of)
    sources = {}
    for rel, path in abs_of.items():
        try:
            with open(path, "r", encoding="utf-8") as f:
                sources[rel] = f.read()
        except OSError:
            continue
    removed_mods = {module_name_for(rel) for rel in removed
                    if rel.endswith(".py")}
    if removed_mods:
        seeds |= _importers_of(sources, removed_mods)
    if not seeds:
        # Nothing scannable changed. A pure deletion still needs a full
        # scan so its baseline entries can be judged stale.
        return None
    closure = import_closure(sources, seeds & set(sources))
    if len(closure) > len(abs_of) / 2:
        print(f"vmtlint: --changed: closure is {len(closure)}/"
              f"{len(abs_of)} files; full scan", file=sys.stderr)
        return None
    subset = [abs_of[rel] for rel in sorted(closure) if rel in abs_of]
    return subset, removed


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "surface":
        return _surface_main(argv[1:])
    if argv and argv[0] == "txn":
        return _txn_main(argv[1:])
    if argv and argv[0] == "proto":
        return _proto_main(argv[1:])
    if argv and argv[0] == "exc":
        return _exc_main(argv[1:])
    args = build_parser().parse_args(argv)
    if args.list_rules:
        for cls in RULES:
            print(f"{cls.id}  {cls.name:24s} [{cls.severity}] "
                  f"{cls.description}")
        return 0

    cfg, root = load_config(os.getcwd())
    root = root or os.getcwd()
    paths = list(args.paths) or [
        p if os.path.isabs(p) else os.path.join(root, p) for p in cfg.paths]
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(f"vmtlint: no such path(s): {', '.join(missing)}",
              file=sys.stderr)
        return 2

    partial = False
    removed_rel: Set[str] = set()
    if args.changed is not None:
        subset = _changed_subset(paths, root, cfg.exclude, args.changed)
        if subset is not None:
            paths, partial = subset[0], True
            removed_rel = subset[1]

    rules = default_rules(cfg.severity, cfg.rule_paths)
    if partial:
        # A subset scan cannot prove project-wide absences (e.g. VMT122's
        # "never read anywhere") — rules that honor the flag degrade those
        # directions instead of reporting false drift.
        for r in rules:
            if hasattr(r, "partial_scan"):
                r.partial_scan = True
    findings = analyze_paths(paths, root=root, rules=rules,
                             exclude=cfg.exclude,
                             library_roots=cfg.library_roots,
                             layers=cfg.layers)
    scanned = {
        os.path.relpath(os.path.abspath(p), root).replace(os.sep, "/")
        for p in iter_python_files(paths, exclude=cfg.exclude)}
    files_scanned = len(scanned)

    if args.write_baseline:
        bl.write_baseline(args.write_baseline, findings)
        print(f"vmtlint: wrote {len(findings)} finding(s) to "
              f"{args.write_baseline}; add a one-line justification to "
              f"each entry", file=sys.stderr)
        return 0

    baseline_path = None
    if not args.no_baseline:
        baseline_path = args.baseline or (
            os.path.join(root, cfg.baseline) if cfg.baseline else None)
    baseline = {}
    if baseline_path and os.path.exists(baseline_path):
        try:
            baseline = bl.load_baseline(baseline_path)
        except (ValueError, OSError) as e:
            print(f"vmtlint: bad baseline: {e}", file=sys.stderr)
            return 2
    elif args.baseline:  # explicitly requested but absent → usage error
        print(f"vmtlint: baseline not found: {args.baseline}",
              file=sys.stderr)
        return 2

    new, baselined, stale = bl.split_baselined(findings, baseline)

    # Stale = "the grandfathered finding is gone" — judgeable for files
    # this run scanned, files removed vs the --changed rev, and (on a
    # full scan) files that no longer exist on disk; a subset scan must
    # not condemn entries for live files outside it.
    def _entry_stale(fp: str) -> bool:
        rel = baseline[fp].get("path", "")
        if rel in scanned or rel in removed_rel:
            return True
        return (not partial and bool(rel)
                and not os.path.exists(os.path.join(root, rel)))

    stale = [fp for fp in stale if _entry_stale(fp)]

    if args.prune_baseline:
        if not baseline_path or not os.path.exists(baseline_path):
            print("vmtlint: --prune-baseline needs an existing baseline",
                  file=sys.stderr)
            return 2
        noun = "entry" if len(stale) == 1 else "entries"
        if args.check:
            if stale:
                for fp in stale:
                    print(f"vmtlint: stale baseline entry: {fp} "
                          f"({baseline[fp].get('path', '?')})",
                          file=sys.stderr)
                print(f"vmtlint: {len(stale)} stale baseline {noun} — "
                      f"run --prune-baseline to drop them",
                      file=sys.stderr)
                return 1
            print("vmtlint: baseline clean (no stale entries)",
                  file=sys.stderr)
            return 0
        bl.prune_baseline(baseline_path, stale)
        print(f"vmtlint: pruned {len(stale)} stale baseline {noun} from "
              f"{baseline_path}", file=sys.stderr)
        return 0

    fmt = args.fmt or ("json" if args.as_json else "human")
    render = {"human": report.render_human, "json": report.render_json,
              "sarif": report.render_sarif}[fmt]
    out = render(new, baselined, stale, files_scanned)
    if out:
        print(out)

    gate: List = [f for f in new if f.severity == "error"]
    if args.strict or cfg.fail_on == "warning":
        gate = list(new)
        if stale and args.strict:
            return 1
    return 1 if gate else 0


def _surface_main(argv: Sequence[str]) -> int:
    """``vmtlint surface [--check] [--out FILE] [--format json|sarif]``:
    build the compile-surface manifest from the library tree (library
    roots only — the key universe is a property of the shipped package,
    not its tests) and write, print, or verify it."""
    from vilbert_multitask_tpu.analysis import surface as surf_mod

    p = argparse.ArgumentParser(
        prog="python -m vilbert_multitask_tpu.analysis surface",
        description="Enumerate the engine's XLA compile-key universe "
                    "(program family × bucket × param_dtype × fused "
                    "mode × topology × attention mode) with witness "
                    "chains, as COMPILE_SURFACE.json")
    p.add_argument("--check", action="store_true",
                   help="verify the committed manifest matches the tree; "
                        "exit 1 on drift (the CI gate)")
    p.add_argument("--out", default=None, metavar="FILE",
                   help=f"manifest path (default: <repo>/"
                        f"{surf_mod.MANIFEST_NAME})")
    p.add_argument("--format", default="json", dest="fmt",
                   choices=("json", "sarif"),
                   help="with no --check: 'json' writes the manifest, "
                        "'sarif' prints witness codeFlows to stdout")
    args = p.parse_args(argv)

    cfg, root = load_config(os.getcwd())
    root = root or os.getcwd()
    roots = [os.path.join(root, r) for r in cfg.library_roots]
    roots = [r for r in roots if os.path.exists(r)] or [root]
    sources = {}
    for path in iter_python_files(roots, exclude=cfg.exclude):
        rel = os.path.relpath(os.path.abspath(path),
                              root).replace(os.sep, "/")
        try:
            with open(path, "r", encoding="utf-8") as f:
                sources[rel] = f.read()
        except OSError:
            continue
    project = surf_mod.load_project(sources)
    fresh = surf_mod.build_surface(project)
    out_path = args.out or os.path.join(root, surf_mod.MANIFEST_NAME)

    if args.check:
        committed = None
        if os.path.exists(out_path):
            try:
                with open(out_path, "r", encoding="utf-8") as f:
                    committed = json.load(f)
            except (OSError, ValueError) as e:
                print(f"vmtlint surface: unreadable manifest "
                      f"{out_path}: {e}", file=sys.stderr)
                return 2
        msgs = surf_mod.diff_surface(committed, fresh)
        if msgs:
            for m in msgs:
                print(f"vmtlint surface: {m}", file=sys.stderr)
            print("vmtlint surface: compile surface drifted — "
                  "regenerate with `python -m vilbert_multitask_tpu."
                  "analysis surface` and commit the result",
                  file=sys.stderr)
            return 1
        print(f"vmtlint surface: check clean — "
              f"{fresh['record_count']} record(s), "
              f"{len(fresh['dimensions']['program_families'])} program "
              f"family(ies)", file=sys.stderr)
        return 0

    if args.fmt == "sarif":
        sys.stdout.write(surf_mod.render_surface_sarif(fresh))
        return 0
    with open(out_path, "w", encoding="utf-8") as f:
        f.write(surf_mod.render_surface(fresh))
    print(f"vmtlint surface: wrote {fresh['record_count']} record(s) "
          f"({len(fresh['dimensions']['program_families'])} program "
          f"family(ies)) to {out_path}", file=sys.stderr)
    return 0


def _txn_main(argv: Sequence[str]) -> int:
    """``vmtlint txn [--check] [--out FILE] [--format json|sarif]``:
    build the durable-state manifest (tables, transaction sites, state
    machines) from the library tree and write, print, or verify it —
    the TXN_SURFACE.json twin of ``surface``."""
    from vilbert_multitask_tpu.analysis import surface as surf_mod
    from vilbert_multitask_tpu.analysis import txn as txn_mod

    p = argparse.ArgumentParser(
        prog="python -m vilbert_multitask_tpu.analysis txn",
        description="Enumerate the durable-state surface of the sqlite "
                    "stores (tables + migrated schema, transaction "
                    "sites with modes, literal-write state machines), "
                    "as TXN_SURFACE.json")
    p.add_argument("--check", action="store_true",
                   help="verify the committed manifest matches the tree; "
                        "exit 1 on drift (the CI gate)")
    p.add_argument("--out", default=None, metavar="FILE",
                   help=f"manifest path (default: <repo>/"
                        f"{txn_mod.MANIFEST_NAME})")
    p.add_argument("--format", default="json", dest="fmt",
                   choices=("json", "sarif"),
                   help="with no --check: 'json' writes the manifest, "
                        "'sarif' prints txn-site witnesses to stdout")
    args = p.parse_args(argv)

    cfg, root = load_config(os.getcwd())
    root = root or os.getcwd()
    roots = [os.path.join(root, r) for r in cfg.library_roots]
    roots = [r for r in roots if os.path.exists(r)] or [root]
    sources = {}
    for path in iter_python_files(roots, exclude=cfg.exclude):
        rel = os.path.relpath(os.path.abspath(path),
                              root).replace(os.sep, "/")
        try:
            with open(path, "r", encoding="utf-8") as f:
                sources[rel] = f.read()
        except OSError:
            continue
    project = surf_mod.load_project(sources)
    fresh = txn_mod.build_txn_surface(project)
    out_path = args.out or os.path.join(root, txn_mod.MANIFEST_NAME)

    if args.check:
        committed = None
        if os.path.exists(out_path):
            try:
                with open(out_path, "r", encoding="utf-8") as f:
                    committed = json.load(f)
            except (OSError, ValueError) as e:
                print(f"vmtlint txn: unreadable manifest "
                      f"{out_path}: {e}", file=sys.stderr)
                return 2
        msgs = txn_mod.diff_txn_surface(committed, fresh)
        if msgs:
            for m in msgs:
                print(f"vmtlint txn: {m}", file=sys.stderr)
            print("vmtlint txn: durable-state surface drifted — "
                  "regenerate with `python -m vilbert_multitask_tpu."
                  "analysis txn` and commit the result",
                  file=sys.stderr)
            return 1
        print(f"vmtlint txn: check clean — "
              f"{fresh['counts']['tables']} table(s), "
              f"{fresh['counts']['txn_sites']} transaction site(s)",
              file=sys.stderr)
        return 0

    if args.fmt == "sarif":
        sys.stdout.write(txn_mod.render_txn_surface_sarif(fresh))
        return 0
    with open(out_path, "w", encoding="utf-8") as f:
        f.write(txn_mod.render_txn_surface(fresh))
    print(f"vmtlint txn: wrote {fresh['counts']['tables']} table(s), "
          f"{fresh['counts']['txn_sites']} transaction site(s) to "
          f"{out_path}", file=sys.stderr)
    return 0


def _proto_main(argv: Sequence[str]) -> int:
    """``vmtlint proto [--check] [--out FILE] [--format json|sarif]``:
    build the protocol-surface manifest (typestate protocols, acquire
    sites, composed wrappers with witness chains, per-function path
    proofs, fault-site coverage) and write, print, or verify it — the
    PROTOCOL_SURFACE.json sibling of ``surface`` and ``txn``.

    Unlike those two this loads the *configured* paths (tests/ and
    scripts/ included, not just library roots): the fault-coverage map
    needs to see the FaultPlans that live in tests, even though findings
    and protocol declarations still bind only library code."""
    from vilbert_multitask_tpu.analysis import proto as proto_mod
    from vilbert_multitask_tpu.analysis import surface as surf_mod

    p = argparse.ArgumentParser(
        prog="python -m vilbert_multitask_tpu.analysis proto",
        description="Enumerate the typestate protocol surface (job "
                    "claim→terminal, replica checkout→checkin, thread "
                    "start→join, sqlite connect→close) with per-path "
                    "proof verdicts and fault-site coverage, as "
                    "PROTOCOL_SURFACE.json")
    p.add_argument("--check", action="store_true",
                   help="verify the committed manifest matches the tree; "
                        "exit 1 on drift (the CI gate)")
    p.add_argument("--out", default=None, metavar="FILE",
                   help=f"manifest path (default: <repo>/"
                        f"{proto_mod.MANIFEST_NAME})")
    p.add_argument("--format", default="json", dest="fmt",
                   choices=("json", "sarif"),
                   help="with no --check: 'json' writes the manifest, "
                        "'sarif' prints protocol witnesses to stdout")
    args = p.parse_args(argv)

    cfg, root = load_config(os.getcwd())
    root = root or os.getcwd()
    roots = [os.path.join(root, r) for r in cfg.paths]
    roots = [r for r in roots if os.path.exists(r)] or [root]
    sources = {}
    for path in iter_python_files(roots, exclude=cfg.exclude):
        rel = os.path.relpath(os.path.abspath(path),
                              root).replace(os.sep, "/")
        try:
            with open(path, "r", encoding="utf-8") as f:
                sources[rel] = f.read()
        except OSError:
            continue
    project = surf_mod.load_project(sources)
    fresh = proto_mod.build_proto_surface(project)
    out_path = args.out or os.path.join(root, proto_mod.MANIFEST_NAME)

    if args.check:
        committed = None
        if os.path.exists(out_path):
            try:
                with open(out_path, "r", encoding="utf-8") as f:
                    committed = json.load(f)
            except (OSError, ValueError) as e:
                print(f"vmtlint proto: unreadable manifest "
                      f"{out_path}: {e}", file=sys.stderr)
                return 2
        msgs = proto_mod.diff_proto_surface(committed, fresh)
        if msgs:
            for m in msgs:
                print(f"vmtlint proto: {m}", file=sys.stderr)
            print("vmtlint proto: protocol surface drifted — "
                  "regenerate with `python -m vilbert_multitask_tpu."
                  "analysis proto` and commit the result",
                  file=sys.stderr)
            return 1
        print(f"vmtlint proto: check clean — "
              f"{fresh['counts']['protocols']} protocol(s), "
              f"{fresh['counts']['acquire_sites']} acquire site(s), "
              f"{fresh['counts']['functions_proved']} function(s) "
              f"proved, {fresh['counts']['fault_points']} fault "
              f"point(s)", file=sys.stderr)
        return 0

    if args.fmt == "sarif":
        sys.stdout.write(proto_mod.render_proto_surface_sarif(fresh))
        return 0
    with open(out_path, "w", encoding="utf-8") as f:
        f.write(proto_mod.render_proto_surface(fresh))
    print(f"vmtlint proto: wrote {fresh['counts']['protocols']} "
          f"protocol(s), {fresh['counts']['acquire_sites']} acquire "
          f"site(s), {fresh['counts']['fault_points']} fault point(s) "
          f"to {out_path}", file=sys.stderr)
    return 0


def _exc_main(argv: Sequence[str]) -> int:
    """``vmtlint exc [--check] [--out FILE] [--format json|sarif]``:
    build the failure-surface manifest (every thread/tick/breaker/
    fault-site boundary with its escaping exception set and verdict,
    the handler inventory, the project exception taxonomy) and write,
    print, or verify it — the FAILURE_SURFACE.json sibling of
    ``surface``, ``txn``, and ``proto``.

    Like ``proto`` this loads the *configured* paths, not just library
    roots: boundaries and findings bind only library code, but the
    escape summaries compose through everything the config scans."""
    from vilbert_multitask_tpu.analysis import exc as exc_mod
    from vilbert_multitask_tpu.analysis import surface as surf_mod

    p = argparse.ArgumentParser(
        prog="python -m vilbert_multitask_tpu.analysis exc",
        description="Enumerate the exception-flow failure surface "
                    "(thread entries, sampler ticks, breaker regions, "
                    "fault sites — each with its escaping exception "
                    "set and verdict), as FAILURE_SURFACE.json")
    p.add_argument("--check", action="store_true",
                   help="verify the committed manifest matches the tree; "
                        "exit 1 on drift (the CI gate)")
    p.add_argument("--out", default=None, metavar="FILE",
                   help=f"manifest path (default: <repo>/"
                        f"{exc_mod.MANIFEST_NAME})")
    p.add_argument("--format", default="json", dest="fmt",
                   choices=("json", "sarif"),
                   help="with no --check: 'json' writes the manifest, "
                        "'sarif' prints boundary escape chains to "
                        "stdout")
    args = p.parse_args(argv)

    cfg, root = load_config(os.getcwd())
    root = root or os.getcwd()
    roots = [os.path.join(root, r) for r in cfg.paths]
    roots = [r for r in roots if os.path.exists(r)] or [root]
    sources = {}
    for path in iter_python_files(roots, exclude=cfg.exclude):
        rel = os.path.relpath(os.path.abspath(path),
                              root).replace(os.sep, "/")
        try:
            with open(path, "r", encoding="utf-8") as f:
                sources[rel] = f.read()
        except OSError:
            continue
    project = surf_mod.load_project(sources)
    fresh = exc_mod.build_failure_surface(project)
    out_path = args.out or os.path.join(root, exc_mod.MANIFEST_NAME)

    if args.check:
        committed = None
        if os.path.exists(out_path):
            try:
                with open(out_path, "r", encoding="utf-8") as f:
                    committed = json.load(f)
            except (OSError, ValueError) as e:
                print(f"vmtlint exc: unreadable manifest "
                      f"{out_path}: {e}", file=sys.stderr)
                return 2
        msgs = exc_mod.diff_failure_surface(committed, fresh)
        if msgs:
            for m in msgs:
                print(f"vmtlint exc: {m}", file=sys.stderr)
            print("vmtlint exc: failure surface drifted — regenerate "
                  "with `python -m vilbert_multitask_tpu.analysis "
                  "exc` and commit the result", file=sys.stderr)
            return 1
        print(f"vmtlint exc: check clean — "
              f"{fresh['counts']['boundaries']} boundary(ies), "
              f"{fresh['counts']['escaping_boundaries']} escaping, "
              f"{fresh['counts']['handlers']} handler(s), "
              f"{fresh['counts']['exception_classes']} exception "
              f"class(es)", file=sys.stderr)
        return 0

    if args.fmt == "sarif":
        sys.stdout.write(exc_mod.render_failure_surface_sarif(fresh))
        return 0
    with open(out_path, "w", encoding="utf-8") as f:
        f.write(exc_mod.render_failure_surface(fresh))
    print(f"vmtlint exc: wrote {fresh['counts']['boundaries']} "
          f"boundary(ies) ({fresh['counts']['escaping_boundaries']} "
          f"escaping), {fresh['counts']['handlers']} handler(s) to "
          f"{out_path}", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
