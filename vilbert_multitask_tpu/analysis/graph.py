"""Project graph: import graph + per-module symbol tables, AST-only.

One :class:`ProjectGraph` spans every module handed to the driver — no
module is ever imported or executed. It gives rules the whole-program
facts the per-module :class:`~.context.ModuleContext` cannot see:

- which project module a dotted name lands in (``resolve_symbol``),
  chasing re-exports through ``__init__`` aliases with a cycle guard;
- every import edge (including lazy function-level imports) for the
  VMT112 layering contracts;
- project-wide mesh axis declarations for VMT111;
- the call graph (``analysis/callgraph.py``) behind interprocedural jit
  propagation (VMT101/102/103 in helpers called *from* jit) and the
  VMT110 thread-entry reachability.

Even a single-file ``analyze_source`` run builds a one-module project, so
rules never branch on "is there a project" — the graph just has fewer
modules.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

from vilbert_multitask_tpu.analysis.context import JitInfo, ModuleContext

_MESH_CALLS = {"jax.sharding.Mesh", "jax.experimental.maps.Mesh"}


def module_name_for(rel_path: str) -> str:
    """Dotted module name for a repo-relative path.

    ``pkg/sub/mod.py`` → ``pkg.sub.mod``; ``pkg/__init__.py`` → ``pkg``.
    Directories without ``__init__.py`` (scripts/, tests/) still get a
    dotted name — layering contracts match on these prefixes.
    """
    path = rel_path[:-3] if rel_path.endswith(".py") else rel_path
    parts = [p for p in path.replace("\\", "/").split("/") if p]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts) or rel_path


@dataclasses.dataclass
class ImportRecord:
    """One import statement edge: the canonical module imported, plus the
    symbol for ``from M import y`` (which may itself be a submodule)."""

    module: str  # canonical dotted module ("" if unresolvable relative)
    symbol: str  # "" for plain `import M`
    node: ast.AST  # for line attribution

    def targets(self) -> Tuple[str, ...]:
        """Dotted names this record may bind — layering matches any."""
        if self.symbol:
            return (self.module, f"{self.module}.{self.symbol}")
        return (self.module,)


class ModuleInfo:
    """One module's project-level view: symbols, imports, canonical refs."""

    def __init__(self, name: str, ctx: ModuleContext, is_package: bool):
        self.name = name
        self.ctx = ctx
        self.is_package = is_package
        # Top-level definitions (functions, classes, assigned names).
        self.symbols: Dict[str, ast.AST] = {}
        # Every import in the module, lazy function-level ones included.
        self.imports: List[ImportRecord] = []
        # Local name -> canonical dotted target, with relative imports
        # resolved against the package (ModuleContext.aliases keeps the
        # raw spelling; this map is what project resolution trusts).
        self.refs: Dict[str, str] = {}
        self._collect()

    def _package(self) -> List[str]:
        parts = self.name.split(".")
        return parts if self.is_package else parts[:-1]

    def _collect(self) -> None:
        for stmt in self.ctx.tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                self.symbols[stmt.name] = stmt
            elif isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        self.symbols[t.id] = stmt
            elif isinstance(stmt, ast.AnnAssign) and isinstance(
                    stmt.target, ast.Name):
                self.symbols[stmt.target.id] = stmt
        for node in ast.walk(self.ctx.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.imports.append(ImportRecord(a.name, "", node))
                    self.refs[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom):
                base = self._from_base(node)
                for a in node.names:
                    if a.name == "*":
                        continue
                    self.imports.append(ImportRecord(base, a.name, node))
                    if base:
                        self.refs[a.asname or a.name] = f"{base}.{a.name}"

    def _from_base(self, node: ast.ImportFrom) -> str:
        """Canonical module of a ``from`` import, resolving relativity:
        in package ``a.b``, ``from .x import y`` has base ``a.b.x``."""
        if not node.level:
            return node.module or ""
        pkg = self._package()
        anchor = pkg[:len(pkg) - (node.level - 1)] if node.level > 1 else pkg
        parts = anchor + (node.module.split(".") if node.module else [])
        return ".".join(parts)


class ProjectGraph:
    """All scanned modules plus the cross-module lookup tables."""

    def __init__(self, contexts: Sequence[ModuleContext],
                 layers: Sequence[Tuple[str, str]] = ()):
        self.layers: List[Tuple[str, str]] = list(layers)
        self.modules: Dict[str, ModuleInfo] = {}
        self.by_path: Dict[str, ModuleInfo] = {}
        for ctx in contexts:
            name = module_name_for(ctx.rel_path)
            info = ModuleInfo(name, ctx,
                              ctx.rel_path.endswith("__init__.py"))
            self.modules[name] = info
            self.by_path[ctx.rel_path] = info
        self._callgraph = None
        self._mesh_axes: Optional[Set[str]] = None

    def module(self, ctx: ModuleContext) -> Optional[ModuleInfo]:
        return self.by_path.get(ctx.rel_path)

    # --------------------------------------------------------- resolution
    def resolve_symbol(self, dotted: str,
                       _seen: Optional[Set[Tuple[str, str]]] = None
                       ) -> Optional[Tuple[ModuleInfo, str]]:
        """(module, symbol_path) for a canonical dotted name, or None if it
        doesn't land in a scanned module. ``symbol_path`` is "" when the
        name IS the module; re-export chains through package ``__init__``
        are followed with a cycle guard."""
        if not dotted:
            return None
        parts = dotted.split(".")
        for i in range(len(parts), 0, -1):
            prefix = ".".join(parts[:i])
            if prefix in self.modules:
                return self._resolve_in(self.modules[prefix], parts[i:],
                                        _seen or set())
        return None

    def _resolve_in(self, mod: ModuleInfo, remainder: List[str],
                    seen: Set[Tuple[str, str]]
                    ) -> Optional[Tuple[ModuleInfo, str]]:
        if not remainder:
            return mod, ""
        head = remainder[0]
        if head in mod.symbols:
            return mod, ".".join(remainder)
        key = (mod.name, head)
        if key in seen:  # import cycle / re-export loop
            return None
        seen.add(key)
        target = mod.refs.get(head)
        if target:
            return self.resolve_symbol(
                ".".join([target] + remainder[1:]), seen)
        sub = f"{mod.name}.{head}"
        if sub in self.modules:
            return self._resolve_in(self.modules[sub], remainder[1:], seen)
        return None

    # ---------------------------------------------------------- callgraph
    @property
    def callgraph(self):
        if self._callgraph is None:
            from vilbert_multitask_tpu.analysis.callgraph import CallGraph

            self._callgraph = CallGraph(self)
        return self._callgraph

    def traced_helpers(self, ctx: ModuleContext
                       ) -> List[Tuple[JitInfo, str]]:
        """Functions in this module that inherit traced context by being
        reachable (calls or references) from some jit body, wrapped as
        :class:`JitInfo` so the lexical rules apply unchanged, each with a
        human-readable witness chain. Functions lexically inside a jit
        body are excluded — the lexical pass already covers them."""
        mod = self.module(ctx)
        if mod is None:
            return []
        jit_ids = {id(info.body) for info in ctx.jit_bodies}
        out: List[Tuple[JitInfo, str]] = []
        for fn, witness in self.callgraph.traced_in(mod):
            if id(fn.node) in jit_ids:
                continue
            if any(id(anc) in jit_ids for anc in ctx.ancestors(fn.node)):
                continue
            out.append((JitInfo(fn.node), witness))
        return out

    def local_donors(self, ctx: ModuleContext) -> Dict[str, Tuple[int, ...]]:
        """Names visible in this module that donate arguments — module-level
        functions whose params are (transitively) donated, plus imported
        aliases of such functions in other modules."""
        mod = self.module(ctx)
        if mod is None:
            return {}
        donations = self.callgraph.donations
        out: Dict[str, Tuple[int, ...]] = {}
        for name in mod.symbols:
            qual = f"{mod.name}:{name}"
            if donations.get(qual):
                out[name] = tuple(sorted(donations[qual]))
        for alias, target in mod.refs.items():
            resolved = self.resolve_symbol(target)
            if resolved is None:
                continue
            tmod, sym = resolved
            donate = donations.get(f"{tmod.name}:{sym}") if sym else None
            if donate:
                out[alias] = tuple(sorted(donate))
            elif sym and sym in tmod.ctx.jit_bound_names:
                # f = jax.jit(g, donate_argnums=...) re-exported by name.
                d = tmod.ctx.jit_bound_names[sym]
                if d:
                    out[alias] = d
        return out

    def hot_path_functions(self, ctx: ModuleContext):
        """Functions in this module that are call-reachable from an engine
        serving entry point (``run``/``run_many``/``predict``/
        ``_dispatch*``), each with its witness chain — the VMT113 scope."""
        mod = self.module(ctx)
        if mod is None:
            return []
        return self.callgraph.hot_in(mod)

    def transfer_witness(self, qualname: Optional[str]) -> Optional[str]:
        """Witness chain if the named project function (transitively)
        performs a host<->device transfer, else None."""
        if qualname is None:
            return None
        return self.callgraph.transfers.get(qualname)

    def thread_witness(self, ctx: ModuleContext, cls_node: ast.ClassDef
                       ) -> Optional[str]:
        """If any function belonging to this class runs on a thread (is a
        thread entry point or is call-reachable from one), the entry's
        qualname — the evidence VMT110 attaches to a race finding."""
        mod = self.module(ctx)
        if mod is None:
            return None
        return self.callgraph.class_thread_witness(mod, cls_node)

    # -------------------------------------------------------------- mesh
    def mesh_axes(self) -> Set[str]:
        """Every mesh axis name declared anywhere in the project: string
        constants in ``Mesh(...)`` axis arguments and in ``axis_names``
        assignments/defaults/keywords. The union is deliberately generous
        — a missing declaration causes false positives, never the
        reverse."""
        if self._mesh_axes is not None:
            return self._mesh_axes
        axes: Set[str] = set()
        for mod in self.modules.values():
            axes |= module_mesh_axes(mod.ctx)
        self._mesh_axes = axes
        return axes


def import_closure(sources: Dict[str, str], changed: Set[str]) -> Set[str]:
    """Scan-set closure for ``--changed``: the changed files, every
    transitive reverse importer (callers whose cross-module findings the
    change could shift), and the transitive forward imports of the changed
    files themselves (the definitions — lock identities, config
    declarations — their analysis needs).

    Deliberately lighter than a full :class:`ProjectGraph`: one throwaway
    parse per file, imports only.  Unparseable files keep their path in the
    closure when changed (so VMT000 still fires) but contribute no edges.
    """
    name_of: Dict[str, str] = {rel: module_name_for(rel) for rel in sources}
    known: Set[str] = set(name_of.values())

    def to_project_module(dotted: str) -> Optional[str]:
        parts = dotted.split(".")
        for i in range(len(parts), 0, -1):
            prefix = ".".join(parts[:i])
            if prefix in known:
                return prefix
        return None

    forward: Dict[str, Set[str]] = {n: set() for n in known}
    reverse: Dict[str, Set[str]] = {n: set() for n in known}
    for rel, source in sources.items():
        name = name_of[rel]
        try:
            tree = ast.parse(source)
        except SyntaxError:
            continue
        pkg = name.split(".") if rel.endswith("__init__.py") else \
            name.split(".")[:-1]
        targets: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    targets.add(a.name)
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    anchor = (pkg[:len(pkg) - (node.level - 1)]
                              if node.level > 1 else pkg)
                    base = ".".join(
                        anchor + (node.module.split(".")
                                  if node.module else []))
                else:
                    base = node.module or ""
                for a in node.names:
                    if a.name == "*":
                        targets.add(base)
                    else:
                        targets.add(f"{base}.{a.name}" if base else a.name)
        for dotted in targets:
            mod = to_project_module(dotted)
            if mod is not None and mod != name:
                forward[name].add(mod)
                reverse[mod].add(name)

    seeds = {name_of[rel] for rel in changed if rel in name_of}
    closure: Set[str] = set(seeds)
    frontier = list(seeds)
    while frontier:  # who (transitively) imports the changed modules
        for imp in reverse.get(frontier.pop(), ()):
            if imp not in closure:
                closure.add(imp)
                frontier.append(imp)
    frontier = list(seeds)
    fwd_seen = set(seeds)
    while frontier:  # what the changed modules (transitively) import
        for dep in forward.get(frontier.pop(), ()):
            if dep not in fwd_seen:
                fwd_seen.add(dep)
                closure.add(dep)
                frontier.append(dep)
    # Siblings coupled through the changed files' dependencies: a module
    # that imports the same lock/config definitions can form cross-module
    # findings (an ABBA half, a knob read) WITH the changed code without
    # ever importing it — reverse-close over the forward set too.  When
    # the forward set contains a hub (config, obs) this legitimately
    # inflates the closure past the fallback threshold, which is the safe
    # direction: full scan, never a silently incomplete lock graph.
    frontier = [n for n in fwd_seen if n not in seeds]
    while frontier:
        for imp in reverse.get(frontier.pop(), ()):
            if imp not in closure:
                closure.add(imp)
                frontier.append(imp)
    return {rel for rel, n in name_of.items() if n in closure}


def module_mesh_axes(ctx: ModuleContext) -> Set[str]:
    axes: Set[str] = set()
    for node in ast.walk(ctx.tree):
        if (isinstance(node, ast.Call)
                and ctx.resolve(node.func) in _MESH_CALLS):
            cands = list(node.args[1:2])
            cands += [kw.value for kw in node.keywords
                      if kw.arg in ("axis_names", "axis_name")]
            for cand in cands:
                axes |= _str_constants(cand)
        elif (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == "axis_names"
                        for t in node.targets)):
            axes |= _str_constants(node.value)
        elif (isinstance(node, ast.AnnAssign) and node.value is not None
                and isinstance(node.target, ast.Name)
                and node.target.id == "axis_names"):
            axes |= _str_constants(node.value)
        elif isinstance(node, ast.Call):
            for kw in node.keywords:
                if kw.arg == "axis_names":
                    axes |= _str_constants(kw.value)
    return axes


def _str_constants(node: ast.AST) -> Set[str]:
    return {n.value for n in ast.walk(node)
            if isinstance(n, ast.Constant) and isinstance(n.value, str)}
