"""Call graph over the project graph: who calls whom, statically.

Nodes are every function in every scanned module — top-level functions,
methods, and nested functions — addressed as ``module:Outer.inner``.
Edges are calls and bare references (a function handed to ``jax.lax.scan``
or ``Thread(target=...)`` is reached without a call expression). Only
statically resolvable targets make edges: ``name`` through local scopes
then imports, ``self.m`` through the lexically enclosing class,
``Class.m`` through the module symbol table, dotted paths through
:meth:`ProjectGraph.resolve_symbol`. Dynamic dispatch (``obj.method`` on
an unknown object) makes no edge — the analyses built on top are
deliberately under-approximate everywhere except thread-entry naming,
which falls back to terminal-name matching (see ``_entry_candidates``).

Five fixed points live here:

- ``traced``: functions reachable from any jit body inherit traced
  context (interprocedural VMT101/102/103), each with a witness chain;
- ``donations``: a function's parameter is donated if it flows into a
  ``donate_argnums`` position of a jitted binding or of another donating
  function (donated-buffer escape across call edges, VMT103);
- ``thread_reachable``: functions reachable from thread entry points
  (``threading.Thread(target=...)``, executor ``submit``/``map``,
  ``BaseHTTPRequestHandler`` do_* verbs, ``threading.Thread`` run
  overrides) — the evidence side of the VMT110 race detector;
- ``hot_reachable``: functions reachable from the engine's serving
  entry points (``run``/``run_many``/``predict``/``_dispatch*`` in
  ``*.engine.*`` modules) — the "is this on the latency path" evidence
  for VMT113;
- ``transfers``: functions that perform a host<->device transfer —
  ``jax.device_put``/``device_get``/``block_until_ready`` directly, or
  any project callee that does, transitively — each with a witness
  chain down to the concrete transfer call (the payload side of
  VMT113).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterator, List, Optional, Set, Tuple

_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef)


@dataclasses.dataclass
class FuncNode:
    qualname: str  # "module:scope.path"
    module: object  # ModuleInfo
    node: ast.AST  # the FunctionDef
    scope: Tuple[str, ...]  # lexical path inside the module
    cls_scope: Tuple[str, ...]  # path up to the innermost class ("" = none)
    # Outgoing edges, (callee qualname, is_call); refs count for
    # reachability (traced / thread) but not for donation positions.
    edges: List[Tuple[str, bool]] = dataclasses.field(default_factory=list)


class CallGraph:
    def __init__(self, project):
        self.project = project
        self.functions: Dict[str, FuncNode] = {}
        self.by_node: Dict[int, FuncNode] = {}
        for mod in project.modules.values():
            self._index_module(mod)
        for fn in self.functions.values():
            fn.edges = list(self._edges_for(fn))
        self.traced: Dict[str, str] = self._propagate_traced()
        self.donations: Dict[str, Set[int]] = self._propagate_donations()
        self.thread_reachable: Dict[str, str] = self._propagate_threads()
        self.hot_reachable: Dict[str, str] = self._propagate_hot()
        self.transfers: Dict[str, str] = self._propagate_transfers()

    # ------------------------------------------------------------ indexing
    def _index_module(self, mod) -> None:
        def visit(node: ast.AST, scope: Tuple[str, ...],
                  cls: Tuple[str, ...]) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, _SCOPES):
                    sub = scope + (child.name,)
                    fn = FuncNode(f"{mod.name}:{'.'.join(sub)}",
                                  mod, child, sub, cls)
                    self.functions[fn.qualname] = fn
                    self.by_node[id(child)] = fn
                    visit(child, sub, cls)
                elif isinstance(child, ast.ClassDef):
                    visit(child, scope + (child.name,),
                          scope + (child.name,))
                else:
                    visit(child, scope, cls)

        visit(mod.ctx.tree, (), ())

    def _own_nodes(self, fn_body: ast.AST) -> Iterator[ast.AST]:
        """Walk a function body without descending into nested function or
        class scopes (those are their own graph nodes)."""
        stack = list(ast.iter_child_nodes(fn_body))
        while stack:
            node = stack.pop()
            if isinstance(node, _SCOPES + (ast.ClassDef,)):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    # ---------------------------------------------------------- resolution
    def resolve_callable(self, mod, expr: ast.AST,
                         scope: Tuple[str, ...] = (),
                         cls_scope: Tuple[str, ...] = ()
                         ) -> Optional[str]:
        """Qualname of the project function ``expr`` denotes, or None."""
        if isinstance(expr, ast.Name):
            # Innermost enclosing scope outward: nested sibling functions
            # shadow module-level ones shadow imports.
            for i in range(len(scope), -1, -1):
                qual = f"{mod.name}:{'.'.join(scope[:i] + (expr.id,))}"
                if qual in self.functions:
                    return qual
            target = mod.refs.get(expr.id)
            if target:
                return self._resolve_dotted(target)
            return None
        if isinstance(expr, ast.Attribute):
            if (isinstance(expr.value, ast.Name)
                    and expr.value.id == "self" and cls_scope):
                qual = (f"{mod.name}:"
                        f"{'.'.join(cls_scope + (expr.attr,))}")
                if qual in self.functions:
                    return qual
                return None
            dotted = mod.ctx.resolve(expr)
            if not dotted:
                return None
            head = dotted.split(".")[0]
            if head in mod.symbols:  # Class.method in this module
                qual = f"{mod.name}:{dotted}"
                return qual if qual in self.functions else None
            return self._resolve_dotted(dotted)
        return None

    def _resolve_dotted(self, dotted: str) -> Optional[str]:
        resolved = self.project.resolve_symbol(dotted)
        if resolved is None:
            return None
        tmod, sym = resolved
        if not sym:
            return None
        qual = f"{tmod.name}:{sym}"
        return qual if qual in self.functions else None

    def _edges_for(self, fn: FuncNode
                   ) -> Iterator[Tuple[str, bool]]:
        seen: Set[Tuple[str, bool]] = set()
        for node in self._own_nodes(fn.node):
            target: Optional[str] = None
            is_call = False
            if isinstance(node, ast.Call):
                target = self.resolve_callable(
                    fn.module, node.func, fn.scope, fn.cls_scope)
                is_call = True
            elif isinstance(node, (ast.Name, ast.Attribute)) and isinstance(
                    getattr(node, "ctx", None), ast.Load):
                parent = fn.module.ctx.parent(node)
                if isinstance(parent, ast.Call) and parent.func is node:
                    continue  # the Call case above owns call positions
                target = self.resolve_callable(
                    fn.module, node, fn.scope, fn.cls_scope)
            if target and (target, is_call) not in seen:
                seen.add((target, is_call))
                yield target, is_call

    # ------------------------------------------------------------- traced
    def _seed_edges(self, mod, body: ast.AST, scope: Tuple[str, ...],
                    cls_scope: Tuple[str, ...]) -> Iterator[str]:
        """Resolvable callables used anywhere inside a jit body (including
        its nested functions — everything lexically inside is traced)."""
        for node in ast.walk(body):
            if isinstance(node, ast.Call):
                t = self.resolve_callable(mod, node.func, scope, cls_scope)
                if t:
                    yield t
            elif isinstance(node, (ast.Name, ast.Attribute)) and isinstance(
                    getattr(node, "ctx", None), ast.Load):
                parent = mod.ctx.parent(node)
                if isinstance(parent, ast.Call) and parent.func is node:
                    continue
                t = self.resolve_callable(mod, node, scope, cls_scope)
                if t:
                    yield t

    def _propagate_traced(self) -> Dict[str, str]:
        traced: Dict[str, str] = {}
        frontier: List[str] = []
        for mod in self.project.modules.values():
            for info in mod.ctx.jit_bodies:
                fn = self.by_node.get(id(info.body))
                scope = fn.scope if fn else ()
                cls = fn.cls_scope if fn else ()
                label = fn.qualname if fn else f"{mod.name}:<lambda>"
                for target in self._seed_edges(mod, info.body, scope, cls):
                    if target not in traced and target != label:
                        traced[target] = f"jitted `{label}`"
                        frontier.append(target)
        while frontier:
            qual = frontier.pop()
            for target, _ in self.functions[qual].edges:
                if target not in traced:
                    traced[target] = f"{traced[qual]} -> `{qual}`"
                    frontier.append(target)
        return traced

    def traced_in(self, mod) -> List[Tuple[FuncNode, str]]:
        return sorted(
            ((self.functions[q], w) for q, w in self.traced.items()
             if self.functions[q].module is mod),
            key=lambda fw: fw[0].qualname)

    # ---------------------------------------------------------- donations
    def _param_index(self, fn: FuncNode, name: str) -> Optional[int]:
        params = [a.arg for a in fn.node.args.args]
        return params.index(name) if name in params else None

    def _propagate_donations(self) -> Dict[str, Set[int]]:
        """Fixed point: param i of f is donated if some call inside f
        passes it in a donating position of a jitted binding or of a
        function already known to donate that position. Restricted to
        module-level functions — method donation would need self-offset
        bookkeeping for no current payoff."""
        donations: Dict[str, Set[int]] = {}
        toplevel = [fn for fn in self.functions.values()
                    if len(fn.scope) == 1 and not fn.cls_scope]
        changed = True
        while changed:
            changed = False
            for fn in toplevel:
                mine = donations.setdefault(fn.qualname, set())
                for node in self._own_nodes(fn.node):
                    if not isinstance(node, ast.Call):
                        continue
                    for pos in self._donating_positions(fn, node,
                                                        donations):
                        if pos >= len(node.args):
                            continue
                        arg = node.args[pos]
                        if not isinstance(arg, ast.Name):
                            continue
                        idx = self._param_index(fn, arg.id)
                        if idx is not None and idx not in mine:
                            mine.add(idx)
                            changed = True
        return {q: d for q, d in donations.items() if d}

    def _donating_positions(self, fn: FuncNode, call: ast.Call,
                            donations: Dict[str, Set[int]]
                            ) -> Tuple[int, ...]:
        if isinstance(call.func, ast.Name):
            donate = fn.module.ctx.jit_bound_names.get(call.func.id)
            if donate:
                return donate
        target = self.resolve_callable(fn.module, call.func, fn.scope,
                                       fn.cls_scope)
        if target and donations.get(target):
            return tuple(sorted(donations[target]))
        return ()

    # ------------------------------------------------------------ threads
    _THREAD_VERB_BASES = {"BaseHTTPRequestHandler",
                          "SimpleHTTPRequestHandler",
                          "http.server.BaseHTTPRequestHandler",
                          "http.server.SimpleHTTPRequestHandler"}

    def _entry_candidates(self) -> Iterator[Tuple[str, str]]:
        """(qualname, entry description) for every thread entry point."""
        for mod in self.project.modules.values():
            ctx = mod.ctx
            for node in ast.walk(ctx.tree):
                if isinstance(node, ast.Call):
                    yield from self._call_entries(mod, node)
                elif isinstance(node, ast.ClassDef):
                    yield from self._class_entries(mod, node)

    def _lexical_scope(self, mod, node: ast.AST
                       ) -> Tuple[Tuple[str, ...], Tuple[str, ...]]:
        fn = mod.ctx.enclosing_function(node)
        owner = self.by_node.get(id(fn)) if fn is not None else None
        if owner is not None:
            return owner.scope, owner.cls_scope
        return (), ()

    def _call_entries(self, mod, call: ast.Call
                      ) -> Iterator[Tuple[str, str]]:
        ctx = mod.ctx
        resolved = ctx.resolve(call.func)
        targets: List[ast.AST] = []
        how = ""
        if resolved in ("threading.Thread", "threading.Timer"):
            targets = [kw.value for kw in call.keywords
                       if kw.arg in ("target", "function")]
            how = "threading.Thread(target=...)"
        elif isinstance(call.func, ast.Attribute):
            attr = call.func.attr
            if attr == "submit" and call.args:
                targets, how = [call.args[0]], "executor.submit"
            elif (attr == "map" and call.args
                    and isinstance(call.func.value, ast.Name)
                    and any(s in call.func.value.id.lower()
                            for s in ("pool", "executor"))):
                targets, how = [call.args[0]], "executor.map"
        scope, cls = self._lexical_scope(mod, call)
        for t in targets:
            qual = self.resolve_callable(mod, t, scope, cls)
            if qual:
                yield qual, how
            elif isinstance(t, ast.Attribute):
                # `Thread(target=self.worker.run_forever)`: the receiver
                # type is unknown statically — fall back to matching the
                # terminal method name project-wide. Over-approximate by
                # design: missing a thread entry hides races.
                for fn in self.functions.values():
                    if fn.scope[-1] == t.attr and fn.cls_scope:
                        yield fn.qualname, f"{how} (by name `{t.attr}`)"

    def _class_entries(self, mod, cls: ast.ClassDef
                       ) -> Iterator[Tuple[str, str]]:
        bases = {mod.ctx.resolve(b) for b in cls.bases}
        handler = bases & self._THREAD_VERB_BASES
        thread_sub = "threading.Thread" in bases
        if not (handler or thread_sub):
            return
        for stmt in cls.body:
            if not isinstance(stmt, _SCOPES):
                continue
            fn = self.by_node.get(id(stmt))
            if fn is None:
                continue
            if handler and stmt.name.startswith("do_"):
                yield fn.qualname, f"{next(iter(handler))}.{stmt.name}"
            if thread_sub and stmt.name == "run":
                yield fn.qualname, "threading.Thread subclass run()"

    def _propagate_threads(self) -> Dict[str, str]:
        reachable: Dict[str, str] = {}
        frontier: List[str] = []
        for qual, how in self._entry_candidates():
            if qual not in reachable:
                reachable[qual] = how
                frontier.append(qual)
        while frontier:
            qual = frontier.pop()
            for target, _ in self.functions[qual].edges:
                if target not in reachable:
                    reachable[target] = f"{reachable[qual]} -> `{qual}`"
                    frontier.append(target)
        return reachable

    # ----------------------------------------------------- engine hot path
    # Serving entry points: the methods callers hit per query. Matched by
    # name inside engine modules (``pkg.engine`` or ``pkg.engine.*``) so a
    # split of runtime.py doesn't silently drop the seed set.
    _HOT_ENTRY_NAMES = {"run", "run_many", "predict"}

    def _hot_entries(self) -> Iterator[Tuple[str, str]]:
        for fn in self.functions.values():
            mod_name = fn.module.name
            if not (mod_name.endswith(".engine")
                    or ".engine." in mod_name):
                continue
            leaf = fn.scope[-1]
            if leaf in self._HOT_ENTRY_NAMES or leaf.startswith("_dispatch"):
                yield fn.qualname, f"serving entry `{fn.qualname}`"

    def _propagate_hot(self) -> Dict[str, str]:
        """Fixed point: everything call-reachable from a serving entry is
        on the latency hot path, with a witness chain back to the entry."""
        reachable: Dict[str, str] = {}
        frontier: List[str] = []
        for qual, how in self._hot_entries():
            if qual not in reachable:
                reachable[qual] = how
                frontier.append(qual)
        while frontier:
            qual = frontier.pop()
            for target, _ in self.functions[qual].edges:
                if target not in reachable:
                    reachable[target] = f"{reachable[qual]} -> `{qual}`"
                    frontier.append(target)
        return reachable

    def hot_in(self, mod) -> List[Tuple[FuncNode, str]]:
        return sorted(
            ((self.functions[q], w) for q, w in self.hot_reachable.items()
             if self.functions[q].module is mod),
            key=lambda fw: fw[0].qualname)

    # ------------------------------------------------------------ transfers
    _TRANSFER_CALLS = {"jax.device_put", "jax.device_get",
                       "jax.block_until_ready"}

    def _propagate_transfers(self) -> Dict[str, str]:
        """Backward fixed point: a function performs a host<->device
        transfer if its own body calls one of ``_TRANSFER_CALLS``, or it
        calls (not merely references) a project function that does. The
        witness chains caller-to-callee down to the concrete call."""
        transfers: Dict[str, str] = {}
        for fn in self.functions.values():
            for node in self._own_nodes(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                resolved = fn.module.ctx.resolve(node.func)
                if resolved in self._TRANSFER_CALLS:
                    transfers[fn.qualname] = (
                        f"calls `{resolved}` at line {node.lineno}")
                    break
        changed = True
        while changed:
            changed = False
            for fn in self.functions.values():
                if fn.qualname in transfers:
                    continue
                for target, is_call in fn.edges:
                    if is_call and target in transfers:
                        transfers[fn.qualname] = (
                            f"via `{target}`: {transfers[target]}")
                        changed = True
                        break
        return transfers

    def own_call_nodes(self, fn: FuncNode) -> Iterator[ast.Call]:
        """Call expressions belonging to ``fn``'s own body — nested
        function/class scopes excluded (they are their own graph nodes)."""
        for node in self._own_nodes(fn.node):
            if isinstance(node, ast.Call):
                yield node

    def class_thread_witness(self, mod, cls_node: ast.ClassDef
                             ) -> Optional[str]:
        path: List[str] = [cls_node.name]
        for anc in mod.ctx.ancestors(cls_node):
            if isinstance(anc, _SCOPES + (ast.ClassDef,)):
                path.insert(0, anc.name)
        cls_scope = tuple(path)
        for fn in self.functions.values():
            if (fn.module is mod and fn.cls_scope == cls_scope
                    and fn.qualname in self.thread_reachable):
                return self.thread_reachable[fn.qualname]
        return None
