"""Baseline file I/O: grandfathered findings carried with justifications.

The baseline lets the analyzer land strict on an existing tree: every
pre-existing finding either gets fixed or gets a baseline entry with a
one-line justification. Entries match on fingerprint (rule + path +
content hash — line-number free, so pure line shifts don't invalidate
them; editing the flagged line does, forcing a re-review).
"""

from __future__ import annotations

import json
from typing import Dict, List, Sequence, Tuple

from vilbert_multitask_tpu.analysis.core import Finding

VERSION = 1


def load_baseline(path: str) -> Dict[str, dict]:
    """{fingerprint: entry}; raises ValueError on a malformed file (a
    silently-ignored baseline would un-grandfather everything at once)."""
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    if not isinstance(data, dict) or data.get("version") != VERSION:
        raise ValueError(f"{path}: not a vmtlint baseline "
                         f"(want version={VERSION})")
    entries = data.get("entries", [])
    out: Dict[str, dict] = {}
    for e in entries:
        if not isinstance(e, dict) or "fingerprint" not in e:
            raise ValueError(f"{path}: baseline entry missing fingerprint: "
                             f"{e!r}")
        out[e["fingerprint"]] = e
    return out


def write_baseline(path: str, findings: Sequence[Finding],
                   justification: str = "grandfathered at baseline "
                   "creation; fix on next touch") -> None:
    entries = []
    seen = set()
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule)):
        fp = f.fingerprint()
        if fp in seen:  # identical line elsewhere in the file: one entry
            continue
        seen.add(fp)
        entries.append({
            "fingerprint": fp,
            "rule": f.rule,
            "name": f.name,
            "path": f.path,
            "line": f.line,  # informational; matching ignores it
            "content": f.content,
            "justification": justification,
        })
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"version": VERSION, "entries": entries}, fh, indent=2)
        fh.write("\n")


def prune_baseline(path: str, stale_fingerprints: Sequence[str]) -> None:
    """Rewrite the baseline file without the given stale entries, keeping
    every surviving entry byte-identical (justifications included). The
    baseline only ever shrinks — growth goes through --write-baseline
    plus a human-authored justification."""
    stale = set(stale_fingerprints)
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    data["entries"] = [e for e in data.get("entries", [])
                       if e.get("fingerprint") not in stale]
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2)
        f.write("\n")


def split_baselined(findings: Sequence[Finding], baseline: Dict[str, dict]
                    ) -> Tuple[List[Finding], List[Finding], List[str]]:
    """(new, baselined, stale_fingerprints). Stale entries — baseline rows
    whose finding no longer exists — are reported so the file shrinks as
    debt is paid instead of accreting dead rows."""
    new: List[Finding] = []
    old: List[Finding] = []
    hit = set()
    for f in findings:
        fp = f.fingerprint()
        if fp in baseline:
            hit.add(fp)
            old.append(f)
        else:
            new.append(f)
    stale = sorted(set(baseline) - hit)
    return new, old, stale
