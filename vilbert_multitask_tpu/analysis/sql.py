"""In-tree SQL-statement model for the transaction tier.

The data plane speaks sqlite through string literals in three stores
(``serve/queue.py``, ``serve/db.py``, ``obs/fleet.py``), so the atomicity
contract ROADMAP item 3 needs — "every read-modify-write is one
transaction" — is statically visible in the AST. This module recovers the
statement-level facts: extract SQL strings from ``execute``-family call
sites (including f-string splices like ``claim()``'s ``NOT IN``
placeholder list and ``executescript`` of a module-level schema
constant), classify each statement, and parse the tables, columns
read/written, WHERE guards, ``ORDER BY`` presence, and ``CREATE TABLE`` /
``ALTER TABLE`` schema deltas that the rules and the ``TXN_SURFACE.json``
manifest consume.

This is not a SQL parser — it is a model of the dialect this repo
actually writes (and the fixtures test): single-table DML, upserts,
partial indexes, and ``BEGIN IMMEDIATE``. Unresolvable splices degrade to
an empty segment with ``spliced=True`` so downstream checks can stay
conservative instead of guessing.

Stdlib-only, like the rest of the analysis package (layer contract:
no jax / numpy / serve imports — the stores are analyzed as source).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Tuple

from vilbert_multitask_tpu.analysis.context import ModuleContext

EXECUTE_METHODS = ("execute", "executemany", "executescript")

_KEYWORDS = frozenset("""
select insert update delete create alter drop table index unique if not
exists from where and or order group by limit offset on conflict do set
values into as is null like in between primary key autoincrement integer
text real blob default asc desc distinct count min max sum avg coalesce
begin immediate exclusive deferred transaction commit rollback pragma
replace ignore abort fail when then case else end cast exists having
""".split())

_IDENT = r"[A-Za-z_][A-Za-z0-9_]*"
_IDENT_RE = re.compile(_IDENT)
_DOTTED_RE = re.compile(rf"({_IDENT})\.({_IDENT})")
_FUNC_RE = re.compile(rf"({_IDENT})\s*\(")
_WS_RE = re.compile(r"\s+")

# A column compared against something — the guard positions the state
# machine and the schema-drift check read.
_GUARD_RE = re.compile(
    rf"((?:{_IDENT}\.)?{_IDENT})\s*(=|!=|<>|<=|>=|<|>|\bIS\b|\bIN\b|"
    rf"\bNOT\s+IN\b|\bLIKE\b)\s*('[^']*'|\d+(?:\.\d+)?|\?)?",
    re.IGNORECASE)


class SqlStatement:
    """One parsed statement plus its AST anchor.

    ``columns_read`` / ``columns_written`` are candidate column tokens in
    structurally-confident positions only; table names, SQL keywords, and
    function names never appear in them. ``where_literals`` maps guard
    columns to the literal they are compared equal to (``'pending'`` →
    ``pending``, ``0`` → ``0``); ``set_params`` maps a ``SET col=?``
    column to its positional ``?`` index in the whole statement, so the
    transaction tier can resolve the python-side literal that flows in.
    """

    __slots__ = ("raw", "kind", "tables", "columns_read", "columns_written",
                 "where_columns", "where_literals", "order_by", "group_by",
                 "has_limit", "set_columns", "set_params", "set_literals",
                 "schema_columns", "spliced", "node", "begin_mode")

    def __init__(self, raw: str, node: Optional[ast.AST] = None,
                 spliced: bool = False):
        self.raw = raw
        self.node = node
        self.spliced = spliced
        self.kind = "other"
        self.tables: Tuple[str, ...] = ()
        self.columns_read: Tuple[str, ...] = ()
        self.columns_written: Tuple[str, ...] = ()
        self.where_columns: Tuple[str, ...] = ()
        self.where_literals: Dict[str, str] = {}
        self.order_by: Tuple[str, ...] = ()
        self.group_by: Tuple[str, ...] = ()
        self.has_limit = False
        self.set_columns: Tuple[str, ...] = ()
        self.set_params: Dict[str, int] = {}
        self.set_literals: Dict[str, str] = {}
        # CREATE TABLE: [(col, decl)]; ALTER ADD COLUMN: the one added col.
        self.schema_columns: Tuple[Tuple[str, str], ...] = ()
        self.begin_mode: Optional[str] = None  # for kind == "begin"
        _parse_into(self)

    @property
    def is_write(self) -> bool:
        return self.kind in ("insert", "update", "delete")

    @property
    def is_schema_write(self) -> bool:
        return self.kind in ("create_table", "alter_table")

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"SqlStatement({self.kind} {self.tables} {self.raw[:40]!r})"


# --------------------------------------------------------------- parsing
def _normalize(text: str) -> str:
    return _WS_RE.sub(" ", text).strip().rstrip(";").strip()

_SQL_STR_RE = re.compile(r"'[^']*'")


def _idents(text: str) -> List[str]:
    """Bare + dotted column candidates in ``text``: quoted SQL literals,
    function names, and keywords are dropped; ``tbl.col`` yields ``col``
    (``excluded.col`` names the incoming upsert row, not a stored read,
    and is skipped)."""
    text = _SQL_STR_RE.sub("''", text)
    out: List[str] = []
    funcs = {m.group(1).lower() for m in _FUNC_RE.finditer(text)}
    skip_quals = {"excluded"}
    spans = []
    for m in _DOTTED_RE.finditer(text):
        spans.append(m.span())
        if m.group(1).lower() not in skip_quals:
            out.append(m.group(2))
    for m in _IDENT_RE.finditer(text):
        if any(a <= m.start() < b for a, b in spans):
            continue
        tok = m.group(0)
        low = tok.lower()
        if low in _KEYWORDS or low in funcs or low in skip_quals:
            continue
        out.append(tok)
    return out


def _clause(text_u: str, text: str, start_kw: str,
            end_kws: Sequence[str]) -> Optional[str]:
    """The region after ``start_kw`` up to the first of ``end_kws`` (or
    end of statement). Case-insensitive keyword match on ``text_u``."""
    m = re.search(rf"\b{start_kw}\b", text_u)
    if m is None:
        return None
    rest = text[m.end():]
    rest_u = text_u[m.end():]
    end = len(rest)
    for kw in end_kws:
        em = re.search(rf"\b{kw}\b", rest_u)
        if em is not None:
            end = min(end, em.start())
    return rest[:end]


def _split_commas(text: str) -> List[str]:
    """Split on commas at paren depth 0."""
    out, depth, cur = [], 0, []
    for ch in text:
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth = max(0, depth - 1)
        if ch == "," and depth == 0:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    out.append("".join(cur))
    return [p.strip() for p in out if p.strip()]


_TABLE_AFTER = {
    "select": r"\bFROM\s+(%s)",
    "delete": r"\bFROM\s+(%s)",
    "update": r"\bUPDATE\s+(%s)",
    "insert": r"\bINTO\s+(%s)",
}


def _guards(region: str) -> Tuple[Tuple[str, ...], Dict[str, str]]:
    cols: List[str] = []
    lits: Dict[str, str] = {}
    for m in _GUARD_RE.finditer(region):
        col = m.group(1).split(".")[-1]
        if col.lower() in _KEYWORDS:
            continue
        cols.append(col)
        if m.group(2) == "=" and m.group(3) and m.group(3) != "?":
            lits[col] = m.group(3).strip("'")
    return tuple(dict.fromkeys(cols)), lits


def _parse_into(st: SqlStatement) -> None:
    text = _normalize(st.raw)
    st.raw = text
    u = text.upper()
    reads: List[str] = []
    writes: List[str] = []

    if u.startswith("BEGIN"):
        st.kind = "begin"
        st.begin_mode = ("immediate" if "IMMEDIATE" in u
                         else "exclusive" if "EXCLUSIVE" in u
                         else "deferred")
        return
    if u.startswith(("COMMIT", "ROLLBACK", "END")):
        st.kind = "commit"
        return
    if u.startswith("PRAGMA"):
        st.kind = "pragma"
        return
    if u.startswith("CREATE") and " TABLE" in u.split("(")[0]:
        st.kind = "create_table"
        m = re.search(
            rf"TABLE\s+(?:IF\s+NOT\s+EXISTS\s+)?({_IDENT})\s*\(", u)
        if m:
            # Recover original-case name from the same span.
            st.tables = (text[m.start(1):m.end(1)],)
            body = text[m.end():]
            depth, end = 1, len(body)
            for i, ch in enumerate(body):
                depth += (ch == "(") - (ch == ")")
                if depth == 0:
                    end = i
                    break
            cols = []
            constraint_kws = ("primary", "unique", "check", "foreign",
                              "constraint")
            for item in _split_commas(body[:end]):
                first = item.split()[0] if item.split() else ""
                if not first or first.lower() in constraint_kws:
                    continue
                cols.append((first, " ".join(item.split()[1:])))
            st.schema_columns = tuple(cols)
        return
    if u.startswith("CREATE") and " INDEX" in u.split("(")[0]:
        st.kind = "create_index"
        m = re.search(rf"\bON\s+({_IDENT})\s*\(([^)]*)\)", text,
                      re.IGNORECASE)
        if m:
            st.tables = (m.group(1),)
            reads.extend(_idents(m.group(2)))
        where = _clause(u, text, "WHERE", ())
        if where is not None:
            wc, lits = _guards(where)
            st.where_columns = wc
            st.where_literals = lits
            reads.extend(wc)
        st.columns_read = tuple(dict.fromkeys(reads))
        return
    if u.startswith("ALTER"):
        st.kind = "alter_table"
        m = re.search(
            rf"ALTER\s+TABLE\s+({_IDENT})\s+ADD\s+COLUMN\s+({_IDENT})\s*(.*)",
            text, re.IGNORECASE)
        if m:
            st.tables = (m.group(1),)
            st.schema_columns = ((m.group(2), m.group(3).strip()),)
        return
    if u.startswith("DROP"):
        st.kind = "drop"
        m = re.search(rf"\b(?:TABLE|INDEX)\s+(?:IF\s+EXISTS\s+)?({_IDENT})",
                      text, re.IGNORECASE)
        if m:
            st.tables = (m.group(1),)
        return

    kind = u.split(None, 1)[0].lower() if u else ""
    if kind not in ("select", "insert", "update", "delete"):
        st.kind = "other"
        return
    st.kind = kind

    # Tables: the statement's own target plus any subquery FROMs.
    tables = []
    pat = _TABLE_AFTER[kind] % _IDENT
    m = re.search(pat, text, re.IGNORECASE)
    if m:
        tables.append(m.group(1))
    for sm in re.finditer(rf"\bFROM\s+({_IDENT})", text, re.IGNORECASE):
        if sm.group(1) not in tables:
            tables.append(sm.group(1))
    st.tables = tuple(tables)

    if kind == "select":
        sel = _clause(u, text, "SELECT", ("FROM",))
        if sel is not None:
            for item in _split_commas(sel):
                reads.extend(_idents(item))
    if kind == "insert":
        m = re.search(rf"\bINTO\s+{_IDENT}\s*\(([^)]*)\)", text,
                      re.IGNORECASE)
        if m:
            writes.extend(_idents(m.group(1)))
        cm = re.search(r"\bON\s+CONFLICT\s*\(([^)]*)\)", text,
                       re.IGNORECASE)
        if cm:
            reads.extend(_idents(cm.group(1)))
    if kind in ("update",) or (kind == "insert" and "DO UPDATE" in u):
        set_region = _clause(u, text, "SET", ("WHERE",))
        if set_region is not None:
            set_off = text.index(set_region)
            for item in _split_commas(set_region):
                if "=" not in item:
                    continue
                lhs, rhs = item.split("=", 1)
                lhs_ids = _idents(lhs)
                if not lhs_ids:
                    continue
                col = lhs_ids[0]
                writes.append(col)
                st.set_columns = st.set_columns + (col,)
                rhs = rhs.strip()
                reads.extend(_idents(rhs))
                if rhs == "?":
                    before = text[:set_off + text[set_off:].index(item)
                                  + item.index("=")]
                    st.set_params[col] = before.count("?")
                elif rhs.startswith("'") or re.fullmatch(
                        r"\d+(\.\d+)?", rhs):
                    st.set_literals.setdefault(col, rhs.strip("'"))

    # WHERE guards: the region may be the outer statement's or (for the
    # retention DELETE) contain a whole subquery — guards inside parens
    # still name real columns of the named tables, so keep them.
    where = _clause(u, text, "WHERE", ("ORDER BY", "GROUP BY"))
    if where is not None:
        wc, lits = _guards(where)
        st.where_columns = wc
        st.where_literals.update(lits)
        reads.extend(_idents(where))

    grp = _clause(u, text, "GROUP BY", ("ORDER BY", "LIMIT"))
    if grp is not None:
        st.group_by = tuple(_idents(grp))
        reads.extend(st.group_by)
    order = _clause(u, text, "ORDER BY", ("LIMIT", "OFFSET"))
    if order is not None:
        st.order_by = tuple(_idents(order))
        reads.extend(st.order_by)
    st.has_limit = re.search(r"\bLIMIT\b", u) is not None

    table_names = {t.lower() for t in st.tables}
    st.columns_read = tuple(dict.fromkeys(
        c for c in reads if c.lower() not in table_names))
    st.columns_written = tuple(dict.fromkeys(
        c for c in writes if c.lower() not in table_names))


def split_script(text: str) -> List[str]:
    """``executescript`` payload → individual statements (top-level ';'
    split; sqlite's dialect here has no ';' inside literals we emit)."""
    parts, depth, cur = [], 0, []
    in_str = False
    for ch in text:
        if ch == "'":
            in_str = not in_str
        elif not in_str:
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth = max(0, depth - 1)
            elif ch == ";" and depth == 0:
                parts.append("".join(cur))
                cur = []
                continue
        cur.append(ch)
    parts.append("".join(cur))
    return [p.strip() for p in parts if p.strip()]


# ----------------------------------------------- string-expression model
_MAX_VARIANTS = 16


def expand_str_expr(ctx: ModuleContext, expr: ast.AST, _depth: int = 0
                    ) -> List[Tuple[str, bool]]:
    """Possible (text, spliced) values of a string-ish expression.

    Handles the idioms the stores use: plain constants (adjacent-literal
    concatenation is already one Constant), f-strings (``claim()``'s
    ``{not_in}`` splice), a Name bound to a local assignment or a literal
    for-loop target (the ``ALTER TABLE ... ADD COLUMN {col} {decl}``
    migration loop), conditional expressions, ``+`` concatenation, and
    ``sep.join(<literal str sequence>)`` (the ``_TASK_COLS`` select
    lists). Anything else becomes an empty segment with spliced=True —
    the parse stays sound, the drift check stays conservative.
    """
    if _depth > 6:
        return [("", True)]
    if isinstance(expr, ast.Constant):
        if isinstance(expr.value, str):
            return [(expr.value, False)]
        return [("", True)]
    if isinstance(expr, ast.JoinedStr):
        loop = _covarying_loop(ctx, expr)
        if loop is not None:
            return loop
        variants: List[Tuple[str, bool]] = [("", False)]
        for part in expr.values:
            if isinstance(part, ast.Constant):
                sub = [(str(part.value), False)]
            elif isinstance(part, ast.FormattedValue):
                sub = expand_str_expr(ctx, part.value, _depth + 1)
            else:  # pragma: no cover - future ast nodes
                sub = [("", True)]
            variants = _cross(variants, sub)
        return variants
    if isinstance(expr, ast.IfExp):
        out = (expand_str_expr(ctx, expr.body, _depth + 1)
               + expand_str_expr(ctx, expr.orelse, _depth + 1))
        return _dedupe(out)
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
        return _cross(expand_str_expr(ctx, expr.left, _depth + 1),
                      expand_str_expr(ctx, expr.right, _depth + 1))
    if isinstance(expr, ast.Name):
        bound = _resolve_name(ctx, expr)
        if bound is not None:
            return expand_str_expr(ctx, bound, _depth + 1)
        loop_vals = _loop_values(ctx, expr, expr.id)
        if loop_vals is not None:
            return loop_vals
        return [("", True)]
    if (isinstance(expr, ast.Call) and isinstance(expr.func, ast.Attribute)
            and expr.func.attr == "join"
            and isinstance(expr.func.value, ast.Constant)
            and isinstance(expr.func.value.value, str)
            and len(expr.args) == 1):
        items = _literal_str_seq(ctx, expr.args[0])
        if items is not None:
            return [(expr.func.value.value.join(items), False)]
        return [("", True)]
    return [("", True)]


def _cross(a: List[Tuple[str, bool]], b: List[Tuple[str, bool]]
           ) -> List[Tuple[str, bool]]:
    out = [(x + y, sx or sy) for x, sx in a for y, sy in b]
    return _dedupe(out)


def _dedupe(variants: List[Tuple[str, bool]]) -> List[Tuple[str, bool]]:
    seen, out = set(), []
    for v in variants:
        if v not in seen:
            seen.add(v)
            out.append(v)
    return out[:_MAX_VARIANTS]


def _resolve_name(ctx: ModuleContext, expr: ast.Name
                  ) -> Optional[ast.AST]:
    """The single local (or module-level) binding of ``expr``'s name."""
    fn = ctx.enclosing_function(expr)
    scopes: List[ast.AST] = [n for n in (fn, ctx.tree) if n is not None]
    for scope in scopes:
        bound = None
        for node in ast.walk(scope):
            if (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == expr.id
                    and node is not expr):
                if bound is not None:
                    return None  # ambiguous rebind
                bound = node.value
        if bound is not None:
            return bound
    return None


def _loop_for(ctx: ModuleContext, at: ast.AST, name: str
              ) -> Optional[ast.For]:
    """The literal-iterable For loop binding ``name`` that encloses or
    precedes ``at`` in its function."""
    fn = ctx.enclosing_function(at) or ctx.tree
    for node in ast.walk(fn):
        if not isinstance(node, ast.For):
            continue
        targets = []
        if isinstance(node.target, ast.Name):
            targets = [node.target.id]
        elif isinstance(node.target, ast.Tuple):
            targets = [e.id for e in node.target.elts
                       if isinstance(e, ast.Name)]
        if name in targets and isinstance(node.iter, (ast.Tuple, ast.List)):
            return node
    return None


def _loop_element_values(loop: ast.For, name: str
                         ) -> Optional[List[str]]:
    if isinstance(loop.target, ast.Name):
        idx = None if loop.target.id != name else -1
    else:
        names = [e.id if isinstance(e, ast.Name) else None
                 for e in loop.target.elts]
        idx = names.index(name) if name in names else None
    if idx is None:
        return None
    vals = []
    for elt in loop.iter.elts:
        if idx == -1:
            item = elt
        elif isinstance(elt, (ast.Tuple, ast.List)) and idx < len(elt.elts):
            item = elt.elts[idx]
        else:
            return None
        if isinstance(item, ast.Constant) and isinstance(item.value, str):
            vals.append(item.value)
        else:
            return None
    return vals


def _loop_values(ctx: ModuleContext, at: ast.AST, name: str
                 ) -> Optional[List[Tuple[str, bool]]]:
    loop = _loop_for(ctx, at, name)
    if loop is None:
        return None
    vals = _loop_element_values(loop, name)
    if vals is None:
        return None
    return _dedupe([(v, False) for v in vals])


def _covarying_loop(ctx: ModuleContext, joined: ast.JoinedStr
                    ) -> Optional[List[Tuple[str, bool]]]:
    """All FormattedValue Names bound by ONE literal for-loop → expand
    per loop element, not as an (incorrect) cartesian product — the
    ``ADD COLUMN {col} {decl}`` migration idiom."""
    names = []
    for part in joined.values:
        if isinstance(part, ast.FormattedValue):
            if not isinstance(part.value, ast.Name):
                return None
            names.append(part.value.id)
    if len(names) < 2:
        return None
    loops = {name: _loop_for(ctx, joined, name) for name in names}
    first = loops[names[0]]
    if first is None or any(lp is not first for lp in loops.values()):
        return None
    per_name = {name: _loop_element_values(first, name) for name in names}
    if any(v is None for v in per_name.values()):
        return None
    n = len(next(iter(per_name.values())))
    out: List[Tuple[str, bool]] = []
    for i in range(n):
        text = "".join(
            str(part.value) if isinstance(part, ast.Constant)
            else per_name[part.value.id][i]
            for part in joined.values)
        out.append((text, False))
    return _dedupe(out)


def _literal_str_seq(ctx: ModuleContext, expr: ast.AST
                     ) -> Optional[List[str]]:
    """Resolve a tuple/list of string constants: inline, via a local
    Name, or via ``self.X`` → a class-level assignment."""
    if isinstance(expr, (ast.Tuple, ast.List)):
        vals = []
        for e in expr.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                vals.append(e.value)
            else:
                return None
        return vals
    if isinstance(expr, ast.Name):
        bound = _resolve_name(ctx, expr)
        if bound is not None:
            return _literal_str_seq(ctx, bound)
        return None
    if (isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id in ("self", "cls")):
        cls = next((a for a in ctx.ancestors(expr)
                    if isinstance(a, ast.ClassDef)), None)
        if cls is None:
            return None
        for stmt in cls.body:
            if (isinstance(stmt, ast.Assign)
                    and any(isinstance(t, ast.Name) and t.id == expr.attr
                            for t in stmt.targets)):
                return _literal_str_seq(ctx, stmt.value)
    return None


# ------------------------------------------------------------ extraction
def statements_from_call(ctx: ModuleContext, call: ast.Call
                         ) -> List[SqlStatement]:
    """Parsed statements behind one ``.execute`` / ``.executemany`` /
    ``.executescript`` call (possibly several: f-string variants expand
    each branch; a script splits on ';'). Returns [] when the first
    argument is not statically string-like."""
    if not (isinstance(call.func, ast.Attribute)
            and call.func.attr in EXECUTE_METHODS and call.args):
        return []
    variants = expand_str_expr(ctx, call.args[0])
    out: List[SqlStatement] = []
    seen = set()
    for text, spliced in variants:
        if not text.strip():
            continue
        pieces = (split_script(text) if call.func.attr == "executescript"
                  else [text])
        for piece in pieces:
            st = SqlStatement(piece, node=call, spliced=spliced)
            key = (st.raw, st.spliced)
            if st.kind != "other" and key not in seen:
                seen.add(key)
                out.append(st)
    return out
