"""VMT132–135: typestate protocol rules over the proto tier.

The load-bearing serving invariant — a claimed job reaches **exactly
one** terminal — was until now enforced only dynamically, by chaos soaks
that sample a handful of paths per run. These rules re-anchor the
findings :class:`analysis.proto.ProtoFlow` precomputes project-wide
(path-exhaustive typestate proofs over the CFG, composed through the
call graph) — the same cached-flow consumption shape as the VMT119/120
lock rules and the VMT128-131 txn rules.

All four are ``library_only``: tests claim-and-drop on purpose (that is
what a fixture *is*), so the protocol obligations bind only the package.
"""

from __future__ import annotations

from typing import Iterator

from vilbert_multitask_tpu.analysis.context import ModuleContext
from vilbert_multitask_tpu.analysis.core import Finding, Rule
from vilbert_multitask_tpu.analysis.locks import _Anchor
from vilbert_multitask_tpu.analysis.proto import proto_flow


class JobTerminalProtocol(Rule):
    """A claim path reaching zero terminals (leak) or two (double).

    The typestate walk enumerates every acyclic CFG path from each
    ``claim`` — exception edges and early-return unwinds included —
    refining ``if job is None`` claim-miss guards per branch and
    treating returned/stored/passed-on handles as the callee's
    obligation. Both witness chains render as SARIF codeFlows.
    """

    id = "VMT132"
    name = "job-terminal-protocol"
    severity = "error"
    library_only = True
    description = ("a control-flow path from a job claim reaches zero "
                   "terminals (leaked claim: the visibility sweep, not "
                   "the protocol, decides the job's fate) or two "
                   "(double terminal: the queue row's lifecycle is "
                   "corrupted)")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if ctx.project is None:
            return
        flow = proto_flow(ctx.project)
        for e in flow.job_findings:
            if e["path"] != ctx.rel_path:
                continue
            f = self.finding(ctx, _Anchor(e["line"], e["col"]),
                             e["message"])
            f.flows = [list(chain) for chain in e["flows"]]
            yield f


class ResourceLeakOnException(Rule):
    """An exception edge escapes a scope still holding a handle.

    The flow-sensitive upgrade of VMT117: the worklist solver runs a
    must-held domain (join = intersection) over the CFG, so a ``raise``
    whose incoming fact still contains a checked-out replica, a
    started-unjoined thread, or a plain (non-``with``) sqlite
    connection is a leak on that exact path — not a heuristic about
    syntax shape.
    """

    id = "VMT133"
    name = "resource-leak-on-exception"
    severity = "error"
    library_only = True
    description = ("an exception path abandons an unreleased handle — "
                   "checkout without checkin, started thread without "
                   "join, sqlite connection without close")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if ctx.project is None:
            return
        flow = proto_flow(ctx.project)
        for e in flow.leak_findings:
            if e["path"] != ctx.rel_path:
                continue
            f = self.finding(ctx, _Anchor(e["line"], e["col"]),
                             e["message"])
            f.flows = [list(chain) for chain in e["flows"]]
            yield f


class FaultPointCoverage(Rule):
    """Every ``fault_point`` site must be named by some FaultRule.

    A project-graph cross-check: the chaos tier's value is coverage, and
    coverage silently drifts the moment someone adds a fault site
    without a FaultPlan that injects there. A subset scan cannot prove a
    site is covered *nowhere*, so ``--changed`` suppresses this rule via
    ``partial_scan`` (the VMT122/VMT130 dead-direction contract).
    """

    id = "VMT134"
    name = "fault-point-coverage"
    severity = "warning"
    library_only = True
    description = ("a resilience.faults.fault_point site named by no "
                   "FaultPlan/FaultRule in tests/ or scripts/ — chaos "
                   "coverage drifted")

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        # Set by the --changed driver: coverage needs the whole project.
        self.partial_scan = False

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if ctx.project is None or self.partial_scan:
            return
        flow = proto_flow(ctx.project)
        for e in flow.fault_findings:
            if e["path"] != ctx.rel_path:
                continue
            yield self.finding(ctx, _Anchor(e["line"], e["col"]),
                               e["message"])


class TerminalFrameDrift(Rule):
    """Job-status strings cross-checked against the recovered machine.

    The txn tier already recovers the ``jobs.status`` state machine from
    the SQL surface (TXN_SURFACE.json). Any status literal the runtime
    compares, stores, or pushes through the frame hub that is not a
    state of that machine compares against nothing — with did-you-mean,
    because these bugs are almost always one-letter drift.
    """

    id = "VMT135"
    name = "terminal-frame-drift"
    severity = "warning"
    library_only = True
    description = ("a job-status string literal that is not a state of "
                   "the recovered jobs.status machine — a terminal "
                   "frame or status check drifting from durable state")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if ctx.project is None:
            return
        flow = proto_flow(ctx.project)
        for e in flow.frame_findings:
            if e["path"] != ctx.rel_path:
                continue
            yield self.finding(ctx, _Anchor(e["line"], e["col"]),
                               e["message"])
