"""VMT137–140: exception-flow rules over the exc tier.

Every prior tier proved a closed universe for compiles, transactions,
and protocols; these rules close the last unproven plane — *failures*.
The fleet runs ~10 daemon threads where an escaping exception kills the
thread silently: the queue backs up, SLOs page late, and nothing names
the culprit. :class:`analysis.exc.ExcFlow` precomputes, project-wide,
the set of exception classes that can escape each function (raise-site
inference, handler narrowing with tuple/alias resolution, per-function
summaries composed through the call graph to a fixed point) and
resolves every boundary to its escaping set — the same cached-flow
consumption shape as the VMT132-135 protocol rules.

All four are ``library_only``: tests raise and swallow on purpose.
"""

from __future__ import annotations

from typing import Iterator

from vilbert_multitask_tpu.analysis.context import ModuleContext
from vilbert_multitask_tpu.analysis.core import Finding, Rule
from vilbert_multitask_tpu.analysis.exc import exc_flow
from vilbert_multitask_tpu.analysis.locks import _Anchor


class ThreadRunLoopEscape(Rule):
    """An exception type escapes a thread entry point.

    A daemon thread that dies takes its loop with it and tells no one —
    the interprocedural escape summary composed down from every reachable
    ``raise`` proves which classes can surface at the entry, and the
    raise→escape witness chain renders as SARIF codeFlows. The fix is
    the runtime twin this tier proves complete: run the loop body under
    ``obs.crash_guard`` so the death records a ``thread_died`` bundle,
    drops ``vmt_thread_alive{name}``, and turns ``/healthz`` unready.
    """

    id = "VMT137"
    name = "thread-run-loop-escape"
    severity = "error"
    library_only = True
    description = ("an exception class escapes a thread entry point "
                   "(Thread/Timer target or Thread-subclass run) — "
                   "silent thread death: the loop stops and nothing "
                   "records why")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if ctx.project is None:
            return
        flow = exc_flow(ctx.project)
        for e in flow.thread_findings:
            if e["path"] != ctx.rel_path:
                continue
            f = self.finding(ctx, _Anchor(e["line"], e["col"]),
                             e["message"])
            f.flows = [list(chain) for chain in e["flows"]]
            yield f


class BreakerBlindException(Rule):
    """An escape the breaker's recording clause never observes.

    A ``CircuitBreaker`` only protects against failures it *sees*:
    a class re-raised via ``no_retry``, or escaping outside
    ``retry_on`` / the manual ``record_failure`` handler's types, never
    trips the breaker — a deterministic fault of that class loops at
    full request rate while the breaker reports closed.
    """

    id = "VMT138"
    name = "breaker-blind-exception"
    severity = "error"
    library_only = True
    description = ("an exception escaping a CircuitBreaker-wrapped "
                   "region that the breaker's recording clause does "
                   "not observe — the breaker never trips on this "
                   "failure class")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if ctx.project is None:
            return
        flow = exc_flow(ctx.project)
        for e in flow.breaker_findings:
            if e["path"] != ctx.rel_path:
                continue
            f = self.finding(ctx, _Anchor(e["line"], e["col"]),
                             e["message"])
            f.flows = [list(chain) for chain in e["flows"]]
            yield f


class HandlerShadowsTerminal(Rule):
    """A broad handler swallows an exception on a path owing a terminal.

    Composes with the protocol tier: between a ``claim``/``checkout``
    and its terminal, a broad ``except`` that neither re-raises nor
    reaches a terminal-bearing call silently converts a failure into a
    leaked handle — the job sits invisible until the visibility sweep
    redelivers it, which is exactly the class of latency bug the
    exactly-one-terminal proof exists to prevent.
    """

    id = "VMT139"
    name = "handler-shadows-terminal"
    severity = "error"
    library_only = True
    description = ("a broad except swallows an exception while an "
                   "acquired protocol handle still owes its terminal — "
                   "the claim leaks until the visibility sweep")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if ctx.project is None:
            return
        flow = exc_flow(ctx.project)
        for e in flow.shadow_findings:
            if e["path"] != ctx.rel_path:
                continue
            yield self.finding(ctx, _Anchor(e["line"], e["col"]),
                               e["message"])


class ErrorFrameDrift(Rule):
    """Handler-emitted verdict strings cross-checked against vocabulary.

    The txn tier recovers the ``jobs.status`` machine; the library's own
    non-handler ``job_finish`` sites establish the verdict vocabulary on
    top of it. A verdict string minted *inside an exception handler*
    that matches neither is a failure class dashboards will drop on the
    floor — with did-you-mean, because these are almost always
    one-letter drift.
    """

    id = "VMT140"
    name = "error-frame-drift"
    severity = "warning"
    library_only = True
    description = ("an error/verdict string emitted from an exception "
                   "handler that is not in the recovered jobs.status "
                   "machine or the library's verdict vocabulary")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if ctx.project is None:
            return
        flow = exc_flow(ctx.project)
        for e in flow.frame_findings:
            if e["path"] != ctx.rel_path:
                continue
            yield self.finding(ctx, _Anchor(e["line"], e["col"]),
                               e["message"])
