"""Per-function control-flow graphs for the dataflow tier.

The builder lowers one ``ast.FunctionDef`` / ``ast.AsyncFunctionDef`` into a
graph of basic blocks.  Each block carries an ordered list of *events*:

* plain statements and branch/loop test expressions (``ast.AST`` nodes), and
* ``WithEnter`` / ``WithExit`` markers, one pair per ``withitem``.

``with`` scopes are the part that matters for the lock-set domain, so the
builder is careful about release edges: a ``return`` / ``break`` / ``continue``
/ ``raise`` inside a ``with`` body emits the ``WithExit`` markers for every
frame it unwinds *before* the jump edge, which is exactly what CPython's
``__exit__`` protocol guarantees at runtime.

``try`` statements are modelled conservatively: exception edges run from each
top-level statement boundary of the ``try`` body to every handler entry (state
*after* a completed statement — by which point any ``with`` opened and closed
inside that statement has already released), handler and body exits funnel
through the ``finally`` blocks when present, and the ``finally`` chain feeds
the join block after the statement.

Blocks that end up with no predecessors (code after a ``return``, an empty
branch arm, ...) simply stay unreachable; the worklist solver in
``analysis.dataflow`` never visits them.

Everything here is stdlib-only — the layering contract forbids the analysis
package from importing jax or numpy.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Sequence, Union


class WithEnter:
    """Marker event: the context manager of ``item`` has been entered."""

    __slots__ = ("item", "stmt")

    def __init__(self, item: ast.withitem, stmt: ast.AST) -> None:
        self.item = item
        self.stmt = stmt

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"WithEnter@{getattr(self.item.context_expr, 'lineno', '?')}"


class WithExit:
    """Marker event: the context manager of ``item`` has been exited."""

    __slots__ = ("item", "stmt")

    def __init__(self, item: ast.withitem, stmt: ast.AST) -> None:
        self.item = item
        self.stmt = stmt

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"WithExit@{getattr(self.item.context_expr, 'lineno', '?')}"


Event = Union[ast.AST, WithEnter, WithExit]


class Block:
    """A basic block: an ordered event list plus successor edges."""

    __slots__ = ("id", "events", "succs")

    def __init__(self, bid: int) -> None:
        self.id = bid
        self.events: List[Event] = []
        self.succs: List["Block"] = []

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return f"Block({self.id}, events={len(self.events)}, succs={[s.id for s in self.succs]})"


class CFG:
    """Control-flow graph of a single function."""

    def __init__(self, fn: ast.AST) -> None:
        self.fn = fn
        self.blocks: List[Block] = []
        builder = _Builder(self)
        builder.build(fn)
        self.entry: Block = builder.entry
        self.exit: Block = builder.exit

    def preds(self, block: Block) -> List[Block]:
        return [b for b in self.blocks if block in b.succs]

    def reachable(self) -> List[Block]:
        """Blocks reachable from the entry, in discovery order."""
        seen = {self.entry.id}
        order = [self.entry]
        stack = [self.entry]
        while stack:
            blk = stack.pop()
            for succ in blk.succs:
                if succ.id not in seen:
                    seen.add(succ.id)
                    order.append(succ)
                    stack.append(succ)
        return order


class _LoopFrame:
    __slots__ = ("continue_target", "break_target", "with_depth")

    def __init__(self, continue_target: Block, break_target: Block, with_depth: int) -> None:
        self.continue_target = continue_target
        self.break_target = break_target
        self.with_depth = with_depth


class _Builder:
    def __init__(self, cfg: CFG) -> None:
        self.cfg = cfg
        self.entry = self._new_block()
        self.exit = self._new_block()
        # Stack of (withitem, stmt) frames currently open, innermost last.
        self._with_stack: List[tuple] = []
        self._loop_stack: List[_LoopFrame] = []

    def build(self, fn: ast.AST) -> None:
        end = self._stmts(fn.body, self.entry)
        if end is not None:
            self._edge(end, self.exit)

    # -- plumbing ---------------------------------------------------------

    def _new_block(self) -> Block:
        blk = Block(len(self.cfg.blocks))
        self.cfg.blocks.append(blk)
        return blk

    @staticmethod
    def _edge(src: Block, dst: Block) -> None:
        if dst not in src.succs:
            src.succs.append(dst)

    def _unwind_withs(self, block: Block, down_to: int) -> None:
        """Emit WithExit markers for every frame above ``down_to``."""
        for item, stmt in reversed(self._with_stack[down_to:]):
            block.events.append(WithExit(item, stmt))

    # -- statement lowering -----------------------------------------------

    def _stmts(self, body: Sequence[ast.stmt], cur: Optional[Block]) -> Optional[Block]:
        for stmt in body:
            if cur is None:
                # Unreachable code after a jump; keep building so nested
                # structures exist, but leave the block predecessor-free.
                cur = self._new_block()
            cur = self._stmt(stmt, cur)
        return cur

    def _stmt(self, stmt: ast.stmt, cur: Block) -> Optional[Block]:
        if isinstance(stmt, ast.If):
            return self._if(stmt, cur)
        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            return self._loop(stmt, cur)
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            return self._with(stmt, cur)
        if isinstance(stmt, ast.Try):
            return self._try(stmt, cur)
        if isinstance(stmt, ast.Return):
            cur.events.append(stmt)
            self._unwind_withs(cur, 0)
            self._edge(cur, self.exit)
            return None
        if isinstance(stmt, ast.Raise):
            cur.events.append(stmt)
            self._unwind_withs(cur, 0)
            self._edge(cur, self.exit)
            return None
        if isinstance(stmt, ast.Break):
            frame = self._loop_stack[-1] if self._loop_stack else None
            cur.events.append(stmt)
            if frame is not None:
                self._unwind_withs(cur, frame.with_depth)
                self._edge(cur, frame.break_target)
            return None
        if isinstance(stmt, ast.Continue):
            frame = self._loop_stack[-1] if self._loop_stack else None
            cur.events.append(stmt)
            if frame is not None:
                self._unwind_withs(cur, frame.with_depth)
                self._edge(cur, frame.continue_target)
            return None
        # Everything else (Assign, Expr, FunctionDef, ClassDef, Import, ...)
        # is a straight-line event.  Nested function/class bodies are opaque to
        # the event walker (see iter_event_nodes).
        cur.events.append(stmt)
        return cur

    def _if(self, stmt: ast.If, cur: Block) -> Block:
        cur.events.append(stmt.test)
        join = self._new_block()
        then_entry = self._new_block()
        self._edge(cur, then_entry)
        then_end = self._stmts(stmt.body, then_entry)
        if then_end is not None:
            self._edge(then_end, join)
        if stmt.orelse:
            else_entry = self._new_block()
            self._edge(cur, else_entry)
            else_end = self._stmts(stmt.orelse, else_entry)
            if else_end is not None:
                self._edge(else_end, join)
        else:
            self._edge(cur, join)
        return join

    def _loop(self, stmt: ast.stmt, cur: Block) -> Block:
        header = self._new_block()
        self._edge(cur, header)
        if isinstance(stmt, ast.While):
            header.events.append(stmt.test)
        else:  # For / AsyncFor: iterating evaluates the iterable + target bind
            header.events.append(stmt.iter)
            header.events.append(stmt.target)
        after = self._new_block()
        body_entry = self._new_block()
        self._edge(header, body_entry)
        # `while True:` has no false edge; everything else can skip the body.
        infinite = isinstance(stmt, ast.While) and _is_truthy_const(stmt.test)
        if not infinite:
            if getattr(stmt, "orelse", None):
                # Normal exit runs `else` then falls to `after`; `break`
                # (edges straight to `after`) skips it.
                else_entry = self._new_block()
                self._edge(header, else_entry)
                else_end = self._stmts(stmt.orelse, else_entry)
                if else_end is not None:
                    self._edge(else_end, after)
            else:
                self._edge(header, after)
        self._loop_stack.append(_LoopFrame(header, after, len(self._with_stack)))
        body_end = self._stmts(stmt.body, body_entry)
        self._loop_stack.pop()
        if body_end is not None:
            self._edge(body_end, header)
        return after

    def _with(self, stmt: ast.stmt, cur: Block) -> Optional[Block]:
        for item in stmt.items:
            cur.events.append(WithEnter(item, stmt))
            self._with_stack.append((item, stmt))
        end = self._stmts(stmt.body, cur)
        frames = [self._with_stack.pop() for _ in stmt.items]
        if end is not None:
            for item, owner in frames:
                end.events.append(WithExit(item, owner))
        return end

    def _try(self, stmt: ast.Try, cur: Block) -> Block:
        after = self._new_block()
        handler_entries = [self._new_block() for _ in stmt.handlers]

        # Exception edges: state observable at a handler is the state at some
        # top-level statement boundary of the try body (locks opened-and-closed
        # inside a statement have released by then; an explicit .acquire() in a
        # completed statement is still held).
        # Split before the body as well: `cur` must end at the pre-try
        # boundary or the first statement's events would retroactively
        # change the state its exception edge carries.
        boundary_blocks = [cur]
        body_entry = self._new_block()
        self._edge(cur, body_entry)
        body_cur: Optional[Block] = body_entry
        for sub in stmt.body:
            if body_cur is None:
                body_cur = self._new_block()
            body_cur = self._stmt(sub, body_cur)
            if body_cur is not None:
                boundary_blocks.append(body_cur)
                # Force a block split so each exception edge carries the
                # state at THIS statement's boundary — straight-line
                # statements would otherwise share a block and leak the
                # whole body's effects into the handler.
                nxt = self._new_block()
                self._edge(body_cur, nxt)
                body_cur = nxt
        for blk in boundary_blocks:
            for entry in handler_entries:
                self._edge(blk, entry)

        if stmt.finalbody:
            fin_entry = self._new_block()
            fin_end = self._stmts(stmt.finalbody, fin_entry)
            normal_target = fin_entry
            if fin_end is not None:
                self._edge(fin_end, after)
        else:
            normal_target = after

        if body_cur is not None:
            if stmt.orelse:
                else_end = self._stmts(stmt.orelse, body_cur)
                if else_end is not None:
                    self._edge(else_end, normal_target)
            else:
                self._edge(body_cur, normal_target)

        for handler, entry in zip(stmt.handlers, handler_entries):
            if handler.type is not None:
                entry.events.append(handler.type)
            handler_end = self._stmts(handler.body, entry)
            if handler_end is not None:
                self._edge(handler_end, normal_target)

        return after


def _is_truthy_const(test: ast.expr) -> bool:
    return isinstance(test, ast.Constant) and bool(test.value)


def build_cfg(fn: ast.AST) -> CFG:
    """Build the CFG of one function definition (sync or async)."""
    return CFG(fn)


_SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.ClassDef)


def iter_event_nodes(event: Event) -> Iterator[ast.AST]:
    """Walk the AST nodes of one event without descending into nested scopes.

    ``WithEnter``/``WithExit`` yield the nodes of their context expression (a
    lock acquisition like ``with self._cond:`` lives there).  Plain statements
    yield themselves and their sub-expressions, but the bodies of nested
    ``def``/``lambda``/``class`` are opaque — they execute on a different
    activation, not on this function's control path.
    """
    if isinstance(event, (WithEnter, WithExit)):
        roots: List[ast.AST] = [event.item.context_expr]
    elif isinstance(event, _SCOPE_NODES):
        # The definition itself executes here (decorators, defaults), but not
        # its body.
        roots = list(getattr(event, "decorator_list", []) or [])
        args = getattr(event, "args", None)
        if args is not None:
            roots.extend(args.defaults)
            roots.extend(d for d in args.kw_defaults if d is not None)
        roots.extend(getattr(event, "bases", []) or [])
        return _walk_many(roots)
    else:
        roots = [event]
    return _walk_many(roots)


def _walk_many(roots: List[ast.AST]) -> Iterator[ast.AST]:
    stack = list(roots)
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _SCOPE_NODES):
                # Nested scope: its decorators/defaults still run here.
                stack.extend(getattr(child, "decorator_list", []) or [])
                args = getattr(child, "args", None)
                if args is not None:
                    stack.extend(args.defaults)
                    stack.extend(d for d in args.kw_defaults if d is not None)
                continue
            stack.append(child)
