"""``[tool.vmtlint]`` configuration from pyproject.toml.

This interpreter is Python 3.10 with no tomllib/tomli available, so a
minimal TOML-subset parser lives here — sections, string/bool/int values,
and (possibly multiline) arrays of strings cover everything the vmtlint
block needs. It is NOT a general TOML parser and only ever reads the
``tool.vmtlint`` tables.
"""

from __future__ import annotations

import dataclasses
import os
import re
from typing import Dict, List, Optional, Tuple


@dataclasses.dataclass
class VmtlintConfig:
    # Default scan roots when the CLI gets no paths.
    paths: List[str] = dataclasses.field(default_factory=lambda: [
        "vilbert_multitask_tpu", "bench.py", "scripts"])
    # Path fragments to skip entirely (matched against the forward-slash
    # relative path, substring semantics).
    exclude: List[str] = dataclasses.field(default_factory=list)
    # Roots treated as library code for library_only rules (stray-print).
    library_roots: List[str] = dataclasses.field(default_factory=lambda: [
        "vilbert_multitask_tpu"])
    # Checked-in baseline of grandfathered findings (repo-root relative).
    baseline: Optional[str] = None
    # Findings at/above this severity fail the run without --strict.
    fail_on: str = "error"
    # Per-rule severity overrides: {"VMT105": "error", ...}
    severity: Dict[str, str] = dataclasses.field(default_factory=dict)
    # Layering contracts ([tool.vmtlint.layers] forbid = ["A -> B", ...]):
    # modules under prefix A must not import modules under prefix B.
    layers: List[Tuple[str, str]] = dataclasses.field(default_factory=list)
    # Per-rule path exclusions ([tool.vmtlint.rule_paths]): rel-path
    # prefixes a rule skips — {"VMT107": ["tests"], ...}.
    rule_paths: Dict[str, List[str]] = dataclasses.field(default_factory=dict)


_SECTION_RE = re.compile(r"^\s*\[([^\]]+)\]\s*$")
_KEY_RE = re.compile(r"^\s*([A-Za-z0-9_\-\.]+)\s*=\s*(.*)$")
_STR_RE = re.compile(r'''^(?:"([^"]*)"|'([^']*)')$''')


def _strip_comment(line: str) -> str:
    """Drop a # comment that is not inside a string literal."""
    out, quote = [], None
    for ch in line:
        if quote:
            if ch == quote:
                quote = None
        elif ch in "\"'":
            quote = ch
        elif ch == "#":
            break
        out.append(ch)
    return "".join(out).rstrip()


def _parse_value(raw: str):
    raw = raw.strip()
    m = _STR_RE.match(raw)
    if m:
        return m.group(1) if m.group(1) is not None else m.group(2)
    if raw in ("true", "false"):
        return raw == "true"
    if raw.startswith("[") and raw.endswith("]"):
        inner = raw[1:-1].strip()
        if not inner:
            return []
        return [_parse_value(part) for part in _split_array(inner)]
    try:
        return int(raw)
    except ValueError:
        return raw  # tolerate; unknown shapes are ignored by the consumer


def _split_array(inner: str) -> List[str]:
    parts, cur, quote = [], [], None
    for ch in inner:
        if quote:
            cur.append(ch)
            if ch == quote:
                quote = None
        elif ch in "\"'":
            quote = ch
            cur.append(ch)
        elif ch == ",":
            if "".join(cur).strip():
                parts.append("".join(cur).strip())
            cur = []
        else:
            cur.append(ch)
    if "".join(cur).strip():
        parts.append("".join(cur).strip())
    return parts


def parse_toml_tables(text: str) -> Dict[str, Dict[str, object]]:
    """{section: {key: value}} for the TOML subset described above."""
    tables: Dict[str, Dict[str, object]] = {}
    section = ""
    lines = text.splitlines()
    i = 0
    while i < len(lines):
        line = _strip_comment(lines[i])
        i += 1
        if not line.strip():
            continue
        m = _SECTION_RE.match(line)
        if m:
            section = m.group(1).strip()
            tables.setdefault(section, {})
            continue
        m = _KEY_RE.match(line)
        if not m:
            continue
        key, raw = m.group(1), m.group(2).strip()
        # Multiline array: keep consuming until brackets balance.
        while raw.count("[") > raw.count("]") and i < len(lines):
            raw += " " + _strip_comment(lines[i]).strip()
            i += 1
        tables.setdefault(section, {})[key] = _parse_value(raw)
    return tables


def find_pyproject(start: str) -> Optional[str]:
    cur = os.path.abspath(start)
    while True:
        cand = os.path.join(cur, "pyproject.toml")
        if os.path.isfile(cand):
            return cand
        nxt = os.path.dirname(cur)
        if nxt == cur:
            return None
        cur = nxt


def load_config(start: str = ".") -> Tuple[VmtlintConfig, Optional[str]]:
    """(config, repo_root). Falls back to defaults with root=start when no
    pyproject.toml is found walking up from ``start``."""
    cfg = VmtlintConfig()
    pyproject = find_pyproject(start)
    if pyproject is None:
        return cfg, None
    with open(pyproject, "r", encoding="utf-8") as f:
        tables = parse_toml_tables(f.read())
    main = tables.get("tool.vmtlint", {})
    for key in ("paths", "exclude", "library_roots"):
        val = main.get(key)
        if isinstance(val, list):
            setattr(cfg, key, [str(v) for v in val])
    if isinstance(main.get("baseline"), str):
        cfg.baseline = main["baseline"]
    if main.get("fail_on") in ("error", "warning"):
        cfg.fail_on = main["fail_on"]
    sev = tables.get("tool.vmtlint.severity", {})
    cfg.severity = {k: str(v) for k, v in sev.items()
                    if v in ("error", "warning")}
    layers = tables.get("tool.vmtlint.layers", {}).get("forbid")
    if isinstance(layers, list):
        cfg.layers = [c for c in (parse_layer_contract(str(v))
                                  for v in layers) if c is not None]
    for key, val in tables.get("tool.vmtlint.rule_paths", {}).items():
        if isinstance(val, list):
            cfg.rule_paths[key] = [str(v) for v in val]
    return cfg, os.path.dirname(pyproject)


def parse_layer_contract(spec: str) -> Optional[Tuple[str, str]]:
    """``"pkg.models -> pkg.serve"`` → ("pkg.models", "pkg.serve").
    Path-style prefixes (``pkg/models``) are normalized to dotted form."""
    if "->" not in spec:
        return None
    src, _, dst = spec.partition("->")

    def norm(s: str) -> str:
        return s.strip().strip("/").replace("/", ".")

    src, dst = norm(src), norm(dst)
    return (src, dst) if src and dst else None
