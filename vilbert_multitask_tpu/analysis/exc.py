"""Exception-flow analysis: the set of exception classes that can
escape each function, resolved down to every *boundary*.

The seventh analyzer tier.  Where the protocol tier proves lifecycles,
this tier proves the *failure plane*: the reference demo survived on one
broad ``try/except`` around its inference loop; our fleet replaced that
with ~10 daemon threads where an escaping exception kills the thread
silently — the queue backs up, SLOs page late, and nothing names the
culprit.

The engine is a per-function *frame IR* mirroring the CFG's conservative
try-lowering (an exception may surface at any statement boundary of a
try body; a handler observes the union of body escapes): each library
function lowers to a sequence of ``raise`` / ``call`` / ``try`` /
``guard`` items, and a monotone fixed point composes per-function
escape summaries through the call graph — witness chains are frozen on
first appearance, so the key set only grows and termination is
structural.  Raise-site inference covers ``raise X from e`` chains and
bare/alias re-raises; handler narrowing resolves tuple aliases
(``_NET_ERRORS``) through the project graph and subclass hierarchies
through a builtin + curated-external + project-class MRO table.

Boundaries — the places an escape stops being a Python exception and
becomes an operational event — are resolved with their escaping sets:

* ``thread``   — ``threading.Thread``/``Timer`` targets and Thread
  subclass ``run``; an escape here is silent thread death (VMT137)
  unless the body runs under ``obs.crash_guard`` (the runtime twin this
  tier proves complete).
* ``http-verb`` — ``do_*`` handlers; the server's dispatch contains
  escapes, so the verdict is ``server-handled``.
* ``tick``     — ``obs.Sampler`` probe callables; ``Sampler._run``
  catches per tick, so the verdict is ``caller-contained``.
* ``breaker``  — ``RetryPolicy.call(..., breaker=...)`` regions and
  manual ``preflight``/``record_failure`` frames; escapes the recording
  clause never observes are breaker-blind (VMT138).
* ``fault-site`` — every ``fault_point``; the verdict says whether the
  injected fault escapes the enclosing function.

Two cross-tier checks ride on the same flow: a broad handler that
swallows an exception while a claim/checkout still owes its terminal
(VMT139, composed with :mod:`analysis.proto`), and outbound
error/verdict strings drifting from the vocabulary the txn tier
recovered plus the library's own non-handler verdict sites (VMT140,
with did-you-mean).

Run generatively (``python -m vilbert_multitask_tpu.analysis exc``)
the tier emits ``FAILURE_SURFACE.json`` — every boundary with its
escaping set and verdict, the handler inventory, and the project
exception taxonomy — committed and drift-gated (``exc --check`` in
check.sh).

Everything here is stdlib-only (the analysis-layer contract).
"""

from __future__ import annotations

import ast
import builtins
import difflib
import json
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .proto import proto_flow
from .txn import txn_flow

EXC_VERSION = 1
MANIFEST_NAME = "FAILURE_SURFACE.json"

# Paths that never host boundaries or findings: test idioms raise and
# swallow on purpose.
_NON_LIBRARY_HEADS = ("tests", "scripts")

# Witness chains stop growing past this depth (the class keeps
# propagating; only the chain is capped).
_MAX_CHAIN = 6
# A by-name callee fallback unions at most this many candidates.
_MAX_CANDIDATES = 4
# Fixed-point round budget — structural monotonicity converges in a
# handful of rounds; the cap turns a bug into silence, not a hang.
_ROUND_CAP = 24

# Control-flow exceptions that are not failures: a thread exiting on
# SystemExit is a shutdown, not a death.
_EXIT_EXCS = {"SystemExit", "KeyboardInterrupt", "GeneratorExit",
              "StopIteration", "StopAsyncIteration"}

_BROAD = ("Exception", "BaseException")
_THREAD_CTORS = ("threading.Thread", "threading.Timer")
# ``with crash_guard("name"):`` / ``with obs.crash_guard(...):`` marks a
# runtime-guarded region: Exception-rooted escapes are recorded and
# swallowed there (obs/watchdog.py), exit exceptions pass through.
_CRASH_GUARD_NAMES = {"crash_guard"}

# Leaf method names too generic for the by-name union fallback —
# matching ``.get()`` against every project ``get`` method would invent
# escapes out of dictionaries.
_GENERIC_LEAVES = {
    "get", "put", "set", "add", "pop", "update", "items", "keys",
    "values", "append", "extend", "insert", "remove", "clear", "copy",
    "close", "open", "read", "write", "flush", "join", "start", "stop",
    "run", "send", "recv", "encode", "decode", "strip", "split",
    "format", "wait", "notify", "acquire", "release", "register",
    "record", "next", "reset",
}


def _builtin_mros() -> Dict[str, Tuple[str, ...]]:
    table: Dict[str, Tuple[str, ...]] = {}
    for name in dir(builtins):
        obj = getattr(builtins, name, None)
        if isinstance(obj, type) and issubclass(obj, BaseException):
            table[name] = tuple(c.__name__ for c in obj.__mro__
                                if issubclass(c, BaseException))
    return table


_BUILTIN_MRO = _builtin_mros()

# Curated leaves of the stdlib exception classes the serving stack
# actually meets (urllib, sockets, sqlite3, json, queue).  Unknown
# classes default to ``(name, Exception, BaseException)`` — handler
# narrowing stays sound for anything Exception-rooted.
_KNOWN_EXTERNAL: Dict[str, Tuple[str, ...]] = {
    "HTTPError": ("HTTPError", "URLError", "OSError",
                  "Exception", "BaseException"),
    "URLError": ("URLError", "OSError", "Exception", "BaseException"),
    "timeout": ("timeout", "OSError", "Exception", "BaseException"),
    "Empty": ("Empty", "Exception", "BaseException"),
    "Full": ("Full", "Exception", "BaseException"),
    "JSONDecodeError": ("JSONDecodeError", "ValueError",
                        "Exception", "BaseException"),
    "Error": ("Error", "Exception", "BaseException"),
    "DatabaseError": ("DatabaseError", "Error",
                      "Exception", "BaseException"),
    "OperationalError": ("OperationalError", "DatabaseError", "Error",
                         "Exception", "BaseException"),
    "IntegrityError": ("IntegrityError", "DatabaseError", "Error",
                       "Exception", "BaseException"),
}


def _is_library(rel_path: str) -> bool:
    head = rel_path.split("/", 1)[0]
    if head in _NON_LIBRARY_HEADS:
        return False
    base = rel_path.rsplit("/", 1)[-1]
    return not (base.startswith("test_") or base == "conftest.py")


def _witness(path: str, line: int, note: str) -> dict:
    return {"path": path, "line": line, "message": note}


# ---------------------------------------------------------------------------
# The flow
# ---------------------------------------------------------------------------

class ExcFlow:
    """Interprocedural escape facts over the whole project.

    Built once per project (see :func:`exc_flow`) and consumed by the
    VMT137-140 rules and by :func:`build_failure_surface`.  All finding
    lists hold plain dicts ``{"path", "line", "col", "message"[,
    "flows"]}`` so rules stay thin adapters."""

    def __init__(self, project) -> None:
        self.project = project
        self.cg = project.callgraph
        self._mro_cache: Dict[str, Tuple[str, ...]] = {}
        self.classes: Dict[str, dict] = {}
        self._build_class_index()
        # Leaf method name -> qualname iff unique among library
        # functions, plus the full leaf -> candidates map for the
        # bounded union fallback (``self.queue.claim`` must union both
        # DurableQueue.claim and the remote twin).
        self._unique: Dict[str, Optional[str]] = {}
        self._by_leaf: Dict[str, List[str]] = {}
        # All library quals, fixed BEFORE frame building: frames are
        # built in sort order, so membership in self.frames would drop
        # every callee that sorts after its caller.
        self._library: Set[str] = set()
        for qual in sorted(self.cg.functions):
            fn = self.cg.functions[qual]
            if not _is_library(fn.module.ctx.rel_path):
                continue
            self._library.add(qual)
            leaf = fn.scope[-1]
            self._unique[leaf] = (
                None if leaf in self._unique else qual)
            if fn.cls_scope:
                self._by_leaf.setdefault(leaf, []).append(qual)
        # Frame IR per library function: (items, has_guard).
        self.frames: Dict[str, Tuple[list, bool]] = {}
        for qual in sorted(self.cg.functions):
            fn = self.cg.functions[qual]
            if _is_library(fn.module.ctx.rel_path):
                self.frames[qual] = self._build_frame(fn)
        # qual -> {exception name -> frozen witness chain}.
        self.summaries: Dict[str, Dict[str, tuple]] = {}
        self._solve()
        self.boundaries: List[dict] = []
        self._discover_boundaries()
        # Finding dicts, populated by the passes below.
        self.thread_findings: List[dict] = []
        self.breaker_findings: List[dict] = []
        self.shadow_findings: List[dict] = []
        self.frame_findings: List[dict] = []
        self._check_thread_escapes()
        self._check_breaker_blind()
        self._check_handler_shadows()
        self._check_frame_drift()

    # ------------------------------------------------------------ taxonomy
    def _build_class_index(self) -> None:
        """Project exception classes: every library ``ClassDef`` whose
        base chain roots in a known exception, to a fixed point (so
        ``class Child(ProjectError)`` lands once ``ProjectError`` has)."""
        candidates: Dict[str, Tuple[List[str], str, int]] = {}
        for mod in sorted(self.project.modules.values(),
                          key=lambda m: m.name):
            ctx = mod.ctx
            if not _is_library(ctx.rel_path):
                continue
            for node in ast.walk(ctx.tree):
                if not isinstance(node, ast.ClassDef) or not node.bases:
                    continue
                leaves = []
                for b in node.bases:
                    dotted = ctx.resolve(b)
                    leaf = dotted.rsplit(".", 1)[-1] if dotted else (
                        b.attr if isinstance(b, ast.Attribute) else "")
                    if leaf:
                        leaves.append(leaf)
                candidates.setdefault(
                    node.name, (leaves, ctx.rel_path, node.lineno))
        known = set(_BUILTIN_MRO) | set(_KNOWN_EXTERNAL)
        changed = True
        while changed:
            changed = False
            for name, (bases, path, line) in candidates.items():
                if name in self.classes:
                    continue
                if any(b in known or b in self.classes for b in bases):
                    self.classes[name] = {
                        "bases": bases, "path": path, "line": line}
                    changed = True

    def _mro(self, name: str) -> Tuple[str, ...]:
        cached = self._mro_cache.get(name)
        if cached is not None:
            return cached
        self._mro_cache[name] = (name,)  # cycle guard
        if name in _BUILTIN_MRO:
            out = _BUILTIN_MRO[name]
        elif name in _KNOWN_EXTERNAL:
            out = _KNOWN_EXTERNAL[name]
        elif name in self.classes:
            acc: List[str] = [name]
            for b in self.classes[name]["bases"]:
                for x in self._mro(b):
                    if x not in acc:
                        acc.append(x)
            out = tuple(acc)
        else:
            # Unknown class: assume Exception-rooted (the sound default
            # for handler narrowing — broad handlers still catch it).
            out = (name, "Exception", "BaseException")
        self._mro_cache[name] = out
        return out

    # ------------------------------------------------------------- helpers
    def _rel_path(self, qual: str) -> str:
        return self.cg.functions[qual].module.ctx.rel_path

    def _display(self, qual: str) -> str:
        mod, scope = qual.split(":", 1)
        return f"{mod}.{scope}"

    def _call_candidates(self, fn, call: ast.Call) -> tuple:
        """Project callees a call may reach: exact resolution first,
        then the by-name unique fallback, then a bounded union over
        same-leaf methods (receiver types are invisible — missing
        ``queue.claim``'s remote twin would hide its escapes)."""
        qual = self.cg.resolve_callable(
            fn.module, call.func, fn.scope, fn.cls_scope)
        if qual is not None:
            return (qual,) if qual in self._library else ()
        func = call.func
        if isinstance(func, ast.Attribute) and not (
                isinstance(func.value, ast.Name)
                and func.value.id == "self"):
            leaf = func.attr
            uq = self._unique.get(leaf)
            if uq is not None and uq != fn.qualname \
                    and uq in self._library:
                return (uq,)
            if leaf not in _GENERIC_LEAVES:
                cands = tuple(
                    q for q in self._by_leaf.get(leaf, ())
                    if q != fn.qualname and q in self._library)
                if 0 < len(cands) <= _MAX_CANDIDATES:
                    return cands
        return ()

    # ------------------------------------------------------------ frame IR
    def _raise_name(self, fn, node: ast.Raise,
                    aliases: frozenset) -> Optional[str]:
        """Class name a ``raise`` throws: ``None`` means re-raise the
        active exception (bare raise, or raising the handler alias);
        ``<dynamic>`` means a value only broad handlers can catch."""
        exc = node.exc
        if exc is None:
            return None
        if isinstance(exc, ast.Call):
            exc = exc.func
        if isinstance(exc, ast.Name) and exc.id in aliases:
            return None
        if isinstance(exc, (ast.Name, ast.Attribute)):
            dotted = fn.module.ctx.resolve(exc)
            leaf = dotted.rsplit(".", 1)[-1] if dotted else (
                exc.attr if isinstance(exc, ast.Attribute) else "")
            if leaf and (leaf in _BUILTIN_MRO or leaf in _KNOWN_EXTERNAL
                         or leaf in self.classes or leaf[:1].isupper()):
                return leaf
        return "<dynamic>"

    def _expr_calls(self, fn, expr: Optional[ast.AST]) -> list:
        """``call`` items for every call inside an expression (lambda
        bodies excluded — they don't run at statement time)."""
        items: list = []
        if expr is None:
            return items
        stack: List[ast.AST] = [expr]
        while stack:
            node = stack.pop()
            if isinstance(node, (ast.Lambda, ast.FunctionDef,
                                 ast.AsyncFunctionDef, ast.ClassDef)):
                continue
            if isinstance(node, ast.Call):
                quals = self._call_candidates(fn, node)
                if quals:
                    func = node.func
                    disp = func.attr if isinstance(func, ast.Attribute) \
                        else (func.id if isinstance(func, ast.Name)
                              else "<call>")
                    items.append(("call", quals, node.lineno, disp))
            stack.extend(ast.iter_child_nodes(node))
        return items

    def _handler_type_names(self, mod, expr: Optional[ast.AST],
                            depth: int = 0) -> Optional[Tuple[str, ...]]:
        """Leaf class names a handler clause declares — ``None`` for a
        bare ``except``; tuple aliases resolve through the graph."""
        if expr is None:
            return None
        names = self._type_names(mod, expr, depth)
        return tuple(names) if names else ("BaseException",)

    def _type_names(self, mod, expr: ast.AST, depth: int) -> List[str]:
        if depth > 4:
            return []
        if isinstance(expr, (ast.Tuple, ast.List)):
            out: List[str] = []
            for e in expr.elts:
                out.extend(self._type_names(mod, e, depth + 1))
            return out
        if isinstance(expr, ast.Name):
            leaf = expr.id
            if leaf in _BUILTIN_MRO or leaf in _KNOWN_EXTERNAL \
                    or leaf in self.classes:
                return [leaf]
            local = mod.symbols.get(leaf)
            if isinstance(local, ast.Assign) \
                    and isinstance(local.value, (ast.Tuple, ast.List)):
                return self._type_names(mod, local.value, depth + 1)
            target = mod.refs.get(leaf)
            if target:
                resolved = self.project.resolve_symbol(target)
                if resolved is not None:
                    tmod, sym = resolved
                    node = tmod.symbols.get(sym.split(".")[0]) if sym \
                        else None
                    if isinstance(node, ast.Assign) and isinstance(
                            node.value, (ast.Tuple, ast.List)):
                        return self._type_names(tmod, node.value,
                                                depth + 1)
                    if isinstance(node, ast.ClassDef):
                        return [node.name]
                return [target.rsplit(".", 1)[-1]]
            return [leaf]
        if isinstance(expr, ast.Attribute):
            dotted = mod.ctx.resolve(expr)
            leaf = dotted.rsplit(".", 1)[-1] if dotted else expr.attr
            if leaf in _BUILTIN_MRO or leaf in _KNOWN_EXTERNAL \
                    or leaf in self.classes:
                return [leaf]
            if dotted:
                resolved = self.project.resolve_symbol(dotted)
                if resolved is not None:
                    tmod, sym = resolved
                    node = tmod.symbols.get(sym.split(".")[0]) if sym \
                        else None
                    if isinstance(node, ast.Assign) and isinstance(
                            node.value, (ast.Tuple, ast.List)):
                        return self._type_names(tmod, node.value,
                                                depth + 1)
                    if isinstance(node, ast.ClassDef):
                        return [node.name]
            return [leaf]
        return []

    def _is_crash_guard(self, fn, stmt) -> bool:
        for item in stmt.items:
            call = item.context_expr
            if not isinstance(call, ast.Call):
                continue
            func = call.func
            leaf = func.attr if isinstance(func, ast.Attribute) else (
                func.id if isinstance(func, ast.Name) else "")
            if leaf in _CRASH_GUARD_NAMES:
                return True
        return False

    def _build_frame(self, fn) -> Tuple[list, bool]:
        guard_seen = [False]
        params = {a.arg for a in fn.node.args.args} | {
            a.arg for a in fn.node.args.kwonlyargs}

        def build(stmts, aliases: frozenset) -> list:
            items: list = []
            for st in stmts:
                if isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.ClassDef)):
                    continue
                if isinstance(st, ast.Raise):
                    items.extend(self._expr_calls(fn, st.exc))
                    items.extend(self._expr_calls(fn, st.cause))
                    items.append(("raise",
                                  self._raise_name(fn, st, aliases),
                                  st.lineno))
                elif isinstance(st, ast.Try):
                    handlers = []
                    for h in st.handlers:
                        types = self._handler_type_names(
                            fn.module, h.type)
                        if types is not None and h.type is not None:
                            exprs = h.type.elts if isinstance(
                                h.type, ast.Tuple) else [h.type]
                            if any(isinstance(e, ast.Name)
                                   and e.id in params for e in exprs):
                                # ``except retry_on`` — the clause's
                                # types only exist at the call site:
                                # catches nothing provable, re-raises
                                # anything.
                                types = ("<dynamic>",)
                        broad = types is None or any(
                            t in _BROAD for t in types)
                        h_aliases = aliases | ({h.name} if h.name
                                               else set())
                        handlers.append(
                            (types, build(h.body, h_aliases),
                             h.lineno, broad))
                    items.append((
                        "try", st.lineno, build(st.body, aliases),
                        handlers, build(st.orelse, aliases),
                        build(st.finalbody, aliases)))
                elif isinstance(st, (ast.With, ast.AsyncWith)):
                    for item in st.items:
                        items.extend(
                            self._expr_calls(fn, item.context_expr))
                    if self._is_crash_guard(fn, st):
                        guard_seen[0] = True
                        items.append(("guard", build(st.body, aliases),
                                      st.lineno))
                    else:
                        items.extend(build(st.body, aliases))
                elif isinstance(st, ast.If):
                    items.extend(self._expr_calls(fn, st.test))
                    items.extend(build(st.body, aliases))
                    items.extend(build(st.orelse, aliases))
                elif isinstance(st, ast.While):
                    items.extend(self._expr_calls(fn, st.test))
                    items.extend(build(st.body, aliases))
                    items.extend(build(st.orelse, aliases))
                elif isinstance(st, (ast.For, ast.AsyncFor)):
                    items.extend(self._expr_calls(fn, st.iter))
                    items.extend(build(st.body, aliases))
                    items.extend(build(st.orelse, aliases))
                else:
                    items.extend(self._expr_calls(fn, st))
            return items

        return build(fn.node.body, frozenset()), guard_seen[0]

    # ---------------------------------------------------------- fixed point
    @staticmethod
    def _merge(out: Dict[str, tuple], name: str, chain: tuple) -> None:
        # Chains freeze on first appearance: the key set is the only
        # thing that grows, which is what makes the solve monotone.
        if name not in out:
            out[name] = chain

    def _caught(self, name: str, types: Optional[Tuple[str, ...]]
                ) -> bool:
        if types is None:
            return True
        if name == "<dynamic>":
            return any(t in _BROAD for t in types)
        mro = self._mro(name)
        return any(t in mro for t in types)

    def _eval_items(self, qual: str, items: list,
                    reraise: Dict[str, tuple],
                    out: Dict[str, tuple]) -> None:
        rel = self._rel_path(qual)
        disp = self._display(qual)
        for it in items:
            kind = it[0]
            if kind == "raise":
                name, line = it[1], it[2]
                if name is None:
                    for n, chain in reraise.items():
                        step = _witness(
                            rel, line, f"re-raised in `{disp}`")
                        new = chain if len(chain) >= _MAX_CHAIN \
                            else chain + (step,)
                        self._merge(out, n, new)
                else:
                    self._merge(out, name, (_witness(
                        rel, line, f"`raise {name}` in `{disp}`"),))
            elif kind == "call":
                quals, line, cdisp = it[1], it[2], it[3]
                for cq in quals:
                    for n, chain in self.summaries.get(cq, {}).items():
                        step = _witness(
                            rel, line,
                            f"escapes `{self._display(cq)}` into "
                            f"`{disp}` via `{cdisp}(...)`")
                        new = chain if len(chain) >= _MAX_CHAIN \
                            else chain + (step,)
                        self._merge(out, n, new)
            elif kind == "guard":
                body_out: Dict[str, tuple] = {}
                self._eval_items(qual, it[1], reraise, body_out)
                for n, chain in body_out.items():
                    # crash_guard records-and-swallows Exception-rooted
                    # escapes; exit exceptions pass through.
                    if "Exception" not in self._mro(n):
                        self._merge(out, n, chain)
            elif kind == "try":
                _line, body, handlers, orelse, final = it[1:]
                body_out = {}
                self._eval_items(qual, body, reraise, body_out)
                remaining = dict(body_out)
                for types, hbody, hline, _broad in handlers:
                    entering = {
                        n: remaining[n] for n in sorted(remaining)
                        if self._caught(n, types)}
                    for n in entering:
                        del remaining[n]
                    hreraise = entering
                    if not hreraise and types is not None:
                        # No proven inflow — a bare re-raise still
                        # forwards whatever the clause declares.
                        hreraise = {
                            t: (_witness(rel, hline,
                                         f"handler for `{t}` in "
                                         f"`{disp}`"),)
                            for t in types}
                    self._eval_items(qual, hbody, hreraise, out)
                for n, chain in remaining.items():
                    self._merge(out, n, chain)
                self._eval_items(qual, orelse, reraise, out)
                self._eval_items(qual, final, reraise, out)

    def _solve(self) -> None:
        for qual in self.frames:
            self.summaries[qual] = {}
        rounds = 0
        changed = True
        while changed and rounds < _ROUND_CAP:
            changed = False
            rounds += 1
            for qual in sorted(self.frames):
                out: Dict[str, tuple] = {}
                self._eval_items(qual, self.frames[qual][0], {}, out)
                summ = self.summaries[qual]
                for n, chain in out.items():
                    if n not in summ:
                        summ[n] = chain
                        changed = True
        self.rounds = rounds

    def escapes(self, qual: str) -> Dict[str, tuple]:
        """Failure escapes of one function (exit exceptions dropped)."""
        return {n: c for n, c in self.summaries.get(qual, {}).items()
                if n not in _EXIT_EXCS}

    # --------------------------------------------------------- boundaries
    def _thread_name(self, mod, call: ast.Call) -> Tuple[str, bool]:
        """(thread name, daemon flag) from a Thread/Timer ctor call —
        ``prefix-*`` for f-strings, module constants resolved, else
        ``<unnamed>``."""
        name = "<unnamed>"
        daemon = False
        for kw in call.keywords:
            if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
                daemon = bool(kw.value.value)
            if kw.arg != "name":
                continue
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                name = v.value
            elif isinstance(v, ast.JoinedStr):
                prefix = ""
                for part in v.values:
                    if isinstance(part, ast.Constant) \
                            and isinstance(part.value, str):
                        prefix = part.value
                        break
                name = f"{prefix}*"
            elif isinstance(v, (ast.Name, ast.Attribute)):
                resolved = self._constant_str(mod, v)
                name = resolved if resolved is not None else "<dynamic>"
        return name, daemon

    def _constant_str(self, mod, expr: ast.AST) -> Optional[str]:
        """A module-level string constant behind a Name/Attribute, or
        None (``name=obs.SAMPLER_THREAD_NAME`` resolves here)."""
        if isinstance(expr, ast.Name):
            node = mod.symbols.get(expr.id)
            if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Constant) and isinstance(
                    node.value.value, str):
                return node.value.value
            dotted = mod.refs.get(expr.id, "")
        else:
            dotted = mod.ctx.resolve(expr)
        if dotted:
            resolved = self.project.resolve_symbol(dotted)
            if resolved is not None:
                tmod, sym = resolved
                node = tmod.symbols.get(sym.split(".")[0]) if sym \
                    else None
                if isinstance(node, (ast.Assign, ast.AnnAssign)):
                    value = node.value
                    if isinstance(value, ast.Constant) and isinstance(
                            value.value, str):
                        return value.value
        return None

    def _entry_quals(self, mod, call: ast.Call) -> Tuple[str, ...]:
        """Entry callables of a thread ctor, exact then by-name."""
        targets = [kw.value for kw in call.keywords
                   if kw.arg in ("target", "function")]
        scope, cls = self.cg._lexical_scope(mod, call)
        out: List[str] = []
        for t in targets:
            qual = self.cg.resolve_callable(mod, t, scope, cls)
            if qual is not None:
                out.append(qual)
            elif isinstance(t, ast.Attribute):
                for q in sorted(self.cg.functions):
                    fnode = self.cg.functions[q]
                    if fnode.scope[-1] == t.attr and fnode.cls_scope \
                            and _is_library(fnode.module.ctx.rel_path):
                        out.append(q)
        return tuple(dict.fromkeys(out))

    def _boundary_escapes(self, quals: Tuple[str, ...]
                          ) -> Dict[str, tuple]:
        merged: Dict[str, tuple] = {}
        for q in quals:
            for n, chain in self.escapes(q).items():
                self._merge(merged, n, chain)
        return merged

    def _add_boundary(self, **kw) -> dict:
        entry = {
            "kind": kw["kind"],
            "name": kw["name"],
            "path": kw["path"],
            "line": kw["line"],
            "entries": sorted(self._display(q) for q in
                              kw.get("quals", ())),
            "daemon": kw.get("daemon", False),
            "guard": kw.get("guard", False),
            "escapes": kw.get("escapes", {}),
            "verdict": kw["verdict"],
        }
        self.boundaries.append(entry)
        return entry

    def _discover_boundaries(self) -> None:
        for mod in sorted(self.project.modules.values(),
                          key=lambda m: m.name):
            ctx = mod.ctx
            if not _is_library(ctx.rel_path):
                continue
            for node in ast.walk(ctx.tree):
                if isinstance(node, ast.Call):
                    self._thread_boundary(mod, node)
                    self._tick_boundary(mod, node)
                    self._fault_boundary(mod, node)
                elif isinstance(node, ast.ClassDef):
                    self._class_boundaries(mod, node)
        # breaker boundaries ride on their own pass (they need the
        # recording-clause analysis VMT138 shares).

    def _thread_boundary(self, mod, call: ast.Call) -> None:
        if mod.ctx.resolve(call.func) not in _THREAD_CTORS:
            return
        name, daemon = self._thread_name(mod, call)
        quals = self._entry_quals(mod, call)
        guard = any(self.frames.get(q, ((), False))[1] for q in quals)
        escapes = self._boundary_escapes(quals)
        if not quals:
            verdict = "unresolved"
        elif escapes:
            verdict = "escapes"
        elif guard:
            verdict = "guarded"
        else:
            verdict = "clean"
        self._add_boundary(
            kind="thread", name=name, path=mod.ctx.rel_path,
            line=call.lineno, quals=quals, daemon=daemon, guard=guard,
            escapes=escapes, verdict=verdict)

    def _class_boundaries(self, mod, cls: ast.ClassDef) -> None:
        bases = {mod.ctx.resolve(b) for b in cls.bases}
        handler = bases & self.cg._THREAD_VERB_BASES
        thread_sub = "threading.Thread" in bases
        if not (handler or thread_sub):
            return
        for stmt in cls.body:
            if not isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            fnode = self.cg.by_node.get(id(stmt))
            if fnode is None:
                continue
            if handler and stmt.name.startswith("do_"):
                self._add_boundary(
                    kind="http-verb", name=f"{cls.name}.{stmt.name}",
                    path=mod.ctx.rel_path, line=stmt.lineno,
                    quals=(fnode.qualname,),
                    escapes=self.escapes(fnode.qualname),
                    verdict="server-handled")
            if thread_sub and stmt.name == "run":
                escapes = self.escapes(fnode.qualname)
                guard = self.frames.get(
                    fnode.qualname, ((), False))[1]
                verdict = "escapes" if escapes else (
                    "guarded" if guard else "clean")
                self._add_boundary(
                    kind="thread", name=f"{cls.name}.run",
                    path=mod.ctx.rel_path, line=stmt.lineno,
                    quals=(fnode.qualname,), daemon=True, guard=guard,
                    escapes=escapes, verdict=verdict)

    def _tick_boundary(self, mod, call: ast.Call) -> None:
        func = call.func
        leaf = func.attr if isinstance(func, ast.Attribute) else (
            func.id if isinstance(func, ast.Name) else "")
        if leaf != "Sampler":
            return
        targets = [kw.value for kw in call.keywords
                   if kw.arg == "sample_fn"]
        if len(call.args) >= 2:
            targets.append(call.args[1])
        scope, cls = self.cg._lexical_scope(mod, call)
        quals: List[str] = []
        for t in targets:
            qual = self.cg.resolve_callable(mod, t, scope, cls)
            if qual is not None:
                quals.append(qual)
        if not quals:
            return
        quals_t = tuple(dict.fromkeys(quals))
        self._add_boundary(
            kind="tick", name="obs-sampler", path=mod.ctx.rel_path,
            line=call.lineno, quals=quals_t,
            escapes=self._boundary_escapes(quals_t),
            verdict="caller-contained")

    def _fault_boundary(self, mod, call: ast.Call) -> None:
        func = call.func
        leaf = func.attr if isinstance(func, ast.Attribute) else (
            func.id if isinstance(func, ast.Name) else "")
        if leaf != "fault_point" or not call.args:
            return
        site = call.args[0]
        if not (isinstance(site, ast.Constant)
                and isinstance(site.value, str)):
            return
        enclosing = mod.ctx.enclosing_function(call)
        fnode = self.cg.by_node.get(id(enclosing)) \
            if enclosing is not None else None
        quals = (fnode.qualname,) if fnode is not None else ()
        escapes = self._boundary_escapes(quals)
        verdict = "propagates" if "FaultInjected" in escapes \
            else "absorbed"
        self._add_boundary(
            kind="fault-site", name=site.value, path=mod.ctx.rel_path,
            line=call.lineno, quals=quals, escapes=escapes,
            verdict=verdict)

    # ------------------------------------------------------------- VMT137
    def _check_thread_escapes(self) -> None:
        seen: Set[Tuple[str, str]] = set()
        for b in self.boundaries:
            if b["kind"] != "thread" or b["verdict"] != "escapes":
                continue
            key = (b["path"], b["name"])
            if key in seen:
                continue
            seen.add(key)
            names = sorted(b["escapes"])
            shown = ", ".join(f"`{n}`" for n in names[:3])
            if len(names) > 3:
                shown += f" (+{len(names) - 3} more)"
            self.thread_findings.append({
                "path": b["path"], "line": b["line"], "col": 0,
                "message": (
                    f"thread `{b['name']}` entry "
                    f"{' / '.join(b['entries']) or '<target>'} lets "
                    f"{shown} escape — an escaping exception kills the "
                    f"thread silently; run the loop body under "
                    f"`obs.crash_guard(...)` so the death is recorded "
                    f"and `/healthz` turns unready"),
                "flows": [list(b["escapes"][n]) for n in names[:3]],
            })

    # ------------------------------------------------------------- VMT138
    def _breaker_call_sites(self) -> Iterator[Tuple[object, ast.Call]]:
        for mod in sorted(self.project.modules.values(),
                          key=lambda m: m.name):
            if not _is_library(mod.ctx.rel_path):
                continue
            for node in ast.walk(mod.ctx.tree):
                if isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "call" \
                        and any(kw.arg == "breaker"
                                and not (isinstance(kw.value,
                                                    ast.Constant)
                                         and kw.value.value is None)
                                for kw in node.keywords):
                    yield mod, node

    def _kw_types(self, mod, call: ast.Call, name: str,
                  default: Optional[Tuple[str, ...]]
                  ) -> Optional[Tuple[str, ...]]:
        for kw in call.keywords:
            if kw.arg == name:
                return tuple(self._type_names(mod, kw.value, 0)) or None
        return default

    def _find_try(self, items: list, line: int):
        for it in items:
            if it[0] == "try":
                if it[1] == line:
                    return it
                for sub in (it[2], it[4], it[5]):
                    found = self._find_try(sub, line)
                    if found is not None:
                        return found
                for _t, hbody, _l, _b in it[3]:
                    found = self._find_try(hbody, line)
                    if found is not None:
                        return found
            elif it[0] == "guard":
                found = self._find_try(it[1], line)
                if found is not None:
                    return found
        return None

    def _check_breaker_blind(self) -> None:
        # (a) RetryPolicy.call(..., breaker=...) sites: no_retry classes
        # re-raise without recording by construction, and callee escapes
        # outside retry_on are never seen by the recording clause.
        for mod, call in self._breaker_call_sites():
            ctx = mod.ctx
            site = None
            for kw in call.keywords:
                if kw.arg == "site" and isinstance(kw.value,
                                                   ast.Constant):
                    site = str(kw.value.value)
            label = site or f"{ctx.rel_path}:{call.lineno}"
            retry_on = self._kw_types(mod, call, "retry_on",
                                      ("Exception",))
            no_retry = self._kw_types(mod, call, "no_retry", ()) or ()
            blind: Dict[str, tuple] = {}
            for t in no_retry:
                blind[t] = (_witness(
                    ctx.rel_path, call.lineno,
                    f"`no_retry` re-raises `{t}` without recording a "
                    f"breaker failure"),)
            enclosing = ctx.enclosing_function(call)
            fnode = self.cg.by_node.get(id(enclosing)) \
                if enclosing is not None else None
            if fnode is not None and call.args:
                callee = ast.Call(func=call.args[0], args=[],
                                  keywords=[])
                for cq in self._call_candidates(
                        fnode, ast.copy_location(callee, call)):
                    for n, chain in self.escapes(cq).items():
                        if not self._caught(n, retry_on):
                            self._merge(blind, n, chain)
            self._breaker_boundary(ctx.rel_path, call.lineno, label,
                                   blind)
        # (b) manual regions: preflight() followed by a try whose
        # recording handlers (calling record_failure) define what the
        # breaker observes.
        for qual in sorted(self.frames):
            fn = self.cg.functions[qual]
            pre_lines = [
                n.lineno for n in self.cg.own_call_nodes(fn)
                if isinstance(n.func, ast.Attribute)
                and n.func.attr == "preflight"]
            if not pre_lines:
                continue
            ctx = fn.module.ctx
            trys = [n for n in self.cg._own_nodes(fn.node)
                    if isinstance(n, ast.Try)
                    and n.lineno >= min(pre_lines)]
            label = f"{self._display(qual)}"
            # Parameter-typed clauses (``except retry_on``/``no_retry``
            # inside the policy engine itself) are dynamic — the types
            # only exist at the call site, which pass (a) analyzes.
            params = {a.arg for a in fn.node.args.args} | {
                a.arg for a in fn.node.args.kwonlyargs}

            def is_dynamic(h) -> bool:
                exprs = h.type.elts if isinstance(h.type, ast.Tuple) \
                    else [h.type]
                return any(isinstance(e, ast.Name) and e.id in params
                           for e in exprs if e is not None)

            if not trys:
                blind = self.escapes(qual)
                if blind:
                    self._breaker_boundary(
                        ctx.rel_path, min(pre_lines), label, blind,
                        note="no recording clause after preflight")
                else:
                    self._breaker_boundary(
                        ctx.rel_path, min(pre_lines), label, {})
                continue
            for t in trys:
                if any(is_dynamic(h) for h in t.handlers
                       if h.type is not None):
                    self._add_boundary(
                        kind="breaker", name=label,
                        path=ctx.rel_path, line=t.lineno,
                        escapes={}, verdict="dynamic")
                    continue
                recording: List[str] = []
                for h in t.handlers:
                    if any(isinstance(n, ast.Attribute)
                           and n.attr == "record_failure"
                           for n in ast.walk(h)):
                        types = self._handler_type_names(
                            fn.module, h.type)
                        if types is None:
                            recording = list(_BROAD)
                            break
                        recording.extend(types)
                frame_try = self._find_try(self.frames[qual][0],
                                           t.lineno)
                try_out: Dict[str, tuple] = {}
                if frame_try is not None:
                    self._eval_items(qual, [frame_try], {}, try_out)
                rec_types = tuple(recording)  # () = nothing observed
                blind = {
                    n: c for n, c in try_out.items()
                    if n not in _EXIT_EXCS
                    and not self._caught(n, rec_types)}
                self._breaker_boundary(ctx.rel_path, t.lineno, label,
                                       blind)

    def _breaker_boundary(self, path: str, line: int, label: str,
                          blind: Dict[str, tuple],
                          note: str = "") -> None:
        verdict = "blind" if blind else "observed"
        self._add_boundary(
            kind="breaker", name=label, path=path, line=line,
            escapes=blind, verdict=verdict)
        if not blind:
            return
        names = sorted(blind)
        shown = ", ".join(f"`{n}`" for n in names[:3])
        extra = f" ({note})" if note else ""
        self.breaker_findings.append({
            "path": path, "line": line, "col": 0,
            "message": (
                f"breaker region `{label}` lets {shown} escape without "
                f"recording a failure{extra} — the breaker never trips "
                f"on this class, so a deterministic fault loops at "
                f"full request rate"),
            "flows": [list(blind[n]) for n in names[:3]],
        })

    # ------------------------------------------------------------- VMT139
    def _check_handler_shadows(self) -> None:
        pf = proto_flow(self.project)
        for qual in sorted(pf.summaries):
            info = pf.summaries[qual]
            if not info.acquire_calls:
                continue
            fn = self.cg.functions[qual]
            ctx = fn.module.ctx
            acquire_lines = sorted(a[2] for a in info.acquire_calls)
            terminal_lines = self._terminal_lines(pf, fn)
            for node in self.cg._own_nodes(fn.node):
                if not isinstance(node, ast.Try):
                    continue
                for h in node.handlers:
                    types = self._handler_type_names(fn.module, h.type)
                    broad = types is None or any(
                        t in _BROAD for t in types)
                    if not broad:
                        continue
                    if any(isinstance(n, ast.Raise)
                           for n in ast.walk(h)):
                        continue
                    if self._handler_reaches_terminal(pf, fn, h):
                        continue
                    owing = [
                        a for a in acquire_lines if a < h.lineno
                        and not any(a < t < node.lineno
                                    for t in terminal_lines)]
                    if not owing:
                        continue
                    self.shadow_findings.append({
                        "path": ctx.rel_path, "line": h.lineno,
                        "col": h.col_offset,
                        "message": (
                            f"broad `except` in "
                            f"`{self._display(qual)}` swallows the "
                            f"exception while the handle acquired at "
                            f"line {owing[0]} still owes a terminal — "
                            f"the claim leaks until the visibility "
                            f"sweep; reach `ack`/`nack`/`release` (or "
                            f"`_fail_job`) inside the handler or "
                            f"re-raise"),
                    })

    def _terminal_lines(self, pf, fn) -> List[int]:
        lines: List[int] = []
        for call in self.cg.own_call_nodes(fn):
            func = call.func
            leaf = func.attr if isinstance(func, ast.Attribute) else (
                func.id if isinstance(func, ast.Name) else "")
            if leaf and pf.registry.terminal_protocol(leaf) is not None:
                lines.append(call.lineno)
                continue
            cq = pf._resolve_call(fn, call)
            if cq is not None:
                csum = pf.summaries.get(cq)
                if csum is not None and csum.terminal_params:
                    lines.append(call.lineno)
        return lines

    def _handler_reaches_terminal(self, pf, fn, handler) -> bool:
        for node in ast.walk(handler):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            leaf = func.attr if isinstance(func, ast.Attribute) else (
                func.id if isinstance(func, ast.Name) else "")
            if leaf and pf.registry.terminal_protocol(leaf) is not None:
                return True
            cq = pf._resolve_call(fn, node)
            if cq is not None:
                csum = pf.summaries.get(cq)
                if csum is not None and csum.terminal_params:
                    return True
        return False

    # ------------------------------------------------------------- VMT140
    def _check_frame_drift(self) -> None:
        machine = txn_flow(self.project).state_machines.get(
            "jobs", {}).get("status")
        if not machine:
            return
        canonical: Set[str] = {
            v for v in machine.get("values", ()) if v is not None}
        handler_sites: List[Tuple[object, str, ast.AST]] = []
        for mod in sorted(self.project.modules.values(),
                          key=lambda m: m.name):
            ctx = mod.ctx
            if not _is_library(ctx.rel_path):
                continue
            spans = [
                (h.lineno, getattr(h, "end_lineno", h.lineno) or
                 h.lineno)
                for n in ast.walk(ctx.tree) if isinstance(n, ast.Try)
                for h in n.handlers]

            def in_handler(node: ast.AST) -> bool:
                return any(a <= node.lineno <= b for a, b in spans)

            for value, node in self._verdict_literals(ctx):
                if in_handler(node):
                    handler_sites.append((mod, value, node))
                else:
                    canonical.add(value)
        vocabulary = sorted(canonical)
        for mod, value, node in handler_sites:
            if value in canonical:
                continue
            hint = difflib.get_close_matches(value, vocabulary, n=1,
                                             cutoff=0.6)
            suggest = f" — did you mean `{hint[0]}`?" if hint else ""
            self.frame_findings.append({
                "path": mod.ctx.rel_path, "line": node.lineno,
                "col": node.col_offset,
                "message": (
                    f"error verdict `{value}` emitted from an "
                    f"exception handler is not in the recovered "
                    f"vocabulary {vocabulary}{suggest} — dashboards "
                    f"keyed on the jobs.status machine will drop this "
                    f"failure class on the floor"),
            })

    @staticmethod
    def _verdict_literals(ctx) -> Iterator[Tuple[str, ast.AST]]:
        """String literals used as an outbound error *verdict*: the 2nd
        positional of ``job_finish``, a ``verdict=`` kwarg, a
        ``"verdict"`` dict value, or a ``verdict`` assignment."""

        def consts(expr: ast.AST) -> Iterator[ast.Constant]:
            if isinstance(expr, ast.Constant) \
                    and isinstance(expr.value, str):
                yield expr
            elif isinstance(expr, ast.IfExp):
                yield from consts(expr.body)
                yield from consts(expr.orelse)
            elif isinstance(expr, (ast.Tuple, ast.List, ast.Set)):
                for elt in expr.elts:
                    yield from consts(elt)

        def is_verdict(expr: ast.AST) -> bool:
            return (isinstance(expr, ast.Name)
                    and expr.id == "verdict") \
                or (isinstance(expr, ast.Attribute)
                    and expr.attr == "verdict")

        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                func = node.func
                leaf = func.attr if isinstance(func, ast.Attribute) \
                    else (func.id if isinstance(func, ast.Name)
                          else "")
                if leaf == "job_finish" and len(node.args) >= 2:
                    for c in consts(node.args[1]):
                        yield c.value, c
                for kw in node.keywords:
                    if kw.arg == "verdict":
                        for c in consts(kw.value):
                            yield c.value, c
            elif isinstance(node, ast.Dict):
                for key, value in zip(node.keys, node.values):
                    if isinstance(key, ast.Constant) \
                            and key.value == "verdict" \
                            and value is not None:
                        for c in consts(value):
                            yield c.value, c
            elif isinstance(node, ast.Assign) \
                    and len(node.targets) == 1 \
                    and is_verdict(node.targets[0]):
                for c in consts(node.value):
                    yield c.value, c


def exc_flow(project) -> ExcFlow:
    flow = getattr(project, "_exc_flow", None)
    if flow is None:
        flow = ExcFlow(project)
        project._exc_flow = flow
    return flow


# ---------------------------------------------------------------------------
# The committed surface
# ---------------------------------------------------------------------------

def _handler_inventory(project) -> List[dict]:
    out: List[dict] = []
    flow = exc_flow(project)
    for mod in sorted(project.modules.values(), key=lambda m: m.name):
        ctx = mod.ctx
        if not _is_library(ctx.rel_path):
            continue
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Try):
                continue
            for h in node.handlers:
                types = flow._handler_type_names(mod, h.type)
                broad = types is None or any(t in _BROAD for t in types)
                reraises = any(isinstance(n, ast.Raise)
                               for n in ast.walk(h))
                out.append({
                    "path": ctx.rel_path,
                    "line": h.lineno,
                    "types": ["*"] if types is None else sorted(types),
                    "broad": broad,
                    "reraises": reraises,
                })
    out.sort(key=lambda h: (h["path"], h["line"]))
    return out


def build_failure_surface(project) -> dict:
    """The failure surface: every boundary with its escaping set and
    verdict, the handler inventory, and the project exception taxonomy.
    Deterministic by construction (sorted everywhere, no timestamps) so
    the rendering is byte-stable."""
    flow = exc_flow(project)
    boundaries = []
    for b in sorted(flow.boundaries,
                    key=lambda b: (b["path"], b["line"], b["kind"],
                                   b["name"])):
        boundaries.append({
            "kind": b["kind"],
            "name": b["name"],
            "path": b["path"],
            "line": b["line"],
            "entries": b["entries"],
            "daemon": b["daemon"],
            "guard": b["guard"],
            "escapes": {n: list(chain)
                        for n, chain in sorted(b["escapes"].items())},
            "verdict": b["verdict"],
        })
    handlers = _handler_inventory(project)
    exceptions = {
        name: {
            "bases": sorted(info["bases"]),
            "path": info["path"],
            "line": info["line"],
        }
        for name, info in sorted(flow.classes.items())
    }
    surface = {
        "version": EXC_VERSION,
        "generator": "vmtlint exc",
        "boundaries": boundaries,
        "handlers": handlers,
        "exceptions": exceptions,
        "counts": {
            "boundaries": len(boundaries),
            "escaping_boundaries": sum(
                1 for b in boundaries
                if b["verdict"] in ("escapes", "blind")),
            "guarded_boundaries": sum(
                1 for b in boundaries if b["guard"]),
            "handlers": len(handlers),
            "broad_handlers": sum(1 for h in handlers if h["broad"]),
            "exception_classes": len(exceptions),
            "functions_analyzed": len(flow.frames),
        },
    }
    return surface


def render_failure_surface(surface: dict) -> str:
    return json.dumps(surface, indent=2, sort_keys=True) + "\n"


def diff_failure_surface(committed: Optional[dict], fresh: dict
                         ) -> List[str]:
    """Human-readable drift between the committed manifest and a fresh
    build — empty when they agree."""
    if committed is None:
        return [f"{MANIFEST_NAME} missing — run `vmtlint exc` and "
                f"commit it"]
    msgs: List[str] = []
    if committed.get("version") != fresh.get("version"):
        msgs.append(f"manifest version drifted: committed "
                    f"{committed.get('version')!r}, tree expects "
                    f"{fresh.get('version')!r}")
        return msgs

    def bkey(b: dict) -> Tuple[str, str, str]:
        return (b["kind"], b["name"], b["path"])

    cb = {bkey(b): b for b in committed.get("boundaries", [])}
    fb = {bkey(b): b for b in fresh.get("boundaries", [])}
    for key in sorted(set(cb) | set(fb)):
        kind, name, path = key
        label = f"{kind} boundary `{name}` ({path})"
        if key not in cb:
            msgs.append(f"{label} is new in the tree")
            continue
        if key not in fb:
            msgs.append(f"{label} is gone from the tree")
            continue
        if cb[key]["verdict"] != fb[key]["verdict"]:
            msgs.append(f"{label} verdict drifted: "
                        f"{cb[key]['verdict']!r} -> "
                        f"{fb[key]['verdict']!r}")
        cset = sorted(cb[key].get("escapes", {}))
        fset = sorted(fb[key].get("escapes", {}))
        if cset != fset:
            msgs.append(f"{label} escape set drifted: "
                        f"{cset} -> {fset}")
    cexc = set(committed.get("exceptions", {}))
    fexc = set(fresh.get("exceptions", {}))
    for name in sorted(fexc - cexc):
        msgs.append(f"exception class `{name}` is new in the tree")
    for name in sorted(cexc - fexc):
        msgs.append(f"exception class `{name}` is gone from the tree")
    if not msgs and committed != fresh:
        msgs.append("manifest metadata drifted (witness lines moved?)")
    return msgs


# ---------------------------------------------------------------------------
# SARIF rendering
# ---------------------------------------------------------------------------

def _sarif_loc(w: dict) -> dict:
    return {
        "physicalLocation": {
            "artifactLocation": {"uri": w["path"]},
            "region": {"startLine": max(1, int(w.get("line", 1)))},
        },
        "message": {"text": w.get("message", "")},
    }


def _sarif_flow(steps: List[dict]) -> dict:
    return {"threadFlows": [{
        "locations": [{"location": _sarif_loc(s)} for s in steps],
    }]}


def render_failure_surface_sarif(surface: dict) -> str:
    """The surface as SARIF results: one per boundary, warning level
    when the verdict says something escapes, with the raise→escape
    witness chains as codeFlows."""
    results: List[dict] = []
    for b in surface.get("boundaries", []):
        escaping = b["verdict"] in ("escapes", "blind")
        names = sorted(b.get("escapes", {}))
        shown = ", ".join(names) or "nothing"
        result = {
            "ruleId": "EXC-BOUNDARY",
            "level": "warning" if escaping else "note",
            "message": {"text": (
                f"{b['kind']} boundary `{b['name']}` "
                f"[{b['verdict']}]: escaping {shown}")},
            "locations": [_sarif_loc({
                "path": b["path"], "line": b["line"],
                "message": f"{b['kind']} boundary `{b['name']}`"})],
        }
        flows = [_sarif_flow(b["escapes"][n])
                 for n in names if b["escapes"][n]]
        if flows:
            result["codeFlows"] = flows
        results.append(result)
    doc = {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "vmtlint-exc",
                "informationUri": "",
                "rules": [
                    {"id": "EXC-BOUNDARY",
                     "shortDescription": {
                         "text": "exception-flow boundary"}},
                ],
            }},
            "results": results,
        }],
    }
    return json.dumps(doc, indent=2, sort_keys=True) + "\n"
