"""Finding/Rule model, inline suppressions, and the file-walking driver."""

from __future__ import annotations

import ast
import dataclasses
import hashlib
import os
import re
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set

from vilbert_multitask_tpu.analysis.context import ModuleContext

SEVERITIES = ("error", "warning")


@dataclasses.dataclass
class Finding:
    rule: str  # "VMT101"
    name: str  # "host-transfer-in-jit"
    severity: str  # error | warning
    path: str  # repo-relative, forward slashes
    line: int
    col: int
    message: str
    content: str = ""  # stripped source line — the baseline fingerprint key
    # Optional witness chains (e.g. the two conflicting acquisition orders
    # of a VMT119 inversion): each flow is an ordered list of
    # {"path", "line", "message"} steps, rendered as SARIF codeFlows.
    # Not part of the fingerprint — chains shift when unrelated code moves.
    flows: List[List[dict]] = dataclasses.field(default_factory=list)

    def fingerprint(self) -> str:
        """Line-number-free identity: surviving a pure line shift must not
        invalidate a baseline entry; editing the flagged line must."""
        digest = hashlib.sha1(self.content.encode("utf-8")).hexdigest()[:12]
        return f"{self.rule}:{self.path}:{digest}"

    def to_json(self) -> dict:
        d = dataclasses.asdict(self)
        d["fingerprint"] = self.fingerprint()
        return d


class Rule:
    """One registered check. Subclasses set the class attrs and implement
    :meth:`check`; severity may be overridden per-repo via config."""

    id: str = ""
    name: str = ""
    severity: str = "error"
    description: str = ""
    # Rel-path prefixes this rule is restricted to ("" = everywhere).
    # e.g. the stray-print rule only polices library code, not scripts.
    library_only: bool = False

    def __init__(self, severity: Optional[str] = None,
                 not_under: Sequence[str] = ()):
        if severity is not None:
            self.severity = severity
        # Per-repo path gating ([tool.vmtlint.rule_paths]): rel-path
        # prefixes this rule instance skips — how the widened tests/
        # scripts/ scan keeps library-grade rules out of test idioms.
        self.not_under: Sequence[str] = tuple(not_under)

    def applies_to(self, ctx: ModuleContext, library_roots: Sequence[str]
                   ) -> bool:
        def under(rel: str, prefix: str) -> bool:
            prefix = prefix.rstrip("/")
            return rel == prefix or rel.startswith(prefix + "/")

        if any(under(ctx.rel_path, p) for p in self.not_under):
            return False
        if not self.library_only:
            return True
        return any(under(ctx.rel_path, root) for root in library_roots)

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, ctx: ModuleContext, node: ast.AST, message: str
                ) -> Finding:
        line = getattr(node, "lineno", 1)
        content = (ctx.lines[line - 1].strip()
                   if 0 < line <= len(ctx.lines) else "")
        return Finding(rule=self.id, name=self.name, severity=self.severity,
                       path=ctx.rel_path, line=line,
                       col=getattr(node, "col_offset", 0) + 1,
                       message=message, content=content)


# ------------------------------------------------------------ suppressions
_SUPPRESS_RE = re.compile(
    r"#\s*vmtlint:\s*(disable|disable-next-line)\s*=\s*"
    r"([A-Za-z0-9_,\-\s]+)")


def suppressions_for(source: str) -> Dict[int, Set[str]]:
    """{line_number: {rule ids/names/'all'}} from inline comments."""
    out: Dict[int, Set[str]] = {}
    for i, line in enumerate(source.splitlines(), start=1):
        m = _SUPPRESS_RE.search(line)
        if not m:
            continue
        target = i + 1 if m.group(1) == "disable-next-line" else i
        rules = {r.strip().lower() for r in m.group(2).split(",") if r.strip()}
        out.setdefault(target, set()).update(rules)
    return out


def is_suppressed(finding: Finding, suppressions: Dict[int, Set[str]]
                  ) -> bool:
    rules = suppressions.get(finding.line)
    if not rules:
        return False
    return bool(rules & {"all", finding.rule.lower(), finding.name.lower()})


# ----------------------------------------------------------------- driver
def analyze_project(sources: Dict[str, str],
                    rules: Optional[Sequence[Rule]] = None,
                    library_roots: Sequence[str] = ("vilbert_multitask_tpu",),
                    layers: Sequence = (),
                    ) -> List[Finding]:
    """Whole-program analysis over {rel_path: source}. All modules are
    parsed first, joined into one ProjectGraph (import graph, symbol
    tables, call graph), and only then checked — so rules see cross-module
    facts: helpers traced from jit in *other* files, imported donating
    functions, thread entries, project-wide mesh axes, layer contracts.

    Syntax errors yield a single VMT000 error for that file — an
    unparseable file must fail loudly, not pass silently — and exclude it
    from the project graph."""
    from vilbert_multitask_tpu.analysis.graph import ProjectGraph

    if rules is None:
        from vilbert_multitask_tpu.analysis.rules import default_rules

        rules = default_rules()
    findings: List[Finding] = []
    ctxs: List[ModuleContext] = []
    for rel_path in sorted(sources):
        source = sources[rel_path]
        try:
            tree = ast.parse(source)
        except SyntaxError as e:
            findings.append(Finding(
                rule="VMT000", name="syntax-error", severity="error",
                path=rel_path, line=e.lineno or 1, col=e.offset or 1,
                message=f"file does not parse: {e.msg}",
                content=(e.text or "").strip()))
            continue
        ctxs.append(ModuleContext(rel_path, source, tree))
    project = ProjectGraph(ctxs, layers=layers)
    for ctx in ctxs:
        ctx.project = project
        sup = suppressions_for(ctx.source)
        findings.extend(
            f for rule in rules if rule.applies_to(ctx, library_roots)
            for f in rule.check(ctx) if not is_suppressed(f, sup))
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))


def analyze_source(source: str, rel_path: str = "<string>",
                   rules: Optional[Sequence[Rule]] = None,
                   library_roots: Sequence[str] = ("vilbert_multitask_tpu",),
                   ) -> List[Finding]:
    """Analyze one module's source (as a one-module project). Returns
    unsuppressed findings sorted by (path, line, rule)."""
    return analyze_project({rel_path: source}, rules=rules,
                           library_roots=library_roots)


def analyze_file(path: str, root: str = ".",
                 rules: Optional[Sequence[Rule]] = None,
                 library_roots: Sequence[str] = ("vilbert_multitask_tpu",),
                 ) -> List[Finding]:
    rel = os.path.relpath(os.path.abspath(path),
                          os.path.abspath(root)).replace(os.sep, "/")
    with open(path, "r", encoding="utf-8") as f:
        source = f.read()
    return analyze_source(source, rel, rules=rules,
                          library_roots=library_roots)


def iter_python_files(paths: Iterable[str],
                      exclude: Sequence[str] = ()) -> Iterator[str]:
    """Expand files/dirs to .py files, skipping excluded path fragments."""

    def excluded(p: str) -> bool:
        norm = p.replace(os.sep, "/")
        return any(pat in norm for pat in exclude)

    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py") and not excluded(p):
                yield p
        elif os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(
                    d for d in dirnames
                    if d != "__pycache__"
                    and not excluded(os.path.join(dirpath, d)))
                for fn in sorted(filenames):
                    full = os.path.join(dirpath, fn)
                    if fn.endswith(".py") and not excluded(full):
                        yield full


def analyze_paths(paths: Sequence[str], root: str = ".",
                  rules: Optional[Sequence[Rule]] = None,
                  exclude: Sequence[str] = (),
                  library_roots: Sequence[str] = ("vilbert_multitask_tpu",),
                  layers: Sequence = (),
                  ) -> List[Finding]:
    """Scan files/dirs as ONE project: every scanned module joins the same
    import/call graph, so cross-file rules see the full picture."""
    sources: Dict[str, str] = {}
    for path in iter_python_files(paths, exclude=exclude):
        rel = os.path.relpath(os.path.abspath(path),
                              os.path.abspath(root)).replace(os.sep, "/")
        with open(path, "r", encoding="utf-8") as f:
            sources[rel] = f.read()
    return analyze_project(sources, rules=rules,
                           library_roots=library_roots, layers=layers)
