"""Abstract shape/dtype interpretation — the fourth analyzer tier.

The engine's compile cache is keyed by ``(program, bucket, attn,
model_gen)`` and the AOT roadmap wants executables persisted per
(bucket, dtype, fused/quant mode, topology) — but nothing before this
module could *enumerate* that key universe or prove it bounded. This is
the domain that can: symbolic dimensions bound to config knobs
(``EngineConfig.max_text_len``, the bucket tuples), a dtype lattice with
the NumPy/JAX promotion rules that matter on the bf16/int8 serving path,
and pytree-aware values including the int8 ``{"int8", "scale"}`` pair.

The interpreter is a plain :class:`~.dataflow.ForwardAnalysis` over the
per-function CFGs of :mod:`analysis.cfg` — same worklist, same join
discipline as the lock-set tier — with an environment of abstract values
per local name. Everything tracks *provenance*: a scalar knows whether it
came from a literal, a config knob, a bucketing call, or request data,
and carries a witness chain (path, line, description) for the finding
flows and the compile-surface manifest.

Stdlib-only, like the rest of the package: the layering contract forbids
importing jax or numpy, so dtype promotion is a lookup table, not a call
into ``jnp.promote_types``.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterator, List, Optional, Set, Tuple

from vilbert_multitask_tpu.analysis.cfg import (
    Event,
    WithEnter,
    WithExit,
    build_cfg,
    iter_event_nodes,
)
from vilbert_multitask_tpu.analysis.context import ModuleContext
from vilbert_multitask_tpu.analysis.dataflow import (
    ForwardAnalysis,
    iter_event_facts,
    solve,
)

# --------------------------------------------------------------- dtypes
# Promotion ranks inside each kind. bf16 and f16 share a rank on purpose:
# combining them promotes OUT of the 16-bit lattice to f32 (the JAX rule).
_FLOAT_RANK = {"bfloat16": 1, "float16": 1, "float32": 2, "float64": 3}
_INT_RANK = {"bool": 0, "int8": 1, "uint8": 1, "int16": 2, "uint16": 2,
             "int32": 3, "uint32": 3, "int64": 4, "uint64": 4}
_FLOAT_BY_RANK = {1: "float32", 2: "float32", 3: "float64"}
# The low-precision storage/compute dtypes the serving path is built on;
# a silent promotion out of this set is the VMT125 bug class.
LOW_PRECISION = {"bfloat16", "float16", "int8"}


@dataclasses.dataclass(frozen=True)
class DType:
    """Abstract dtype. ``weak=True`` models Python scalars (they adopt the
    other operand's dtype instead of widening it — the JAX weak-type
    rule). ``ctor_line > 0`` records that this dtype came from a
    default-dtype constructor (``jnp.zeros(shape)`` with no ``dtype=``) at
    that source line — the provenance VMT125 reports."""

    name: str = ""  # "" = unknown
    weak: bool = False
    ctor_line: int = 0

    @property
    def known(self) -> bool:
        return bool(self.name)


UNKNOWN_DT = DType()


def promote(a: DType, b: DType) -> DType:
    """JAX-style binary promotion (subset: the kinds this repo serves)."""
    if not a.known or not b.known:
        return UNKNOWN_DT
    if a.name == b.name:
        return DType(a.name, a.weak and b.weak,
                     a.ctor_line or b.ctor_line)
    # Weak scalars adopt the strong side when kinds are compatible.
    if a.weak and not b.weak:
        a, b = b, a
    if b.weak and not a.weak:
        if b.name in _FLOAT_RANK and a.name in _INT_RANK:
            # int array + python float → default float.
            return DType("float32", weak=True)
        return a
    fa, fb = a.name in _FLOAT_RANK, b.name in _FLOAT_RANK
    if fa and fb:
        ra, rb = _FLOAT_RANK[a.name], _FLOAT_RANK[b.name]
        if ra == rb:  # bf16 × f16 → f32
            return DType("float32")
        hi = a if ra > rb else b
        return DType(hi.name, ctor_line=hi.ctor_line)
    if fa != fb:  # int × float → the float side
        hi = a if fa else b
        return DType(hi.name, ctor_line=hi.ctor_line)
    ra = _INT_RANK.get(a.name, 0)
    rb = _INT_RANK.get(b.name, 0)
    return DType(a.name if ra >= rb else b.name)


def promotion_leak(a: DType, b: DType) -> Optional[Tuple[str, int]]:
    """(low_dtype_name, f32_ctor_line) when combining ``a`` and ``b``
    silently widens a low-precision operand to f32 because the other side
    is a *strong* float32 that a default-dtype constructor produced.
    Explicit ``astype(float32)`` casts (ctor_line == 0) are deliberate and
    never reported."""
    for lo, hi in ((a, b), (b, a)):
        if (lo.name in LOW_PRECISION and hi.name == "float32"
                and not hi.weak and hi.ctor_line > 0):
            return lo.name, hi.ctor_line
    return None


# -------------------------------------------------------------- origins
# Provenance lattice for scalar values, ordered by "how dynamic": joins
# take the max rank, so a value that is data-dependent on ANY path stays
# flagged. BOUNDED origins can only take finitely many values per process
# lifetime — safe compile-cache key material.
_ORIGIN_RANK = {"literal": 0, "config": 1, "bucket": 2, "shape": 3,
                "unknown": 4, "param": 5, "data": 6}
BOUNDED_ORIGINS = {"literal", "config", "bucket", "shape"}
# Witness chains are capped so loop fixed points terminate (a chain that
# grows per iteration would never converge).
_MAX_WITNESS = 6

WitnessStep = Tuple[str, int, str]  # (rel_path, line, description)


def _join_origin(a: str, b: str) -> str:
    return a if _ORIGIN_RANK.get(a, 4) >= _ORIGIN_RANK.get(b, 4) else b


@dataclasses.dataclass(frozen=True)
class Scalar:
    """An abstract Python value (int/str/bool dims, static args)."""

    value: object = None  # concrete value when statically known
    origin: str = "unknown"
    sym: str = ""  # knob binding, e.g. "EngineConfig.max_text_len"
    dtype: DType = UNKNOWN_DT
    witness: Tuple[WitnessStep, ...] = ()

    def with_step(self, step: WitnessStep) -> "Scalar":
        chain = (self.witness + (step,))[:_MAX_WITNESS]
        return dataclasses.replace(self, witness=chain)


@dataclasses.dataclass(frozen=True)
class Array:
    """An abstract array: tuple of Scalar dims (None = unknown rank)."""

    shape: Optional[Tuple[Scalar, ...]] = None
    dtype: DType = UNKNOWN_DT

    @property
    def rank(self) -> Optional[int]:
        return None if self.shape is None else len(self.shape)


@dataclasses.dataclass(frozen=True)
class Tup:
    """A Python tuple/list of abstract values."""

    elts: Tuple[object, ...] = ()


@dataclasses.dataclass(frozen=True)
class Tree:
    """A string-keyed pytree node (the dict idiom of batch/param trees)."""

    items: Tuple[Tuple[str, object], ...] = ()

    def child(self, key: str):
        for k, v in self.items:
            if k == key:
                return v
        return None


def is_int8_pair(val) -> bool:
    """The quantized-leaf convention: ``{"int8": values, "scale": scales}``
    (quant.py). Shape rules must treat the pair as one logical leaf whose
    shape is the values leaf's."""
    return (isinstance(val, Tree)
            and {k for k, _ in val.items} == {"int8", "scale"})


def join_values(a, b):
    """Least upper bound of two abstract values (None = unknown/⊤)."""
    if a is None or b is None:
        return None
    if a == b:
        return a
    if isinstance(a, Scalar) and isinstance(b, Scalar):
        return Scalar(
            value=a.value if a.value == b.value else None,
            origin=_join_origin(a.origin, b.origin),
            sym=a.sym if a.sym == b.sym else "",
            dtype=a.dtype if a.dtype == b.dtype else promote(a.dtype,
                                                             b.dtype),
            witness=a.witness if a.witness == b.witness else ())
    if isinstance(a, Array) and isinstance(b, Array):
        if (a.shape is not None and b.shape is not None
                and len(a.shape) == len(b.shape)):
            shape = tuple(join_values(x, y) or Scalar()
                          for x, y in zip(a.shape, b.shape))
        else:
            shape = None
        dt = a.dtype if a.dtype == b.dtype else UNKNOWN_DT
        return Array(shape, dt)
    if (isinstance(a, Tup) and isinstance(b, Tup)
            and len(a.elts) == len(b.elts)):
        return Tup(tuple(join_values(x, y) for x, y in zip(a.elts, b.elts)))
    if isinstance(a, Tree) and isinstance(b, Tree):
        keys = {k for k, _ in a.items} & {k for k, _ in b.items}
        return Tree(tuple((k, join_values(a.child(k), b.child(k)))
                          for k in sorted(keys)))
    return None


def element_of(val):
    """Abstract element of an iterable value (loop-target binding)."""
    if isinstance(val, Tup):
        out = None
        for e in val.elts:
            out = e if out is None else join_values(out, e)
        return out
    if isinstance(val, Array):
        if val.shape is not None and len(val.shape) > 1:
            return Array(val.shape[1:], val.dtype)
        if val.shape is not None and len(val.shape) == 1:
            return Scalar(origin="data", dtype=val.dtype)
        return Array(None, val.dtype)
    if isinstance(val, Scalar):
        # Iterating something scalar-tracked (a request list, range(n)):
        # elements inherit the provenance.
        return Scalar(origin=val.origin, sym=val.sym, witness=val.witness)
    return None


# ----------------------------------------------------------- knob table
# The config dataclasses whose literal field defaults anchor symbolic
# dims. Collected once per project, AST-only.
KNOB_CLASSES = ("EngineConfig", "ViLBertConfig", "MeshConfig",
                "ServingConfig")


@dataclasses.dataclass(frozen=True)
class Knob:
    cls: str
    field: str
    value: object  # literal default (int/str/bool/tuple) or None
    path: str
    line: int

    @property
    def sym(self) -> str:
        return f"{self.cls}.{self.field}"


class KnobTable:
    """Literal config-knob defaults, indexed by class and by field name."""

    def __init__(self) -> None:
        self.by_class: Dict[str, Dict[str, Knob]] = {}
        self._by_field: Dict[str, Optional[Knob]] = {}

    def add(self, knob: Knob) -> None:
        self.by_class.setdefault(knob.cls, {})[knob.field] = knob
        # Field-name lookup is only trusted when unambiguous across the
        # knob classes — a collision poisons the entry.
        if knob.field in self._by_field:
            self._by_field[knob.field] = None
        else:
            self._by_field[knob.field] = knob

    def get(self, cls: str, field: str) -> Optional[Knob]:
        return self.by_class.get(cls, {}).get(field)

    def field(self, name: str) -> Optional[Knob]:
        return self._by_field.get(name)

    def ints(self) -> Set[int]:
        """Every integer derivable from a knob default (tuple elements
        flattened) — the VMT127 'declared shape vocabulary'."""
        out: Set[int] = set()
        for fields in self.by_class.values():
            for knob in fields.values():
                vals = (knob.value if isinstance(knob.value, (tuple, list))
                        else (knob.value,))
                for v in vals:
                    if isinstance(v, int) and not isinstance(v, bool):
                        out.add(v)
        return out

    @property
    def empty(self) -> bool:
        return not self.by_class


def _literal_default(node: Optional[ast.AST]):
    if node is None:
        return None
    try:
        return ast.literal_eval(node)
    except (ValueError, SyntaxError, TypeError):
        return None


def module_knobs(ctx: ModuleContext, table: KnobTable) -> None:
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.ClassDef)
                and node.name in KNOB_CLASSES):
            continue
        for stmt in node.body:
            if (isinstance(stmt, ast.AnnAssign)
                    and isinstance(stmt.target, ast.Name)):
                table.add(Knob(node.name, stmt.target.id,
                               _literal_default(stmt.value),
                               ctx.rel_path, stmt.lineno))
            elif isinstance(stmt, ast.Assign):
                val = _literal_default(stmt.value)
                for t in stmt.targets:
                    if isinstance(t, ast.Name):
                        table.add(Knob(node.name, t.id, val,
                                       ctx.rel_path, stmt.lineno))


def knob_table(project) -> KnobTable:
    """Project-wide knob table, cached on the ProjectGraph."""
    cached = getattr(project, "_shape_knobs", None)
    if cached is not None:
        return cached
    table = KnobTable()
    for mod in project.modules.values():
        module_knobs(mod.ctx, table)
    project._shape_knobs = table
    return table


# ------------------------------------------------------ jit static info
@dataclasses.dataclass(frozen=True)
class JitBinding:
    """A locally-callable jitted binding plus its static-argument facts —
    the call-site side of the compile-key analysis (VMT124)."""

    name: str  # the name call sites use
    params: Tuple[str, ...]  # wrapped function's parameter names
    static_names: Tuple[str, ...]
    line: int


def jit_static_bindings(ctx: ModuleContext) -> Dict[str, JitBinding]:
    """Callable-name → static-arg facts for every jitted binding with at
    least one static argument: decorated defs (called by their own name)
    and ``f = jax.jit(g, static_arg...)`` assignments (called as ``f``)."""
    out: Dict[str, JitBinding] = {}
    for info in ctx.jit_bodies:
        body = info.body
        if (info.static_params
                and isinstance(body, (ast.FunctionDef,
                                      ast.AsyncFunctionDef))):
            params = tuple(a.arg for a in body.args.args)
            out[body.name] = JitBinding(body.name, params,
                                        tuple(info.static_params),
                                        body.lineno)
    defs: Dict[str, ast.AST] = {}
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, node)
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
                and ctx.is_jit_entry(node.value.func)
                and node.value.args):
            continue
        target = node.value.args[0]
        if not isinstance(target, ast.Name):
            continue
        body = defs.get(target.id)
        if body is None:
            continue
        statics = ctx._static_params_of(node.value, body)
        if not statics:
            continue
        params = tuple(a.arg for a in body.args.args)
        for t in node.targets:
            if isinstance(t, ast.Name):
                out[t.id] = JitBinding(t.id, params, tuple(statics),
                                       node.lineno)
    return out


# ---------------------------------------------------------- interpreter
_SHAPE_CTORS = {"zeros", "ones", "full", "empty"}
_FLOAT_DEFAULT_CTORS = {"zeros", "ones", "full", "empty", "linspace"}
_ARRAY_NAMESPACES = ("jax.numpy", "numpy")
_DTYPE_NAMES = set(_FLOAT_RANK) | set(_INT_RANK)
_BUCKETIZERS = {"bucket_for", "row_bucket_for"}
_ELEMENTWISE = {"add", "subtract", "multiply", "divide", "maximum",
                "minimum", "where", "matmul", "dot", "einsum", "tensordot"}
# Attribute bases that plausibly denote a config object — the guard that
# keeps `anything.max_text_len` from false-binding to a knob.
_CONFIG_TOKENS = ("cfg", "config", "engine", "serving", "model")


def _looks_config(dotted: str) -> bool:
    parts = dotted.split(".")
    return any(any(tok in p for tok in _CONFIG_TOKENS) for p in parts)


class ShapeInterp(ForwardAnalysis):
    """Forward abstract interpretation of one function body.

    Facts are ``{local name: abstract value}`` environments; the solver is
    the shared worklist in :mod:`analysis.dataflow`. Alongside the facts,
    the interpreter accumulates *promotion incidents* — places where a
    low-precision operand met a strong default-constructed f32 — keyed by
    node id so the fixed-point re-runs of ``transfer`` stay idempotent.
    """

    def __init__(self, ctx: ModuleContext, fn: ast.AST, knobs: KnobTable,
                 param_origin: str = "param") -> None:
        self.ctx = ctx
        self.fn = fn
        self.knobs = knobs
        self.param_origin = param_origin
        # id(node) -> (node, low dtype name, f32 ctor line)
        self.promotions: Dict[int, Tuple[ast.AST, str, int]] = {}
        self._loop_iter: Dict[int, ast.expr] = {}
        for node in ast.walk(fn):
            if isinstance(node, (ast.For, ast.AsyncFor)):
                self._loop_iter[id(node.target)] = node.iter
        self.cfg = build_cfg(fn)
        self.in_facts: Optional[Dict[int, object]] = None

    def run(self) -> "ShapeInterp":
        self.in_facts = solve(self.cfg, self)
        return self

    def iter_facts(self) -> Iterator[Tuple[Event, Dict[str, object]]]:
        assert self.in_facts is not None, "run() first"
        return iter_event_facts(self.cfg, self, self.in_facts)

    # ------------------------------------------------------------ lattice
    def initial(self) -> Dict[str, object]:
        env: Dict[str, object] = {}
        args = getattr(self.fn, "args", None)
        if args is None:
            return env
        names = [a.arg for a in (list(getattr(args, "posonlyargs", ()))
                                 + args.args + args.kwonlyargs)]
        for name in names:
            if name == "self":
                continue
            env[name] = Scalar(
                origin=self.param_origin,
                witness=((self.ctx.rel_path, self.fn.lineno,
                          f"parameter `{name}` of "
                          f"`{getattr(self.fn, 'name', '<lambda>')}` — "
                          f"caller-controlled"),))
        return env

    def join(self, a: Dict[str, object], b: Dict[str, object]
             ) -> Dict[str, object]:
        out: Dict[str, object] = {}
        for name in set(a) | set(b):
            if name in a and name in b:
                out[name] = join_values(a[name], b[name])
            else:
                out[name] = a.get(name, b.get(name))
        return out

    # ----------------------------------------------------------- transfer
    def transfer(self, event: Event, fact: Dict[str, object]
                 ) -> Dict[str, object]:
        if isinstance(event, (WithEnter, WithExit)):
            return fact
        if isinstance(event, ast.Assign):
            val = self.eval(event.value, fact)
            env = dict(fact)
            for t in event.targets:
                self._bind(t, val, env)
            return env
        if isinstance(event, ast.AnnAssign) and event.value is not None:
            val = self.eval(event.value, fact)
            env = dict(fact)
            self._bind(event.target, val, env)
            return env
        if isinstance(event, ast.AugAssign):
            self.eval(event.value, fact)
            env = dict(fact)
            self._bind(event.target, None, env)
            return env
        if (isinstance(event, (ast.Name, ast.Tuple, ast.List))
                and isinstance(getattr(event, "ctx", None), ast.Store)):
            # A loop target appended to the loop header by the CFG builder:
            # bind to an abstract element of the iterable.
            it = self._loop_iter.get(id(event))
            elem = element_of(self.eval(it, fact)) if it is not None \
                else None
            env = dict(fact)
            self._bind(event, elem, env)
            return env
        if isinstance(event, ast.Return) and event.value is not None:
            self.eval(event.value, fact)
            return fact
        if isinstance(event, ast.Expr):
            self.eval(event.value, fact)
            return fact
        if isinstance(event, ast.expr):
            # Branch tests and loop iterables appear as bare expr events.
            self.eval(event, fact)
            return fact
        return fact

    def _bind(self, target: ast.AST, val, env: Dict[str, object]) -> None:
        if isinstance(target, ast.Name):
            env[target.id] = val
            return
        if isinstance(target, (ast.Tuple, ast.List)):
            elts = target.elts
            vals: List[object]
            if isinstance(val, Tup) and len(val.elts) == len(elts):
                vals = list(val.elts)
            else:
                vals = [element_of(val) if val is not None else None] \
                    * len(elts)
            for t, v in zip(elts, vals):
                if isinstance(t, ast.Starred):
                    self._bind(t.value, None, env)
                else:
                    self._bind(t, v, env)

    # --------------------------------------------------------------- eval
    def eval(self, node: Optional[ast.AST], env: Dict[str, object]):
        """Abstract value of an expression under ``env`` (None = ⊤)."""
        if node is None:
            return None
        if isinstance(node, ast.Constant):
            return self._const(node)
        if isinstance(node, ast.Name):
            return env.get(node.id)
        if isinstance(node, (ast.Tuple, ast.List)):
            return Tup(tuple(self.eval(e, env) for e in node.elts))
        if isinstance(node, ast.Dict):
            if all(isinstance(k, ast.Constant) and isinstance(k.value, str)
                   for k in node.keys if k is not None):
                items = tuple(sorted(
                    (k.value, self.eval(v, env))
                    for k, v in zip(node.keys, node.values)
                    if k is not None))
                return Tree(items)
            return None
        if isinstance(node, ast.Attribute):
            return self._attribute(node, env)
        if isinstance(node, ast.Call):
            return self._call(node, env)
        if isinstance(node, ast.BinOp):
            return self._binop(node, env)
        if isinstance(node, ast.UnaryOp):
            return self.eval(node.operand, env)
        if isinstance(node, ast.Subscript):
            return self._subscript(node, env)
        if isinstance(node, ast.IfExp):
            self.eval(node.test, env)
            return join_values(self.eval(node.body, env),
                               self.eval(node.orelse, env))
        if isinstance(node, (ast.Compare, ast.BoolOp)):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.expr):
                    self.eval(child, env)
            return Scalar(dtype=DType("bool", weak=True), origin="unknown")
        if isinstance(node, ast.Starred):
            return self.eval(node.value, env)
        return None

    def _const(self, node: ast.Constant):
        v = node.value
        step = (self.ctx.rel_path, node.lineno, f"literal `{v!r}`")
        if isinstance(v, bool):
            return Scalar(v, "literal", dtype=DType("bool", weak=True),
                          witness=(step,))
        if isinstance(v, int):
            return Scalar(v, "literal", dtype=DType("int32", weak=True),
                          witness=(step,))
        if isinstance(v, float):
            return Scalar(v, "literal", dtype=DType("float32", weak=True),
                          witness=(step,))
        if isinstance(v, str):
            return Scalar(v, "literal", witness=(step,))
        return None

    def _attribute(self, node: ast.Attribute, env: Dict[str, object]):
        base = self.eval(node.value, env)
        attr = node.attr
        if isinstance(base, Array):
            if attr == "shape":
                return Tup(base.shape) if base.shape is not None else None
            if attr == "ndim":
                return (Scalar(base.rank, "literal")
                        if base.rank is not None else None)
            if attr == "dtype":
                return Scalar(value=base.dtype.name or None,
                              origin="literal", dtype=base.dtype,
                              sym="<dtype>")
            if attr == "T":
                shape = (tuple(reversed(base.shape))
                         if base.shape is not None else None)
                return Array(shape, base.dtype)
            return None
        if isinstance(base, Tree):
            return base.child(attr)
        # Config-knob read: `cfg.engine.max_text_len`, `ecfg.image_buckets`.
        knob = self.knobs.field(attr)
        if knob is not None:
            dotted = self.ctx.resolve(node.value)
            src = node.value
            base_name = (src.id if isinstance(src, ast.Name)
                         else src.attr if isinstance(src, ast.Attribute)
                         else "")
            if _looks_config(dotted or base_name):
                return self._knob_scalar(knob, node.lineno)
        if attr == "bucket":
            # `req.bucket` — prepared requests carry an already-bucketed
            # row count (engine.prepare routes through bucket_for).
            return Scalar(origin="bucket", sym=".bucket",
                          witness=((self.ctx.rel_path, node.lineno,
                                    "reads `.bucket` of a prepared "
                                    "request (bucketed upstream by "
                                    "EngineConfig.bucket_for)"),))
        return None

    def _knob_scalar(self, knob: Knob, line: int):
        step = (knob.path, knob.line,
                f"declared `{knob.sym} = {knob.value!r}`")
        use = (self.ctx.rel_path, line, f"reads config knob `{knob.sym}`")
        if isinstance(knob.value, (tuple, list)):
            elts = tuple(
                Scalar(v, "config", sym=knob.sym, witness=(step, use))
                for v in knob.value)
            return Tup(elts)
        return Scalar(knob.value, "config", sym=knob.sym,
                      witness=(step, use))

    # ----------------------------------------------------------- calls
    def _call(self, node: ast.Call, env: Dict[str, object]):
        resolved = self.ctx.resolve(node.func)
        func = node.func
        # Evaluate arguments first — reports (promotions) must fire even
        # for calls the interpreter doesn't model.
        arg_vals = [self.eval(a, env) for a in node.args]
        kw_vals = {kw.arg: self.eval(kw.value, env)
                   for kw in node.keywords if kw.arg}

        if isinstance(func, ast.Name):
            if func.id == "len" and len(node.args) == 1:
                return self._len(node, arg_vals[0])
            if func.id in ("min", "max", "int", "abs", "round") \
                    and node.args:
                return self._scalar_math(node, arg_vals)
            if func.id == "sorted" and node.args:
                return arg_vals[0]
            if func.id == "range":
                return self._scalar_math(node, arg_vals)
        if isinstance(func, ast.Attribute):
            attr = func.attr
            if attr in _BUCKETIZERS:
                arg = arg_vals[0] if arg_vals else None
                chain = tuple(arg.witness) if isinstance(arg, Scalar) \
                    else ()
                return Scalar(
                    origin="bucket", sym=f"EngineConfig.{attr}",
                    witness=(chain + (
                        (self.ctx.rel_path, node.lineno,
                         f"bucketized via `EngineConfig.{attr}()` — "
                         f"domain bounded by the declared buckets"),)
                    )[:_MAX_WITNESS])
            if attr == "all_row_buckets":
                return self._all_row_buckets(node)
            if attr == "astype" and node.args:
                recv = self.eval(func.value, env)
                dt = self._as_dtype(node.args[0], env) or UNKNOWN_DT
                shape = recv.shape if isinstance(recv, Array) else None
                return Array(shape, dataclasses.replace(dt, ctor_line=0))
            if attr == "reshape":
                recv = self.eval(func.value, env)
                dt = recv.dtype if isinstance(recv, Array) else UNKNOWN_DT
                shape_val = (Tup(tuple(arg_vals))
                             if len(node.args) > 1
                             else (arg_vals[0] if arg_vals else None))
                return Array(self._as_shape(shape_val), dt)
            if attr in ("sum", "mean", "squeeze", "flatten", "ravel"):
                recv = self.eval(func.value, env)
                if isinstance(recv, Array):
                    return Array(None, recv.dtype)
                return None
            if attr in ("get", "pop") and node.args:
                recv = self.eval(func.value, env)
                key = arg_vals[0]
                if (isinstance(recv, Tree) and isinstance(key, Scalar)
                        and isinstance(key.value, str)):
                    return recv.child(key.value)
                return None
        ns_call = self._namespace_call(resolved)
        if ns_call is not None:
            return self._array_ctor(ns_call, node, arg_vals, kw_vals, env)
        return None

    @staticmethod
    def _namespace_call(resolved: str) -> Optional[str]:
        for ns in _ARRAY_NAMESPACES:
            if resolved.startswith(ns + "."):
                return resolved[len(ns) + 1:]
        return None

    def _array_ctor(self, name: str, node: ast.Call, arg_vals, kw_vals,
                    env: Dict[str, object]):
        if name in _SHAPE_CTORS or name == "linspace":
            dtype_expr = None
            for kw in node.keywords:
                if kw.arg == "dtype":
                    dtype_expr = kw.value
            dtype_pos = {"zeros": 1, "ones": 1, "empty": 1, "full": 2}
            pos = dtype_pos.get(name)
            if dtype_expr is None and pos is not None \
                    and len(node.args) > pos:
                dtype_expr = node.args[pos]
            dt = self._as_dtype(dtype_expr, env) if dtype_expr is not None \
                else None
            if dt is None:
                dt = (DType("float32", ctor_line=node.lineno)
                      if name in _FLOAT_DEFAULT_CTORS else UNKNOWN_DT)
            shape = self._as_shape(arg_vals[0]) if arg_vals else None
            return Array(shape, dt)
        if name in ("array", "asarray"):
            dtype_expr = None
            for kw in node.keywords:
                if kw.arg == "dtype":
                    dtype_expr = kw.value
            if dtype_expr is None and len(node.args) > 1:
                dtype_expr = node.args[1]
            if dtype_expr is not None:
                dt = self._as_dtype(dtype_expr, env) or UNKNOWN_DT
                return Array(None, dataclasses.replace(dt, ctor_line=0))
            src = arg_vals[0] if arg_vals else None
            if isinstance(src, Array):
                return src
            if isinstance(src, Tup):
                has_float = any(isinstance(e, Scalar)
                                and isinstance(e.value, float)
                                for e in src.elts)
                dt = (DType("float32", ctor_line=node.lineno) if has_float
                      else DType("int32"))
                return Array((Scalar(len(src.elts), "literal"),), dt)
            return Array(None, UNKNOWN_DT)
        if name == "arange":
            any_float = any(isinstance(v, Scalar)
                            and isinstance(v.value, float)
                            for v in arg_vals)
            dt = (DType("float32", ctor_line=node.lineno) if any_float
                  else DType("int32"))
            for kw in node.keywords:
                if kw.arg == "dtype":
                    dt = self._as_dtype(kw.value, env) or UNKNOWN_DT
            return Array(None, dt)
        if name == "broadcast_to" and len(node.args) >= 2:
            src = arg_vals[0]
            dt = src.dtype if isinstance(src, Array) else UNKNOWN_DT
            return Array(self._as_shape(arg_vals[1]), dt)
        if name == "pad" and arg_vals:
            src = arg_vals[0]
            dt = src.dtype if isinstance(src, Array) else UNKNOWN_DT
            return Array(None, dt)
        if name.split(".")[-1] in _ELEMENTWISE:
            return self._combine(node, arg_vals)
        return None

    def _all_row_buckets(self, node: ast.Call):
        img = self.knobs.field("image_buckets")
        thr = self.knobs.field("throughput_buckets")
        values: Set[int] = set()
        for knob in (img, thr):
            if knob is not None and isinstance(knob.value, (tuple, list)):
                values |= {v for v in knob.value if isinstance(v, int)}
        step = (self.ctx.rel_path, node.lineno,
                "iterates `EngineConfig.all_row_buckets()` — the sorted "
                "union of image_buckets and throughput_buckets")
        if values:
            return Tup(tuple(
                Scalar(v, "bucket", sym="EngineConfig.all_row_buckets",
                       witness=(step,))
                for v in sorted(values)))
        return Scalar(origin="bucket",
                      sym="EngineConfig.all_row_buckets", witness=(step,))

    def _len(self, node: ast.Call, arg):
        if isinstance(arg, Tup):
            return Scalar(len(arg.elts), "literal")
        if isinstance(arg, Array) and arg.shape is not None:
            return arg.shape[0] if arg.shape else Scalar(0, "literal")
        if isinstance(arg, Scalar):
            if arg.origin in ("param", "data"):
                stepped = arg.with_step(
                    (self.ctx.rel_path, node.lineno,
                     "`len()` of it — varies with the request payload"))
                return dataclasses.replace(stepped, value=None,
                                           origin="data")
            return dataclasses.replace(arg, value=None)
        return None

    def _scalar_math(self, node: ast.Call, arg_vals):
        origin, sym = "literal", ""
        witness: Tuple[WitnessStep, ...] = ()
        for v in arg_vals:
            if isinstance(v, Scalar):
                if _ORIGIN_RANK.get(v.origin, 4) > _ORIGIN_RANK[origin]:
                    origin, sym, witness = v.origin, v.sym, v.witness
            elif v is None:
                if _ORIGIN_RANK["unknown"] > _ORIGIN_RANK[origin]:
                    origin, sym, witness = "unknown", "", ()
        return Scalar(None, origin, sym=sym, witness=witness)

    def _combine(self, node: ast.AST, vals) -> Optional[Array]:
        """Arithmetic combination: promote dtypes, record promotion leaks,
        and keep an elementwise shape when the ranks agree."""
        dts: List[DType] = []
        shapes: List[Optional[Tuple[Scalar, ...]]] = []
        any_array = False
        for v in vals:
            if isinstance(v, Array):
                any_array = True
                dts.append(v.dtype)
                shapes.append(v.shape)
            elif isinstance(v, Scalar) and v.dtype.known:
                dts.append(v.dtype)
        if not any_array:
            return None
        acc = UNKNOWN_DT
        leaked = False
        for dt in dts:
            if not acc.known:
                acc = dt
                continue
            leak = promotion_leak(acc, dt)
            if leak is not None:
                leaked = True
                if id(node) not in self.promotions:
                    self.promotions[id(node)] = (node, leak[0], leak[1])
            acc = promote(acc, dt)
        if leaked:
            # The widening is reported once at its root; stripping the
            # ctor provenance keeps every downstream use of the (now-f32)
            # result from re-reporting the same leak.
            acc = dataclasses.replace(acc, ctor_line=0)
        shape = None
        known = [s for s in shapes if s is not None]
        if known and all(len(s) == len(known[0]) for s in known):
            shape = known[0]
        return Array(shape, acc)

    def _binop(self, node: ast.BinOp, env: Dict[str, object]):
        lhs = self.eval(node.left, env)
        rhs = self.eval(node.right, env)
        if isinstance(lhs, Array) or isinstance(rhs, Array):
            return self._combine(node, [lhs, rhs])
        if isinstance(lhs, Scalar) and isinstance(rhs, Scalar):
            value = None
            if lhs.value is not None and rhs.value is not None and \
                    isinstance(lhs.value, (int, float)) and \
                    isinstance(rhs.value, (int, float)):
                try:
                    value = _fold_binop(node.op, lhs.value, rhs.value)
                except (ZeroDivisionError, TypeError, ValueError):
                    value = None
            origin = _join_origin(lhs.origin, rhs.origin)
            worse = lhs if _ORIGIN_RANK.get(lhs.origin, 4) >= \
                _ORIGIN_RANK.get(rhs.origin, 4) else rhs
            return Scalar(value, origin, sym=worse.sym,
                          dtype=promote(lhs.dtype, rhs.dtype),
                          witness=worse.witness)
        if isinstance(lhs, Tup) and isinstance(rhs, Tup) and \
                isinstance(node.op, ast.Add):
            return Tup(lhs.elts + rhs.elts)
        return None

    def _subscript(self, node: ast.Subscript, env: Dict[str, object]):
        base = self.eval(node.value, env)
        idx = self.eval(node.slice, env)
        if isinstance(base, Tup):
            if isinstance(idx, Scalar) and isinstance(idx.value, int):
                i = idx.value
                if -len(base.elts) <= i < len(base.elts):
                    return base.elts[i]
            return element_of(base) if not isinstance(node.slice,
                                                      ast.Slice) else base
        if isinstance(base, Tree) and isinstance(idx, Scalar) \
                and isinstance(idx.value, str):
            return base.child(idx.value)
        if isinstance(base, Array):
            if isinstance(node.slice, ast.Slice):
                return Array(None, base.dtype)
            if base.shape is not None and len(base.shape) >= 1 \
                    and not isinstance(node.slice, ast.Tuple):
                if len(base.shape) == 1:
                    return Scalar(origin="data", dtype=base.dtype)
                return Array(base.shape[1:], base.dtype)
            return Array(None, base.dtype)
        return None

    # ------------------------------------------------------------- dtypes
    def _as_dtype(self, expr: Optional[ast.AST], env: Dict[str, object]
                  ) -> Optional[DType]:
        if expr is None:
            return None
        resolved = self.ctx.resolve(expr)
        leaf = resolved.split(".")[-1] if resolved else ""
        if leaf in _DTYPE_NAMES:
            return DType(leaf)
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str) \
                and expr.value in _DTYPE_NAMES:
            return DType(expr.value)
        val = self.eval(expr, env)
        if isinstance(val, Scalar):
            if val.sym == "<dtype>" and val.dtype.known:
                return dataclasses.replace(val.dtype, ctor_line=0)
            if isinstance(val.value, str) and val.value in _DTYPE_NAMES:
                return DType(val.value)
            if isinstance(val.value, str):
                # A config-bound dtype string we don't recognize —
                # treat as explicit (never a default-dtype leak).
                return DType(val.value)
        return None

    def _as_shape(self, val) -> Optional[Tuple[Scalar, ...]]:
        if isinstance(val, Tup):
            return tuple(e if isinstance(e, Scalar) else Scalar()
                         for e in val.elts)
        if isinstance(val, Scalar):
            return (val,)
        return None


def _fold_binop(op: ast.AST, a, b):
    if isinstance(op, ast.Add):
        return a + b
    if isinstance(op, ast.Sub):
        return a - b
    if isinstance(op, ast.Mult):
        return a * b
    if isinstance(op, ast.FloorDiv):
        return a // b
    if isinstance(op, ast.Mod):
        return a % b
    if isinstance(op, ast.Pow) and abs(b) < 64:
        return a ** b
    return None


def interpret_function(ctx: ModuleContext, fn: ast.AST, knobs: KnobTable,
                       param_origin: str = "param") -> ShapeInterp:
    """Build, solve, and return the interpreter for one function."""
    return ShapeInterp(ctx, fn, knobs, param_origin=param_origin).run()


def flows_from(witness: Tuple[WitnessStep, ...],
               final: Optional[WitnessStep] = None) -> List[List[dict]]:
    """Witness chain → the Finding.flows / SARIF codeFlows schema."""
    steps = list(witness) + ([final] if final is not None else [])
    if not steps:
        return []
    return [[{"path": p, "line": ln, "message": msg}
             for p, ln, msg in steps]]


def call_nodes_in(event: Event) -> Iterator[ast.Call]:
    for node in iter_event_nodes(event):
        if isinstance(node, ast.Call):
            yield node
