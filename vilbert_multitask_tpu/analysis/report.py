"""Finding reporters: human-readable lines and a machine JSON document."""

from __future__ import annotations

import json
from typing import Dict, List, Sequence

from vilbert_multitask_tpu.analysis.core import Finding


def _counts(findings: Sequence[Finding]) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for f in findings:
        out[f.severity] = out.get(f.severity, 0) + 1
    return out


def render_human(new: Sequence[Finding], baselined: Sequence[Finding],
                 stale: Sequence[str], files_scanned: int) -> str:
    lines: List[str] = []
    for f in new:
        lines.append(f"{f.path}:{f.line}:{f.col}: {f.rule}[{f.severity}] "
                     f"{f.message} ({f.name})")
    counts = _counts(new)
    summary = (f"vmtlint: {len(new)} finding(s) "
               f"({counts.get('error', 0)} error, "
               f"{counts.get('warning', 0)} warning) "
               f"in {files_scanned} file(s)")
    if baselined:
        summary += f"; {len(baselined)} baselined"
    if stale:
        summary += f"; {len(stale)} stale baseline entr(y/ies)"
        for fp in stale:
            lines.append(f"stale baseline entry (fixed? remove it): {fp}")
    lines.append(summary)
    return "\n".join(lines)


def render_json(new: Sequence[Finding], baselined: Sequence[Finding],
                stale: Sequence[str], files_scanned: int) -> str:
    return json.dumps({
        "findings": [f.to_json() for f in new],
        "baselined": [f.to_json() for f in baselined],
        "stale_baseline_entries": list(stale),
        "counts": _counts(new),
        "files_scanned": files_scanned,
    }, indent=2)


_SARIF_LEVEL = {"error": "error", "warning": "warning"}


def render_sarif(new: Sequence[Finding], baselined: Sequence[Finding],
                 stale: Sequence[str], files_scanned: int) -> str:
    """Minimal SARIF 2.1.0 for editor/CI integration. Only unsuppressed,
    non-baselined findings become results — the baseline is this tool's
    suppression store, so re-surfacing grandfathered rows in an IDE would
    undo it."""
    from vilbert_multitask_tpu.analysis.rules import RULES

    rules_meta = [{
        "id": cls.id,
        "name": cls.name,
        "shortDescription": {"text": cls.description},
        "defaultConfiguration": {
            "level": _SARIF_LEVEL.get(cls.severity, "warning")},
    } for cls in RULES]
    results = []
    for f in new:
        result = {
            "ruleId": f.rule,
            "level": _SARIF_LEVEL.get(f.severity, "warning"),
            "message": {"text": f.message},
            "partialFingerprints": {"vmtlint/v1": f.fingerprint()},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": {"startLine": f.line, "startColumn": f.col},
                },
            }],
        }
        if f.flows:
            # Witness chains (VMT119 reports one per conflicting lock
            # order) as threadFlows — clickable step-by-step in SARIF
            # viewers.
            result["codeFlows"] = [{
                "threadFlows": [{
                    "locations": [{
                        "location": {
                            "physicalLocation": {
                                "artifactLocation": {"uri": step["path"]},
                                "region": {"startLine": step["line"]},
                            },
                            "message": {"text": step["message"]},
                        },
                    } for step in flow],
                }],
            } for flow in f.flows]
        results.append(result)
    return json.dumps({
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "vmtlint",
                "rules": rules_meta,
            }},
            "results": results,
        }],
    }, indent=2)
