"""Shape-tier rules (VMT124–VMT127), built on the abstract interpreter.

Every recompile-hazard rule before this tier (VMT102 closure capture,
VMT121 knob drift) reasoned about *names*; these four reason about
*values*: where a static argument's value originates, which dtype an
array actually carries after promotion, whether a PartitionSpec's rank
can fit the array it shards, and whether a literal dimension belongs to
the declared bucket vocabulary. They live in their own module (like the
lock rules in locks.py) and are imported into the rules registry.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from vilbert_multitask_tpu.analysis.context import ModuleContext
from vilbert_multitask_tpu.analysis.core import Finding, Rule
from vilbert_multitask_tpu.analysis.shapes import (
    Array,
    KnobTable,
    Scalar,
    call_nodes_in,
    flows_from,
    interpret_function,
    jit_static_bindings,
    knob_table,
)

_PARTITION_SPEC = "jax.sharding.PartitionSpec"
_SHARDING_SINKS = {"jax.lax.with_sharding_constraint", "jax.device_put"}
# Dimensions at or below this are structural constants (coords, heads,
# channels), not bucket-sized axes; only larger literals must trace back
# to a declared knob.
_STRUCTURAL_DIM = 8


def _project_knobs(ctx: ModuleContext) -> KnobTable:
    if ctx.project is not None:
        return knob_table(ctx.project)
    table = KnobTable()
    return table


def _module_functions(ctx: ModuleContext) -> Iterator[ast.AST]:
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _own_scope(fn: ast.AST) -> Iterator[ast.AST]:
    """Walk a function's own scope: no nested defs/lambdas/classes."""
    todo: List[ast.AST] = list(getattr(fn, "body", ()))
    while todo:
        node = todo.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.ClassDef)):
                continue
            todo.append(child)


def _shape_scalars(val) -> Iterator[Scalar]:
    """Flatten an abstract shape-ish value to its Scalar dims."""
    from vilbert_multitask_tpu.analysis.shapes import Tup

    if isinstance(val, Scalar):
        yield val
    elif isinstance(val, Tup):
        for e in val.elts:
            yield from _shape_scalars(e)


class UnboundedCompileKey(Rule):
    """VMT124: a jitted function's *static* argument receives a value
    whose provenance is request/data-dependent. Every distinct value is a
    distinct XLA program — the compile-cache cardinality blowup the
    bucketing scheme exists to prevent. Values routed through
    ``bucket_for``/``row_bucket_for``/config knobs are bounded and clean.
    """

    id = "VMT124"
    name = "unbounded-compile-key"
    severity = "error"
    description = ("static jit argument fed from request/data-dependent "
                   "values — unbounded compile-cache key universe")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        bindings = jit_static_bindings(ctx)
        if not bindings:
            return
        knobs = _project_knobs(ctx)
        jit_ids = {id(info.body) for info in ctx.jit_bodies}
        seen: Set[Tuple[int, str]] = set()
        for fn in _module_functions(ctx):
            if id(fn) in jit_ids:
                # Inside a jit body the static params are already
                # trace-time constants; JAX itself rejects passing a
                # traced value onward as static.
                continue
            callees = {n.func.id for n in _own_scope(fn)
                       if isinstance(n, ast.Call)
                       and isinstance(n.func, ast.Name)
                       and n.func.id in bindings
                       and n.func.id != getattr(fn, "name", "")}
            if not callees:
                continue
            interp = interpret_function(ctx, fn, knobs)
            for event, fact in interp.iter_facts():
                for call in call_nodes_in(event):
                    if not (isinstance(call.func, ast.Name)
                            and call.func.id in callees):
                        continue
                    binding = bindings[call.func.id]
                    for expr, pname in _static_args(call, binding):
                        key = (id(call), pname)
                        if key in seen:
                            continue
                        val = interp.eval(expr, fact)
                        if not (isinstance(val, Scalar)
                                and val.origin in ("param", "data")):
                            continue
                        seen.add(key)
                        f = self.finding(
                            ctx, call,
                            f"static argument `{pname}` of jitted "
                            f"`{binding.name}` is "
                            f"{_ORIGIN_DESC[val.origin]} — every "
                            f"distinct value compiles a new XLA "
                            f"program; route it through "
                            f"`EngineConfig.bucket_for`/"
                            f"`row_bucket_for` or a config knob so "
                            f"the key universe stays bounded")
                        f.flows = flows_from(
                            val.witness,
                            (ctx.rel_path, call.lineno,
                             f"flows into static arg `{pname}` of "
                             f"jitted `{binding.name}` — a new value "
                             f"here is a new XLA program"))
                        yield f


_ORIGIN_DESC = {
    "param": "caller-controlled (an unconstrained parameter)",
    "data": "derived from request data (e.g. a payload length)",
}


def _static_args(call: ast.Call, binding
                 ) -> Iterator[Tuple[ast.expr, str]]:
    for kw in call.keywords:
        if kw.arg in binding.static_names:
            yield kw.value, kw.arg
    for i, arg in enumerate(call.args):
        if isinstance(arg, ast.Starred):
            continue
        if i < len(binding.params) and binding.params[i] in \
                binding.static_names:
            yield arg, binding.params[i]


class DtypePromotionLeak(Rule):
    """VMT125: inside jit-traced code, a low-precision operand (bf16/f16/
    int8) is silently promoted to float32 because the other operand came
    from a default-dtype constructor (``jnp.zeros(shape)`` with no
    ``dtype=``). The math runs — at double the HBM traffic the serving
    path was sized against. Explicit ``dtype=``/`astype` casts are
    deliberate and never flagged."""

    id = "VMT125"
    name = "dtype-promotion-leak"
    severity = "warning"
    description = ("silent f32 promotion in the bf16/int8 compute path "
                   "via a default-dtype constructor")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        knobs = _project_knobs(ctx)
        seen: Set[int] = set()
        for body, witness in _traced_bodies(ctx):
            interp = interpret_function(ctx, body, knobs)
            for node, low, ctor_line in interp.promotions.values():
                if id(node) in seen:
                    continue
                seen.add(id(node))
                via = f" (traced: {witness})" if witness else ""
                f = self.finding(
                    ctx, node,
                    f"`{low}` operand silently promoted to float32 by "
                    f"the default-dtype constructor at line "
                    f"{ctor_line}{via}; pass an explicit `dtype=` to "
                    f"keep the low-precision path low-precision")
                f.flows = [[
                    {"path": ctx.rel_path, "line": ctor_line,
                     "message": "constructor defaults to float32 — no "
                                "`dtype=` given"},
                    {"path": ctx.rel_path, "line": node.lineno,
                     "message": f"combines with a `{low}` operand: "
                                f"result widens to float32"},
                ]]
                yield f


def _traced_bodies(ctx: ModuleContext
                   ) -> Iterator[Tuple[ast.AST, str]]:
    """Jit bodies plus project-traced helpers, FunctionDefs only (the CFG
    builder wants a statement body, which lambdas don't have)."""
    seen: Set[int] = set()
    for info in ctx.jit_bodies:
        body = info.body
        if isinstance(body, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and id(body) not in seen:
            seen.add(id(body))
            yield body, ""
    if ctx.project is not None:
        for info, witness in ctx.project.traced_helpers(ctx):
            body = info.body
            if isinstance(body, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and id(body) not in seen:
                seen.add(id(body))
                yield body, witness


class PartitionRankMismatch(Rule):
    """VMT126: a ``PartitionSpec`` names more axes than the array it
    constrains has dimensions. VMT111 checks the axis *names* against the
    project's declared mesh; this checks the *rank* against the abstract
    shape — the mismatch XLA reports only at trace time on a real mesh.
    Specs shorter than the rank are fine (JAX pads with replication)."""

    id = "VMT126"
    name = "partition-rank-mismatch"
    severity = "error"
    description = "PartitionSpec rank exceeds the abstract array rank"

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        if "PartitionSpec" not in ctx.source:
            return
        knobs = _project_knobs(ctx)
        seen: Set[int] = set()
        for fn in _module_functions(ctx):
            sinks = [n for n in _own_scope(fn)
                     if isinstance(n, ast.Call)
                     and ctx.resolve(n.func) in _SHARDING_SINKS
                     and len(n.args) >= 2]
            if not sinks:
                continue
            interp = interpret_function(ctx, fn, knobs)
            for event, fact in interp.iter_facts():
                for call in call_nodes_in(event):
                    if not (isinstance(call, ast.Call)
                            and ctx.resolve(call.func) in _SHARDING_SINKS
                            and len(call.args) >= 2):
                        continue
                    if id(call) in seen:
                        continue
                    val = interp.eval(call.args[0], fact)
                    rank = _rank_of(val)
                    if rank is None:
                        continue
                    for spec in _partition_specs(ctx, call.args[1]):
                        spec_rank = _spec_rank(spec)
                        if spec_rank is None or spec_rank <= rank:
                            continue
                        seen.add(id(call))
                        yield self.finding(
                            ctx, spec,
                            f"PartitionSpec names {spec_rank} axes but "
                            f"the constrained array has rank {rank} — "
                            f"XLA rejects this at trace time on a real "
                            f"mesh; drop the extra axes or reshape "
                            f"first")


def _rank_of(val) -> Optional[int]:
    from vilbert_multitask_tpu.analysis.shapes import Tree, is_int8_pair

    if isinstance(val, Array):
        return val.rank
    if isinstance(val, Tree) and is_int8_pair(val):
        inner = val.child("int8")
        if isinstance(inner, Array):
            return inner.rank
    return None


def _partition_specs(ctx: ModuleContext, expr: ast.expr
                     ) -> Iterator[ast.Call]:
    for node in ast.walk(expr):
        if isinstance(node, ast.Call) \
                and ctx.resolve(node.func) == _PARTITION_SPEC:
            yield node


def _spec_rank(spec: ast.Call) -> Optional[int]:
    if any(isinstance(a, ast.Starred) for a in spec.args):
        return None
    return len(spec.args)


class BucketShapeDrift(Rule):
    """VMT127: a literal dimension in jit-traced models/engine code that
    the declared config-knob vocabulary (bucket tuples, max_text_len,
    max_regions, …) cannot produce. A shape the bucketing scheme doesn't
    know about means a compile the warmup never warms and the AOT
    manifest never lists — a silent recompile on the serving path."""

    id = "VMT127"
    name = "bucket-shape-drift"
    severity = "warning"
    description = ("literal shape in models/engine jit code not "
                   "derivable from declared buckets/knobs")

    def check(self, ctx: ModuleContext) -> Iterator[Finding]:
        parts = ctx.rel_path.split("/")
        if "models" not in parts and "engine" not in parts:
            return
        knobs = _project_knobs(ctx)
        if knobs.empty:
            # Subset scan without config.py in view: no vocabulary to
            # judge against, so stay silent rather than guess.
            return
        vocab = knobs.ints()
        seen: Set[Tuple[int, int]] = set()
        for body, _witness in _traced_bodies(ctx):
            interp = interpret_function(ctx, body, knobs)
            for event, fact in interp.iter_facts():
                for call in call_nodes_in(event):
                    for expr in _shape_exprs(ctx, call):
                        val = interp.eval(expr, fact)
                        for dim in _shape_scalars(val):
                            if not (dim.origin == "literal"
                                    and isinstance(dim.value, int)
                                    and not isinstance(dim.value, bool)):
                                continue
                            if dim.value <= _STRUCTURAL_DIM \
                                    or dim.value in vocab:
                                continue
                            key = (id(call), dim.value)
                            if key in seen:
                                continue
                            seen.add(key)
                            yield self.finding(
                                ctx, call,
                                f"literal dimension {dim.value} is not "
                                f"derivable from any declared config "
                                f"knob or bucket — this shape compiles "
                                f"outside the declared universe (never "
                                f"warmed, never AOT-cached); derive it "
                                f"from a config knob or add it to the "
                                f"bucket vocabulary")


def _shape_exprs(ctx: ModuleContext, call: ast.Call
                 ) -> Iterator[ast.expr]:
    """The shape-position argument expressions of a constructor/reshape/
    pad/broadcast call (the places literal dims sneak in)."""
    func = call.func
    resolved = ctx.resolve(func)
    leaf = resolved.split(".")[-1] if resolved else ""
    ns = resolved.startswith(("jax.numpy.", "numpy."))
    if ns and leaf in ("zeros", "ones", "full", "empty") and call.args:
        yield call.args[0]
    elif ns and leaf == "broadcast_to" and len(call.args) >= 2:
        yield call.args[1]
    elif ns and leaf == "pad" and len(call.args) >= 2:
        yield call.args[1]
    elif isinstance(func, ast.Attribute) and func.attr == "reshape":
        yield from call.args
    for kw in call.keywords:
        if kw.arg == "shape":
            yield kw.value
