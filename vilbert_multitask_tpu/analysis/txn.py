"""Transaction-scope analysis + the durable-state manifest (TXN_SURFACE.json).

The atomicity tier on top of :mod:`analysis.sql`: it recovers every
*connection scope* — a ``with self._conn() as c:`` body (or a bare
``c = factory()`` binding) whose context manager resolves to a
sqlite-connection factory anywhere in the project — orders the SQL
statements executed inside it via the CFG/worklist machinery, and
classifies the scope's transaction mode:

- ``immediate``/``exclusive``: an explicit ``BEGIN IMMEDIATE``/
  ``EXCLUSIVE`` statement opens the scope — the write lock is taken up
  front, so a read-modify-write inside is atomic across OS processes;
- ``deferred``: a plain ``with`` scope — pysqlite only issues the
  implicit ``BEGIN`` before DML, so a ``SELECT`` takes no write lock and
  DDL autocommits per-statement;
- ``autocommit``: a connection used without ``with`` — nothing groups
  the statements at all.

From those facts it precomputes the findings the VMT128–131 rules
(:mod:`analysis.txnrules`) re-anchor per module, and builds the
generative ``TXN_SURFACE.json`` manifest: every durable table with its
full migrated schema, every transaction site with mode and statement
list, and the literal-write state machines (``jobs.status``,
``jobs.dead_notified``) that ROADMAP item 3's multi-process queue work
consumes as its contract.

Stdlib-only, like the rest of the analysis package — the stores are
analyzed as source, never imported.
"""

from __future__ import annotations

import ast
import difflib
import json
import re
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from vilbert_multitask_tpu.analysis.cfg import (
    WithEnter,
    WithExit,
    build_cfg,
    iter_event_nodes,
)
from vilbert_multitask_tpu.analysis.context import ModuleContext
from vilbert_multitask_tpu.analysis.dataflow import (
    ForwardAnalysis,
    iter_event_facts,
    solve,
)
from vilbert_multitask_tpu.analysis.sql import (
    EXECUTE_METHODS,
    SqlStatement,
    statements_from_call,
)

TXN_VERSION = 1
MANIFEST_NAME = "TXN_SURFACE.json"

_DEFAULT_RE = re.compile(r"\bDEFAULT\s+('[^']*'|-?\d+(?:\.\d+)?)", re.I)
_SQLITE_PSEUDO_COLS = frozenset(("rowid", "oid", "_rowid_"))


def _witness(path: str, line: int, note: str) -> dict:
    return {"path": path, "line": line, "message": note}


def _qualname(ctx: ModuleContext, fn: ast.AST) -> str:
    parts = [getattr(fn, "name", "<lambda>")]
    for anc in ctx.ancestors(fn):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef)):
            parts.append(anc.name)
    mod = ctx.rel_path[:-3].replace("/", ".")
    return f"{mod}:{'.'.join(reversed(parts))}"


def stmt_reads(st: SqlStatement) -> Tuple[str, ...]:
    """Every column position a statement *reads* — the credit set the
    dead-column direction of VMT130 and the manifest both use."""
    seen: Dict[str, None] = {}
    for group in (st.columns_read, st.where_columns, st.order_by,
                  st.group_by):
        for c in group:
            seen.setdefault(c)
    return tuple(seen)


# ---------------------------------------------------------------- scopes
class ExecSite:
    """One ``.execute``-family call inside a connection scope."""

    __slots__ = ("call", "line", "col", "statements")

    def __init__(self, ctx: ModuleContext, call: ast.Call) -> None:
        self.call = call
        self.line = call.lineno
        self.col = call.col_offset
        self.statements: List[SqlStatement] = statements_from_call(ctx, call)


class ConnScope:
    """One connection scope: the statements one sqlite connection runs.

    ``kind`` is ``"with"`` (context-managed — commits on exit) or
    ``"bare"`` (a plain assignment from a factory — nothing commits).
    ``mode`` is computed after site collection: ``immediate`` /
    ``exclusive`` / ``deferred`` / ``autocommit``.
    """

    __slots__ = ("ctx", "fn_node", "function", "path", "line", "conn_var",
                 "kind", "factory", "sites", "mode")

    def __init__(self, ctx: ModuleContext, fn_node: ast.AST, line: int,
                 conn_var: Optional[str], kind: str, factory: str) -> None:
        self.ctx = ctx
        self.fn_node = fn_node
        self.function = _qualname(ctx, fn_node)
        self.path = ctx.rel_path
        self.line = line
        self.conn_var = conn_var
        self.kind = kind
        self.factory = factory
        self.sites: List[ExecSite] = []
        self.mode = "deferred"

    def add_site(self, ctx: ModuleContext, call: ast.Call) -> None:
        self.sites.append(ExecSite(ctx, call))

    def finalize(self) -> None:
        self.sites.sort(key=lambda s: (s.line, s.col))
        modes = [st.begin_mode for site in self.sites
                 for st in site.statements if st.kind == "begin"]
        if "exclusive" in modes:
            self.mode = "exclusive"
        elif "immediate" in modes:
            self.mode = "immediate"
        else:
            # An explicit plain BEGIN is still deferred; a bare conn with
            # no BEGIN at all groups nothing.
            self.mode = "deferred" if (self.kind == "with" or modes) \
                else "autocommit"

    def entries(self) -> List[Tuple[ExecSite, SqlStatement]]:
        return [(site, st) for site in self.sites for st in site.statements]


class _OpenConnScopes(ForwardAnalysis):
    """Must-open connection scopes before each event (join = ∩) — the
    same lock-set shape ``analysis.locks`` uses, over conn withitems."""

    def __init__(self, items: Dict[int, ConnScope]) -> None:
        self.items = items

    def initial(self) -> FrozenSet[int]:
        return frozenset()

    def join(self, a: FrozenSet[int], b: FrozenSet[int]) -> FrozenSet[int]:
        return a & b

    def transfer(self, event, fact: FrozenSet[int]) -> FrozenSet[int]:
        if isinstance(event, WithEnter) and id(event.item) in self.items:
            return fact | {id(event.item)}
        if isinstance(event, WithExit) and id(event.item) in self.items:
            return fact - {id(event.item)}
        return fact


# ------------------------------------------------------------- the flow
class TxnFlow:
    """Project-wide transaction facts, cached on the ProjectGraph.

    Rules consume the precomputed finding lists (``rmw``,
    ``multi_write``, ``drift``, ``claims``) filtered by their module's
    path; the manifest builder consumes ``scopes`` / ``schema`` /
    ``state_machines``.
    """

    def __init__(self, project) -> None:
        self.project = project
        self.factories: Set[str] = _factory_functions(project)
        self.scopes: List[ConnScope] = []
        # table -> {col: {"decl", "origin", "path", "line"}} in
        # declaration order; witness per table is the CREATE site.
        self.schema: Dict[str, Dict[str, dict]] = {}
        self.table_witness: Dict[str, Tuple[str, int]] = {}
        self.links: List[dict] = []        # read→write deps, every mode
        self.rmw: List[dict] = []          # VMT128
        self.multi_write: List[dict] = []  # VMT129
        self.drift: List[dict] = []        # VMT130 (kind: unknown | dead)
        self.claims: List[dict] = []       # VMT131
        self.state_machines: Dict[str, Dict[str, dict]] = {}
        self._collect_scopes()
        self._collect_schema()
        self._collect_links()
        self._check_rmw()
        self._check_multi_write()
        self._check_drift()
        self._check_claims()
        self._recover_state_machines()

    # ------------------------------------------------------- collection
    def _collect_scopes(self) -> None:
        for mod in sorted(self.project.modules.values(),
                          key=lambda m: m.name):
            ctx = mod.ctx
            if "execute" not in ctx.source:
                continue
            for fn in ast.walk(ctx.tree):
                if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self._scopes_in_function(ctx, fn)

    def _factory_of(self, ctx: ModuleContext,
                    expr: ast.AST) -> Optional[str]:
        """Factory name when ``expr`` is a call producing a sqlite
        connection: ``sqlite3.connect(...)`` directly, ``self._conn()``
        on a discovered factory method, or a (possibly imported) factory
        function by name — the ProjectGraph-backed resolution that lets
        one pass cover all three stores."""
        if not isinstance(expr, ast.Call):
            return None
        resolved = ctx.resolve(expr.func)
        if resolved == "sqlite3.connect":
            return "sqlite3.connect"
        if isinstance(expr.func, ast.Attribute) \
                and expr.func.attr in self.factories:
            return expr.func.attr
        if resolved and resolved.split(".")[-1] in self.factories:
            return resolved.split(".")[-1]
        return None

    def _scopes_in_function(self, ctx: ModuleContext, fn: ast.AST) -> None:
        items: Dict[int, ConnScope] = {}
        for node in ast.walk(fn):
            if isinstance(node, (ast.With, ast.AsyncWith)) \
                    and ctx.enclosing_function(node) is fn:
                for item in node.items:
                    fac = self._factory_of(ctx, item.context_expr)
                    if fac is None:
                        continue
                    var = (item.optional_vars.id
                           if isinstance(item.optional_vars, ast.Name)
                           else None)
                    items[id(item)] = ConnScope(ctx, fn, node.lineno, var,
                                                "with", fac)
        bare: List[Tuple[ast.Assign, ConnScope]] = []
        for node in ast.walk(fn):
            if (isinstance(node, ast.Assign)
                    and ctx.enclosing_function(node) is fn
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)):
                fac = self._factory_of(ctx, node.value)
                if fac is not None:
                    bare.append((node, ConnScope(
                        ctx, fn, node.lineno, node.targets[0].id, "bare",
                        fac)))
        if not items and not bare:
            return
        claimed: Set[int] = set()
        if items:
            cfg = build_cfg(fn)
            analysis = _OpenConnScopes(items)
            facts = solve(cfg, analysis)
            for event, fact in iter_event_facts(cfg, analysis, facts):
                if isinstance(event, (WithEnter, WithExit)):
                    continue
                for node in iter_event_nodes(event):
                    if not (isinstance(node, ast.Call)
                            and isinstance(node.func, ast.Attribute)
                            and node.func.attr in EXECUTE_METHODS
                            and isinstance(node.func.value, ast.Name)):
                        continue
                    if id(node) in claimed:
                        continue
                    cands = [items[k] for k in fact
                             if items[k].conn_var == node.func.value.id]
                    if not cands:
                        continue
                    claimed.add(id(node))
                    max(cands, key=lambda s: s.line).add_site(ctx, node)
        for assign, scope in bare:
            for node in ast.walk(fn):
                if (isinstance(node, ast.Call)
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in EXECUTE_METHODS
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id == scope.conn_var
                        and node.lineno >= assign.lineno
                        and id(node) not in claimed
                        and ctx.enclosing_function(node) is fn):
                    claimed.add(id(node))
                    scope.add_site(ctx, node)
        for scope in list(items.values()) + [s for _, s in bare]:
            if scope.sites:
                scope.finalize()
                self.scopes.append(scope)

    def _collect_schema(self) -> None:
        for scope in self.scopes:
            for site in scope.sites:
                for st in site.statements:
                    if not st.is_schema_write or not st.tables:
                        continue
                    table = st.tables[0]
                    cols = self.schema.setdefault(table, {})
                    if st.kind == "create_table":
                        self.table_witness.setdefault(
                            table, (scope.path, site.line))
                    origin = ("create" if st.kind == "create_table"
                              else "alter")
                    for col, decl in st.schema_columns:
                        cols.setdefault(col, {
                            "decl": decl, "origin": origin,
                            "path": scope.path, "line": site.line})

    # ------------------------------------------------- read→write links
    def _collect_links(self) -> None:
        for scope in self.scopes:
            self.links.extend(self._scope_links(scope))

    def _scope_links(self, scope: ConnScope) -> List[dict]:
        ctx = scope.ctx
        out: List[dict] = []
        seen: Set[Tuple[int, int, str]] = set()
        entries = scope.entries()
        fn_assigns = sorted(
            (a for a in ast.walk(scope.fn_node)
             if isinstance(a, ast.Assign)
             and ctx.enclosing_function(a) is scope.fn_node),
            key=lambda a: a.lineno)
        for rsite, rst in entries:
            if rst.kind != "select" or not rst.tables:
                continue
            assign = _assign_of(ctx, rsite.call)
            if assign is None:
                continue
            base = _target_names(assign)
            if not base:
                continue
            # Taint events: (line, names-added, witness-step), monotone.
            taint_events: List[Tuple[int, Set[str], dict]] = []
            tainted = set(base)
            for a in fn_assigns:
                if a.lineno <= rsite.line or a is assign:
                    continue
                if _loads(a.value) & tainted:
                    added = _target_names(a) - tainted
                    if added:
                        tainted |= added
                        taint_events.append((a.lineno, added, _witness(
                            ctx.rel_path, a.lineno,
                            f"`{', '.join(sorted(added))}` derived from "
                            f"the read result")))
            for wsite, wst in entries:
                if not wst.is_write or wsite.line <= rsite.line:
                    continue
                shared = [t for t in wst.tables if t in rst.tables]
                if not shared:
                    continue
                key = (rsite.line, wsite.line, shared[0])
                if key in seen:
                    continue
                taint_at = set(base)
                steps = [_witness(
                    ctx.rel_path, rsite.line,
                    f"SELECT on `{rst.tables[0]}` — result bound to "
                    f"`{', '.join(sorted(base))}` (no write lock taken)")]
                for line, added, step in taint_events:
                    if line < wsite.line:
                        taint_at |= added
                        steps.append(step)
                dep = None
                if _loads_in_args(wsite.call) & taint_at:
                    dep = "data"
                else:
                    guard = _guard_if(ctx, scope.fn_node, rsite.line,
                                      wsite.line, taint_at)
                    if guard is not None:
                        dep = "control"
                        steps.append(_witness(
                            ctx.rel_path, guard.lineno,
                            "read result decides whether the write "
                            "runs (early exit guard)"))
                if dep is None:
                    continue
                seen.add(key)
                steps.append(_witness(
                    ctx.rel_path, wsite.line,
                    f"dependent {wst.kind.upper()} on `{shared[0]}` "
                    f"commits here"))
                out.append({
                    "scope": scope, "read_site": rsite, "read": rst,
                    "write_site": wsite, "write": wst,
                    "table": shared[0], "dep": dep, "steps": steps})
        return out

    # ------------------------------------------------------------ rules
    def _check_rmw(self) -> None:
        for link in self.links:
            scope = link["scope"]
            if scope.mode not in ("deferred", "autocommit"):
                continue
            table = link["table"]
            self.rmw.append({
                "path": scope.path,
                "line": link["read_site"].line,
                "col": link["read_site"].col,
                "message": (
                    f"read-modify-write on `{table}` inside a "
                    f"{scope.mode} connection scope: the SELECT takes no "
                    f"write lock, so another process can commit between "
                    f"it and the dependent "
                    f"{link['write'].kind.upper()} at line "
                    f"{link['write_site'].line} (cross-process lost "
                    f"update / SQLITE_BUSY lock upgrade) — open the "
                    f"scope with c.execute(\"BEGIN IMMEDIATE\") so read "
                    f"and write share one write transaction"),
                "flows": [list(link["steps"])],
            })

    def _check_multi_write(self) -> None:
        for scope in self.scopes:
            if scope.mode not in ("deferred", "autocommit"):
                continue
            ddl_units: Dict[str, int] = {}
            dml_tables: Set[str] = set()
            first_site: Dict[str, int] = {}
            for site in scope.sites:
                per_site: Dict[str, int] = {}
                for st in site.statements:
                    if not st.tables:
                        continue
                    t = st.tables[0]
                    if st.is_schema_write:
                        per_site[t] = per_site.get(t, 0) + 1
                        first_site.setdefault(t, site.line)
                    elif st.is_write:
                        dml_tables.add(t)
                        first_site.setdefault(t, site.line)
                for t, n in per_site.items():
                    if n == 1 and scope.ctx.in_loop(site.call):
                        n = 2  # the looped site runs the DDL repeatedly
                    ddl_units[t] = ddl_units.get(t, 0) + n
            for t in sorted(set(ddl_units) | dml_tables):
                units = ddl_units.get(t, 0) + (1 if t in dml_tables else 0)
                if units < 2 or ddl_units.get(t, 0) == 0:
                    continue
                self.multi_write.append({
                    "path": scope.path, "line": scope.line, "col": 0,
                    "message": (
                        f"{units} dependent writes to `{t}` split across "
                        f"autocommit transactions in one {scope.mode} "
                        f"scope (pysqlite autocommits each DDL "
                        f"statement; only DML shares the implicit "
                        f"transaction) — a crash or concurrent boot "
                        f"between them leaves a partial migration; take "
                        f"BEGIN IMMEDIATE so the whole migration is one "
                        f"transaction"),
                })

    def _check_drift(self) -> None:
        reads_by_table: Dict[str, Set[str]] = {t: set() for t in self.schema}
        for scope in self.scopes:
            for site in scope.sites:
                for st in site.statements:
                    for t in st.tables:
                        if t in reads_by_table:
                            reads_by_table[t].update(stmt_reads(st))
        # Unknown columns: narrow, structurally-confident positions only.
        seen: Set[Tuple[str, int, str]] = set()
        for scope in self.scopes:
            for site in scope.sites:
                for st in site.statements:
                    if not st.tables or st.is_schema_write \
                            or st.kind in ("begin", "commit", "pragma"):
                        continue
                    if any(t not in self.schema for t in st.tables):
                        continue  # table unknown — stay conservative
                    known: Set[str] = set()
                    for t in st.tables:
                        known.update(self.schema[t])
                    cols: Dict[str, None] = {}
                    for group in (st.columns_read, st.columns_written,
                                  st.where_columns, st.order_by,
                                  st.group_by, st.set_columns):
                        for c in group:
                            cols.setdefault(c)
                    for col in cols:
                        if col in known \
                                or col.lower() in _SQLITE_PSEUDO_COLS:
                            continue
                        key = (scope.path, site.line, col)
                        if key in seen:
                            continue
                        seen.add(key)
                        close = difflib.get_close_matches(
                            col, sorted(known), n=2)
                        hint = (" — did you mean "
                                + " or ".join(f"`{c}`" for c in close)
                                + "?") if close else ""
                        self.drift.append({
                            "kind": "unknown", "path": scope.path,
                            "line": site.line, "col": site.col,
                            "message": (
                                f"column `{col}` is not in the modeled "
                                f"schema of "
                                f"{'/'.join(sorted(st.tables))} (CREATE "
                                f"TABLE + ALTER migrations){hint}"),
                        })
        for t in sorted(self.schema):
            reads = reads_by_table.get(t, set())
            for col, info in self.schema[t].items():
                if col in reads:
                    continue
                self.drift.append({
                    "kind": "dead", "path": info["path"],
                    "line": info["line"], "col": 0,
                    "message": (
                        f"column `{t}.{col}` is never read by any SQL "
                        f"statement in the project — dead durable state "
                        f"(declared via {info['origin'].upper()} here); "
                        f"read it or drop it from the schema"),
                })

    def _check_claims(self) -> None:
        seen: Set[Tuple[str, int]] = set()
        for scope in self.scopes:
            entries = scope.entries()
            for ssite, sst in entries:
                if sst.kind != "select" or not sst.has_limit \
                        or sst.order_by or not sst.tables:
                    continue
                feeds = [wst for wsite, wst in entries
                         if wst.is_write and wsite.line > ssite.line
                         and any(t in sst.tables for t in wst.tables)]
                if not feeds:
                    continue
                key = (scope.path, ssite.line)
                if key in seen:
                    continue
                seen.add(key)
                self.claims.append({
                    "path": scope.path, "line": ssite.line,
                    "col": ssite.col,
                    "message": (
                        f"competitive SELECT on `{sst.tables[0]}` uses "
                        f"LIMIT without a total ORDER BY and feeds a "
                        f"claim-style write — which row wins is "
                        f"arbitrary across competing processes "
                        f"(unfair/flappy claim order); add a total "
                        f"ORDER BY"),
                })

    # ---------------------------------------------------- state machines
    def _recover_state_machines(self) -> None:
        link_by_write = {id(link["write_site"].call): link
                         for link in self.links}
        machines: Dict[str, Dict[str, dict]] = {}
        for scope in self.scopes:
            for site in scope.sites:
                for st in site.statements:
                    if st.kind not in ("update", "insert") \
                            or not st.tables:
                        continue
                    table = st.tables[0]
                    if table not in self.schema:
                        continue
                    values: Dict[str, List[str]] = {}
                    for col, lit in st.set_literals.items():
                        values.setdefault(col, []).append(lit)
                    for col, idx in st.set_params.items():
                        lits = _param_literals(scope.ctx, site.call, idx)
                        if lits:
                            values.setdefault(col, []).extend(lits)
                    for col, lits in values.items():
                        if col not in self.schema[table]:
                            continue
                        frm = st.where_literals.get(col)
                        if frm is None:
                            link = link_by_write.get(id(site.call))
                            if link is not None:
                                frm = link["read"].where_literals.get(col)
                        slot = machines.setdefault(table, {}).setdefault(
                            col, {"values": set(), "transitions": {}})
                        for lit in lits:
                            slot["values"].add(lit)
                            slot["transitions"].setdefault(
                                (frm, lit),
                                _witness(scope.path, site.line,
                                         f"written by {scope.function}"))
        for table, cols in machines.items():
            for col, slot in cols.items():
                info = self.schema[table][col]
                m = _DEFAULT_RE.search(info["decl"])
                initial = m.group(1).strip("'") if m else None
                if initial is not None:
                    slot["values"].add(initial)
                if len(slot["values"]) < 2:
                    continue
                self.state_machines.setdefault(table, {})[col] = {
                    "initial": initial,
                    "values": sorted(slot["values"]),
                    "transitions": [
                        {"from": frm, "to": to, "witness": w}
                        for (frm, to), w in sorted(
                            slot["transitions"].items(),
                            key=lambda kv: (kv[0][0] or "", kv[0][1]))],
                }


def txn_flow(project) -> TxnFlow:
    flow = getattr(project, "_txn_flow", None)
    if flow is None:
        flow = TxnFlow(project)
        project._txn_flow = flow
    return flow


# ------------------------------------------------------------- helpers
def _factory_functions(project) -> Set[str]:
    """Names of functions that return a ``sqlite3.connect`` result —
    the connection factories scope detection resolves against,
    project-wide (all three stores use the ``_conn`` idiom)."""
    names: Set[str] = set()
    for mod in project.modules.values():
        ctx = mod.ctx
        if "connect" not in ctx.source:
            continue
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            conn_names: Set[str] = set()
            returns_conn = False
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) \
                        and ctx.resolve(node.func) == "sqlite3.connect":
                    parent = ctx.parent(node)
                    if isinstance(parent, ast.Return):
                        returns_conn = True
                    elif isinstance(parent, ast.Assign):
                        for t in parent.targets:
                            if isinstance(t, ast.Name):
                                conn_names.add(t.id)
            if conn_names and not returns_conn:
                for node in ast.walk(fn):
                    if isinstance(node, ast.Return) \
                            and isinstance(node.value, ast.Name) \
                            and node.value.id in conn_names:
                        returns_conn = True
                        break
            if returns_conn:
                names.add(fn.name)
    return names


def _assign_of(ctx: ModuleContext, node: ast.AST) -> Optional[ast.Assign]:
    for anc in ctx.ancestors(node):
        if isinstance(anc, ast.Assign):
            return anc
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return None
    return None


def _target_names(assign: ast.Assign) -> Set[str]:
    names: Set[str] = set()
    for t in assign.targets:
        for n in ast.walk(t):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
                names.add(n.id)
    return names


def _loads(node: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(node)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)}


def _loads_in_args(call: ast.Call) -> Set[str]:
    out: Set[str] = set()
    for arg in list(call.args[1:]) + [kw.value for kw in call.keywords]:
        out |= _loads(arg)
    return out


def _guard_if(ctx: ModuleContext, fn: ast.AST, read_line: int,
              write_line: int, tainted: Set[str]) -> Optional[ast.If]:
    """An ``if`` between read and write whose test reads the tainted
    names and whose body can exit the function — the control dependency
    shape of ``if row is None: return`` / ``if row: return row[0]``."""
    for node in ast.walk(fn):
        if not isinstance(node, ast.If) \
                or not read_line < node.lineno <= write_line:
            continue
        if not _loads(node.test) & tainted:
            continue
        for sub in node.body + node.orelse:
            for n in ast.walk(sub):
                if isinstance(n, (ast.Return, ast.Raise)):
                    return node
    return None


def _param_literals(ctx: ModuleContext, call: ast.Call,
                    idx: int) -> Optional[List[str]]:
    """Literal values that can flow into the ``idx``-th ``?`` of an
    execute call's parameter tuple — the python side of a
    ``SET col=?`` literal write."""
    if len(call.args) < 2:
        return None
    params = call.args[1]
    if not isinstance(params, (ast.Tuple, ast.List)):
        return None
    if any(isinstance(e, ast.Starred) for e in params.elts[:idx + 1]):
        return None
    if idx >= len(params.elts):
        return None
    return _const_values(ctx, params.elts[idx])


def _const_values(ctx: ModuleContext, expr: ast.AST,
                  _depth: int = 0) -> Optional[List[str]]:
    if _depth > 4:
        return None
    if isinstance(expr, ast.Constant):
        v = expr.value
        if isinstance(v, bool):
            return [str(int(v))]
        if isinstance(v, (int, float, str)):
            return [str(v)]
        return None
    if isinstance(expr, ast.IfExp):
        a = _const_values(ctx, expr.body, _depth + 1)
        b = _const_values(ctx, expr.orelse, _depth + 1)
        if a is not None and b is not None:
            return sorted(set(a + b))
        return None
    if isinstance(expr, ast.Name):
        from vilbert_multitask_tpu.analysis.sql import _resolve_name

        bound = _resolve_name(ctx, expr)
        if bound is not None:
            return _const_values(ctx, bound, _depth + 1)
    return None


# ------------------------------------------------------------- manifest
def build_txn_surface(project) -> dict:
    """The durable-state manifest as a JSON-ready dict. Deterministic:
    no timestamps, stable ordering — byte-identical output for an
    unchanged tree is what makes ``txn --check`` a meaningful gate."""
    flow = txn_flow(project)
    tables: Dict[str, dict] = {}
    for t in sorted(flow.schema):
        w = flow.table_witness.get(t)
        tables[t] = {
            "columns": [
                {"name": c, "decl": i["decl"], "origin": i["origin"],
                 "witness": _witness(
                     i["path"], i["line"],
                     f"declared via {i['origin'].upper()}")}
                for c, i in flow.schema[t].items()],
            "witness": (_witness(w[0], w[1], "CREATE TABLE site")
                        if w else None),
        }
    sites = []
    for scope in sorted(flow.scopes,
                        key=lambda s: (s.path, s.line, s.function)):
        groups: Dict[Tuple[str, Tuple[str, ...]], dict] = {}
        for site in scope.sites:
            for st in site.statements:
                key = (st.kind, st.tables)
                g = groups.setdefault(key, {
                    "kind": st.kind, "line": site.line,
                    "tables": list(st.tables), "reads": set(),
                    "writes": set(), "spliced": False})
                g["line"] = min(g["line"], site.line)
                g["reads"].update(stmt_reads(st))
                g["writes"].update(st.columns_written)
                g["spliced"] = g["spliced"] or st.spliced
        stmts = [{
            "kind": g["kind"], "line": g["line"], "tables": g["tables"],
            "reads": sorted(g["reads"]), "writes": sorted(g["writes"]),
            "spliced": g["spliced"],
        } for g in sorted(groups.values(),
                          key=lambda g: (g["line"], g["kind"],
                                         tuple(g["tables"])))]
        sites.append({
            "function": scope.function, "path": scope.path,
            "line": scope.line, "kind": scope.kind, "mode": scope.mode,
            "factory": scope.factory, "statements": stmts,
        })
    return {
        "version": TXN_VERSION,
        "generator": "vmtlint txn",
        "tables": tables,
        "txn_sites": sites,
        "state_machines": flow.state_machines,
        "counts": {
            "tables": len(tables),
            "txn_sites": len(sites),
            "statements": sum(len(s["statements"]) for s in sites),
        },
    }


def render_txn_surface(surface: dict) -> str:
    return json.dumps(surface, indent=2, sort_keys=True) + "\n"


# ---------------------------------------------------------------- check
def diff_txn_surface(committed: Optional[dict], fresh: dict) -> List[str]:
    """Human-readable drift between the committed manifest and a fresh
    build — schema/site-level first (the actionable story), then the
    metadata fallback."""
    if committed is None:
        return [f"{MANIFEST_NAME} missing — run `vmtlint txn` and "
                f"commit it"]
    msgs: List[str] = []
    if committed.get("version") != fresh.get("version"):
        msgs.append(f"manifest version {committed.get('version')} != "
                    f"generator version {fresh.get('version')}")
    ct = sorted(committed.get("tables", {}))
    ft = sorted(fresh.get("tables", {}))
    if ct != ft:
        msgs.append(f"durable tables drifted: committed {ct} vs tree {ft}")
    for t in sorted(set(ct) & set(ft)):
        cc = [c["name"] for c in committed["tables"][t]["columns"]]
        fc = [c["name"] for c in fresh["tables"][t]["columns"]]
        if cc != fc:
            msgs.append(f"schema of `{t}` drifted: committed {cc} vs "
                        f"tree {fc}")
    cs = [f"{s['function']}@{s['mode']}"
          for s in committed.get("txn_sites", [])]
    fs = [f"{s['function']}@{s['mode']}"
          for s in fresh.get("txn_sites", [])]
    if cs != fs:
        gone = sorted(set(cs) - set(fs))
        new = sorted(set(fs) - set(cs))
        detail = "; ".join(
            p for p in (f"gone: {', '.join(gone)}" if gone else "",
                        f"new: {', '.join(new)}" if new else "")
            if p) or "mode/order changed"
        msgs.append(f"transaction sites drifted ({detail})")
    cm = _machine_edges(committed)
    fm = _machine_edges(fresh)
    if cm != fm:
        msgs.append(f"state machines drifted: committed edges "
                    f"{sorted(cm - fm) + sorted(fm - cm)} changed")
    if not msgs and committed != fresh:
        msgs.append("manifest metadata drifted (witness lines moved?) — "
                    "regenerate with `vmtlint txn`")
    return msgs


def _machine_edges(surface: dict) -> Set[Tuple[str, str, str, str]]:
    out: Set[Tuple[str, str, str, str]] = set()
    for table, cols in surface.get("state_machines", {}).items():
        for col, m in cols.items():
            for tr in m.get("transitions", []):
                out.add((table, col, tr.get("from") or "*", tr["to"]))
    return out


# ---------------------------------------------------------------- sarif
def render_txn_surface_sarif(surface: dict) -> str:
    """SARIF view: one informational result per transaction site (its
    statements as a codeFlow) and one per recovered state machine —
    the same schema the rule findings use."""
    results = []
    for site in surface["txn_sites"]:
        loc = _witness(site["path"], site["line"],
                       f"{site['mode']} scope via {site['factory']}()")
        steps = [loc] + [
            _witness(site["path"], st["line"],
                     f"{st['kind']} on {', '.join(st['tables']) or '-'}")
            for st in site["statements"]]
        results.append({
            "ruleId": "TXN-SURFACE",
            "level": "note",
            "message": {"text": (
                f"transaction site `{site['function']}` "
                f"(mode {site['mode']}, {len(site['statements'])} "
                f"statement group(s))")},
            "locations": [_sarif_loc(loc)],
            "codeFlows": [_sarif_flow(steps)],
        })
    for table in sorted(surface.get("state_machines", {})):
        for col, m in surface["state_machines"][table].items():
            steps = [tr["witness"] for tr in m["transitions"]]
            if not steps:
                continue
            edges = ", ".join(
                f"{tr.get('from') or '*'}→{tr['to']}"
                for tr in m["transitions"])
            results.append({
                "ruleId": "TXN-STATE-MACHINE",
                "level": "note",
                "message": {"text": (
                    f"`{table}.{col}` state machine: {edges}")},
                "locations": [_sarif_loc(steps[0])],
                "codeFlows": [_sarif_flow(steps)],
            })
    doc = {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "vmtlint-txn",
                "informationUri": "",
                "rules": [
                    {"id": "TXN-SURFACE",
                     "shortDescription": {
                         "text": "transaction-site manifest witness"}},
                    {"id": "TXN-STATE-MACHINE",
                     "shortDescription": {
                         "text": "durable-state machine witness"}},
                ],
            }},
            "results": results,
        }],
    }
    return json.dumps(doc, indent=2, sort_keys=True) + "\n"


def _sarif_loc(w: dict) -> dict:
    return {"physicalLocation": {
        "artifactLocation": {"uri": w["path"]},
        "region": {"startLine": max(1, int(w.get("line", 1)))}},
        "message": {"text": w.get("message", "")}}


def _sarif_flow(steps: List[dict]) -> dict:
    return {"threadFlows": [{"locations": [
        {"location": _sarif_loc(s)} for s in steps]}]}
